"""OpenAI-compatible HTTP server for the TPU engine (`pst-engine`).

This is the pod the stack deploys where the reference deploys the
`vllm/vllm-openai` image (`helm/templates/deployment-vllm-multi.yaml:101-118`).
Surface contract (everything the router, stats scraper, operator, and
dashboards depend on — SURVEY.md §1 "Serving engine" row):

- `/v1/models`, `/v1/chat/completions`, `/v1/completions` (SSE streaming),
  `/v1/embeddings`, `/tokenize`, `/detokenize`, `/rerank`, `/v1/rerank`,
  `/score`, `/v1/score`
- `/metrics` with `vllm:`-prefixed gauge names the router's
  `EngineStats.from_vllm_scrape` parses (reference `stats/engine_stats.py:63-76`)
- `/health`, `/is_sleeping`, `/sleep`, `/wake_up` (tutorial 19 drain flow)
- `/v1/load_lora_adapter`, `/v1/unload_lora_adapter` (operator LoRA flow,
  `loraadapter_controller.go:582-611`)
- `/version`

Auth: optional `--api-key` (Bearer) mirroring the chart's vllmApiKey secret.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from typing import List, Optional

import numpy as np
from aiohttp import web
from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
)

from .. import __version__
from ..logging_utils import init_logger
from ..obs import (
    ENGINE_TELEMETRY,
    ENGINE_TELEMETRY_REGISTRY,
    OBS_REGISTRY,
    SpanRecorder,
    bind_log_context,
    configure_logging,
    debug_requests_response,
    render_registries,
    unbind_log_context,
)
from ..obs.metrics import observe_stage
from ..obs.tasks import spawn_owned
from ..resilience.deadline import DEADLINE_EXCEEDED_HEADER, parse_deadline
from ..protocols import (
    ChatCompletionRequest,
    ChatMessage,
    CompletionRequest,
    EmbeddingRequest,
    ErrorResponse,
    random_id,
)
from .async_engine import AsyncLLMEngine
from .config import EngineConfig
from .sequence import SamplingParams

logger = init_logger(__name__)


def _error(message: str, status: int = 400, etype: str = "invalid_request_error",
           headers: Optional[dict] = None):
    return web.json_response(
        ErrorResponse(message=message, type=etype, code=status).model_dump(),
        status=status,
        headers=headers,
    )


def _drain_error():
    # The X-PST-Draining marker lets the router tell a deliberate drain
    # rejection apart from a backend failure: it reconciles its drain state
    # from live traffic (even with health probes off) instead of tripping
    # the circuit breaker.
    return _error("engine is draining", 503, "service_unavailable",
                  headers={"X-PST-Draining": "1"})


def _warming_error():
    # Same contract as the drain marker, for the startup precompile pass:
    # accepting the request would queue it behind the 46-138 s XLA lattice
    # compile (exactly the cold-engine TTFT warmup exists to prevent), so
    # reject with a marker the router reconciles from live traffic — it
    # marks the endpoint warming and fails over without a breaker penalty.
    return _error("engine is warming up (precompiling)", 503,
                  "service_unavailable", headers={"X-PST-Warming": "1"})


def _deadline_error():
    # Instant 504 for work whose router-propagated budget is already gone:
    # cheaper to shed at HTTP admission than to let the scheduler drop it.
    # The marker header tells the router this was a deliberate budget shed,
    # not an engine failure.
    return _error("deadline exceeded", 504, "deadline_exceeded",
                  headers={DEADLINE_EXCEEDED_HEADER: "1"})


class EngineMetrics:
    """Prometheus surface, `vllm:`-named for scraper/dashboard compatibility."""

    def __init__(self, model: str):
        self.registry = CollectorRegistry()
        label = {"model_name": model}

        def gauge(name, doc):
            g = Gauge(name, doc, ["model_name"], registry=self.registry)
            return g.labels(**label)

        def counter(name, doc):
            c = Counter(name, doc, ["model_name"], registry=self.registry)
            return c.labels(**label)

        def hist(name, doc, buckets):
            h = Histogram(
                name, doc, ["model_name"], registry=self.registry, buckets=buckets
            )
            return h.labels(**label)

        self.running = gauge("vllm:num_requests_running", "running requests")
        self.waiting = gauge("vllm:num_requests_waiting", "waiting requests")
        self.swapped = gauge(
            "vllm:num_requests_swapped", "sequences with KV parked host-side"
        )
        self.preemptions = counter(
            "vllm:num_preemptions", "recompute preemptions"
        )
        self.cache_usage = gauge(
            "vllm:gpu_cache_usage_perc", "KV page usage (HBM)"
        )
        self.hit_rate = gauge(
            "vllm:gpu_prefix_cache_hit_rate", "prefix cache hit rate"
        )
        self.hits = gauge(
            "vllm:gpu_prefix_cache_hits_total", "prefix cache hit tokens"
        )
        self.queries = gauge(
            "vllm:gpu_prefix_cache_queries_total", "prefix cache query tokens"
        )
        self.prompt_tokens = counter(
            "vllm:prompt_tokens_total", "prompt tokens processed"
        )
        self.generation_tokens = counter(
            "vllm:generation_tokens_total", "tokens generated"
        )
        self.ttft = hist(
            "vllm:time_to_first_token_seconds",
            "TTFT",
            (0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 6.4),
        )
        self.e2e = hist(
            "vllm:e2e_request_latency_seconds",
            "request latency",
            (0.1, 0.25, 0.5, 1, 2, 4, 8, 16, 32, 64),
        )
        self.success = counter("vllm:request_success_total", "finished requests")
        # Counters (not gauges): the _total suffix promises monotonic
        # counter semantics — rate()/increase() queries and counter-typed
        # dashboards break across restarts otherwise (vLLM exports these as
        # Counters). The engine reports cumulative totals, so refresh()
        # inc()s by delta.
        self.spec_draft = counter(
            "vllm:spec_decode_num_draft_tokens",
            "speculative draft tokens proposed",
        )
        self.spec_accepted = counter(
            "vllm:spec_decode_num_accepted_tokens",
            "speculative draft tokens accepted",
        )
        self.adaptive_deep = counter(
            "pst:adaptive_deep_bursts",
            "decode bursts executed at the adaptive deep depth",
        )
        self.pipelined_bursts = counter(
            "pst:pipelined_bursts",
            "decode bursts dispatched as part of an overlapped pipeline "
            "(one burst in flight, host bookkeeping off the critical path)",
        )
        # Deadline shedding by stage (docs/resilience.md): admission counts
        # at the HTTP layer; queued/running refresh from scheduler stats.
        self.deadline_shed_admission = counter(
            "pst:deadline_shed_admission",
            "requests shed at HTTP admission (budget already expired)",
        )
        self.deadline_shed_queued = counter(
            "pst:deadline_shed_queued",
            "queued sequences shed before consuming a prefill step",
        )
        self.deadline_shed_running = counter(
            "pst:deadline_shed_running",
            "running sequences shed between decode steps",
        )
        self.swap_out = counter(
            "pst:kv_swap_out", "sequences swapped out (KV parked)"
        )
        self.swap_in = counter(
            "pst:kv_swap_in", "sequences swapped back in (KV resumed)"
        )
        self.swap_tail_pages = counter(
            "pst:kv_swap_tail_pages",
            "uncommitted tail pages physically moved by swap",
        )
        self.swap_fallback = counter(
            "pst:kv_swap_fallback_recompute",
            "swap-ins that degraded to recompute (committed pages lost)",
        )
        self.swap_stash = gauge(
            "pst:kv_swap_stash_blocks", "host-DRAM stash occupancy (pages)"
        )
        # Streamed disagg KV handoff (docs/disagg.md): pages shipped to
        # the remote store per prefill chunk, pages staged by the decode
        # side's manifest-following prefetch, and transfers that degraded
        # to the fused path (manifest timeout / kvserver death).
        self.kv_published_blocks = counter(
            "pst:kv_published_blocks",
            "KV pages published to the remote store by the streamed "
            "disagg handoff (per prefill chunk, batched)",
        )
        self.kv_prefetched_blocks = counter(
            "pst:kv_prefetched_blocks",
            "KV pages prefetched from a disagg prefill's manifest while "
            "the prefill was still running",
        )
        self.kv_transfer_fallbacks = counter(
            "pst:kv_transfer_fallbacks",
            "disagg transfers that degraded to the fused path "
            "(manifest timeout or kvserver failure)",
        )
        self.kv_remote_retries = counter(
            "pst:kv_remote_retries",
            "remote-KV GET attempts retried after a transient shard "
            "error (bounded, jittered — docs/kvserver.md)",
        )
        # Tenant QoS (docs/multi-tenancy.md): per-tier queue age is the
        # starvation signal the flood-isolation guarantee asserts on, and
        # batch preemptions count pages reclaimed for interactive work.
        self.tenant_queue_age_interactive = gauge(
            "pst:tenant_queue_age_interactive_seconds",
            "oldest interactive-tier queued sequence's wait (seconds)",
        )
        self.tenant_queue_age_batch = gauge(
            "pst:tenant_queue_age_batch_seconds",
            "oldest batch-tier queued sequence's wait (seconds)",
        )
        self.tenant_batch_preemptions = counter(
            "pst:tenant_batch_preemptions",
            "batch-tier sequences preempted (swap/shed) so a waiting "
            "interactive sequence could admit",
        )
        self._counter_last: dict = {}

    def _counter_to(self, c, key: str, total: float) -> None:
        last = self._counter_last.get(key, 0.0)
        if total > last:
            c.inc(total - last)
            self._counter_last[key] = total
        elif total < last:
            # Engine-side cumulative stat reset in-process: counting
            # restarted from 0, so everything counted since the reset is
            # `total`. Export it and re-baseline, instead of freezing until
            # the total re-exceeds the stale high-water mark.
            if total > 0:
                c.inc(total)
            self._counter_last[key] = total

    def refresh(self, stats: dict) -> None:
        self.running.set(stats["num_requests_running"])
        self.waiting.set(stats["num_requests_waiting"])
        self.swapped.set(
            stats.get("num_requests_swapped", stats["num_preemptions_total"])
        )
        self._counter_to(
            self.preemptions, "preempt", stats["num_preemptions_total"]
        )
        self._counter_to(
            self.swap_out, "swap_out", stats.get("kv_swap_out_total", 0)
        )
        self._counter_to(
            self.swap_in, "swap_in", stats.get("kv_swap_in_total", 0)
        )
        self._counter_to(
            self.swap_tail_pages, "swap_tail",
            stats.get("kv_swap_tail_pages_total", 0),
        )
        self._counter_to(
            self.swap_fallback, "swap_fallback",
            stats.get("kv_swap_fallback_recompute_total", 0),
        )
        self.swap_stash.set(stats.get("kv_swap_stash_blocks", 0))
        self.cache_usage.set(stats["kv_cache_usage_perc"])
        self.hit_rate.set(stats["prefix_cache_hit_rate"])
        self.hits.set(stats["prefix_cache_hits_total"])
        self.queries.set(stats["prefix_cache_queries_total"])
        self._counter_to(
            self.spec_draft, "draft",
            stats.get("spec_decode_num_draft_tokens_total", 0),
        )
        self._counter_to(
            self.spec_accepted, "accepted",
            stats.get("spec_decode_num_accepted_tokens_total", 0),
        )
        self._counter_to(
            self.adaptive_deep, "deep",
            stats.get("adaptive_deep_bursts_total", 0),
        )
        self._counter_to(
            self.pipelined_bursts, "pipelined",
            stats.get("pipelined_bursts_total", 0),
        )
        self._counter_to(
            self.deadline_shed_queued, "dl_queued",
            stats.get("deadline_sheds_queued_total", 0),
        )
        self._counter_to(
            self.deadline_shed_running, "dl_running",
            stats.get("deadline_sheds_running_total", 0),
        )
        self._counter_to(
            self.kv_published_blocks, "kv_pub",
            stats.get("kv_published_blocks_total", 0),
        )
        self._counter_to(
            self.kv_prefetched_blocks, "kv_prefetch",
            stats.get("kv_prefetched_blocks_total", 0),
        )
        self._counter_to(
            self.kv_transfer_fallbacks, "kv_fallback",
            stats.get("kv_transfer_fallbacks_total", 0),
        )
        self._counter_to(
            self.kv_remote_retries, "kv_retry",
            stats.get("kv_remote_retries_total", 0),
        )
        self.tenant_queue_age_interactive.set(
            stats.get("tenant_queue_age_interactive", 0.0)
        )
        self.tenant_queue_age_batch.set(
            stats.get("tenant_queue_age_batch", 0.0)
        )
        self._counter_to(
            self.tenant_batch_preemptions, "tenant_batch_preempt",
            stats.get("tenant_batch_preemptions_total", 0),
        )


def _kv_transfer_params(req) -> Optional[dict]:
    """The request's ``kv_transfer_params`` (the router's disagg handoff
    stamp, pydantic ``extra="allow"``), validated to a request-id-bearing
    dict — anything else is ignored rather than 400d, mirroring the
    reference connector's permissive surface."""
    raw = getattr(req, "kv_transfer_params", None)
    if not isinstance(raw, dict) or not raw.get("request_id"):
        return None
    return {
        "request_id": str(raw["request_id"]),
        "role": str(raw["role"]) if raw.get("role") else None,
    }


def _parse_logit_bias(raw) -> tuple:
    """OpenAI logit_bias keys are stringified token ids; a non-numeric key
    must surface as a 400, not a 500 (callers catch ValueError). Values are
    validated to OpenAI's documented [-100, 100] range — unbounded biases
    can force tokens users only meant to discourage."""
    if not raw:
        return ()
    try:
        parsed = tuple((int(k), float(v)) for k, v in raw.items())
    except (TypeError, ValueError):
        raise ValueError("logit_bias keys must be integer token ids")
    for _, v in parsed:
        if not (-100.0 <= v <= 100.0):
            raise ValueError(
                "logit_bias values must be in [-100, 100]"
            )
    return parsed


def _parse_guided_choice(raw, tok) -> tuple:
    """Tokenize guided_choice strings (no special tokens — the choices are
    output continuations). Invalid shapes 400 via ValueError."""
    if not raw:
        return ()
    if tok is None:
        raise ValueError("guided_choice is not supported on this endpoint")
    if not isinstance(raw, list) or not all(
        isinstance(c, str) and c for c in raw
    ):
        raise ValueError("guided_choice must be a list of non-empty strings")
    if len(raw) > 64:
        raise ValueError("guided_choice supports at most 64 choices")
    choices = []
    for c in raw:
        ids = tuple(tok.encode(c, add_special_tokens=False))
        if not ids or len(ids) > 256:
            raise ValueError(
                f"guided_choice entry tokenizes to {len(ids)} tokens "
                "(must be 1..256)"
            )
        choices.append(ids)
    return tuple(choices)


def build_sampling(
    req, max_model_len: int, prompt_len: int, tok=None
) -> SamplingParams:
    limit = max(max_model_len - prompt_len - 1, 1)
    want = req.max_completion_tokens or req.max_tokens
    # OpenAI shapes: completions carry an int `logprobs` (top-N count);
    # chat carries bool `logprobs` + int `top_logprobs` (0 is valid: chosen
    # token's logprob only, no alternatives).
    lp = getattr(req, "logprobs", None)
    if isinstance(lp, bool):
        if lp:
            top = getattr(req, "top_logprobs", None)
            lp = int(top) if top is not None else 0
        else:
            lp = None
    gc = _parse_guided_choice(getattr(req, "guided_choice", None), tok)
    return SamplingParams(
        max_tokens=min(want, limit) if want else limit,
        temperature=req.temperature,
        top_p=req.top_p,
        top_k=req.top_k,
        min_p=req.min_p,
        stop=req.stop,
        stop_token_ids=tuple(req.stop_token_ids or ()),
        # Guided requests terminate via EOS at a completed choice (the
        # prefix-choice escape hatch) — ignore_eos would deadlock the mask.
        ignore_eos=req.ignore_eos and not gc,
        seed=req.seed,
        presence_penalty=req.presence_penalty,
        frequency_penalty=req.frequency_penalty,
        repetition_penalty=req.repetition_penalty,
        logprobs=int(lp) if lp is not None else None,
        logit_bias=_parse_logit_bias(getattr(req, "logit_bias", None)),
        guided_choice=gc,
    )


def _fmt_completion_logprobs(tok, entries, echo_ids=None, base_offset=0):
    """OpenAI completions `logprobs` object. Echoed prompt tokens carry null
    logprobs (the engine does not keep prefill logits; same shape as the
    API's null-first-token convention). ``base_offset`` anchors text_offset
    into the FULL accumulated completion text for streaming chunks."""
    tokens, token_lps, top_lps, offsets = [], [], [], []
    off = base_offset
    for tid in echo_ids or []:
        s = tok.decode([tid])
        tokens.append(s)
        token_lps.append(None)
        top_lps.append(None)
        offsets.append(off)
        off += len(s)
    for e in entries:
        s = tok.decode([e["token_id"]])
        tokens.append(s)
        token_lps.append(e["logprob"])
        top_lps.append({tok.decode([t]): lp for t, lp in e["top"]})
        offsets.append(off)
        off += len(s)
    return {
        "tokens": tokens,
        "token_logprobs": token_lps,
        "top_logprobs": top_lps,
        "text_offset": offsets,
    }


def _fmt_chat_logprobs(tok, entries):
    """OpenAI chat `logprobs.content` entries."""
    def one(tid, lp):
        s = tok.decode([tid])
        return {"token": s, "logprob": lp, "bytes": list(s.encode())}

    return {
        "content": [
            dict(
                one(e["token_id"], e["logprob"]),
                top_logprobs=[one(t, lp) for t, lp in e["top"]],
            )
            for e in entries
        ]
    }


def create_engine_app(
    engine: AsyncLLMEngine,
    api_key: Optional[str] = None,
    cross_encoder=None,
    tracing: bool = True,
    debug_requests_buffer: int = 256,
    profiling: bool = False,
    profile_dir: str = "/tmp/pst_profiles",
) -> web.Application:
    # Everything except unauthenticated probe/scrape endpoints is guarded
    # when --api-key is set (/sleep in particular is destructive). Enforced
    # as a middleware so no handler can be forgotten.
    # /debug/requests is deliberately NOT open: timelines carry
    # per-request metadata (request ids, backend URLs, error strings) —
    # when an api key is configured it is guarded like the work endpoints.
    _OPEN_PATHS = {
        "/health", "/ready", "/metrics", "/version", "/is_sleeping",
        "/is_draining",
    }

    # Paths that get a root span + timeline entry (the work the router
    # proxies; admin/probe endpoints are not traced).
    _TRACED_PATHS = {
        "/v1/chat/completions", "/v1/completions", "/v1/embeddings",
        "/rerank", "/v1/rerank", "/v2/rerank", "/score", "/v1/score",
    }

    recorder = SpanRecorder(
        "engine", buffer=debug_requests_buffer, enabled=tracing
    )

    @web.middleware
    async def tracing_middleware(request: web.Request, handler):
        """Root span per generation request, joining the router's trace via
        the propagated W3C ``traceparent``; ``X-Request-Id`` (the router's
        id, or a fresh one) lands on every unprepared response —
        including 503 drain and 504 deadline sheds."""
        if not (
            recorder.enabled
            and request.method == "POST"
            and request.path in _TRACED_PATHS
        ):
            return await handler(request)
        request_id = request.headers.get("X-Request-Id") or random_id("req")
        trace = recorder.trace(
            request_id,
            headers=request.headers,
            name="engine_request",
            attributes={"http.target": request.path},
        )
        request["trace"] = trace
        request["request_id"] = request_id
        # Structured-log correlation: engine log lines under this request
        # carry the SAME trace id the router's lines do (the propagated
        # traceparent joined the trace above), plus the router-stamped
        # tenant — one grep spans the whole hop chain.
        log_token = bind_log_context(
            request_id=request_id,
            trace_id=trace.trace_id,
            tenant=request.headers.get("X-PST-Tenant"),
        )
        status: Optional[int] = None
        try:
            response = await handler(request)
            status = response.status
            if not response.prepared:
                response.headers.setdefault("X-Request-Id", request_id)
            return response
        finally:
            unbind_log_context(log_token)
            trace.finish(status=status)

    @web.middleware
    async def auth_middleware(request: web.Request, handler):
        if api_key is not None and request.path not in _OPEN_PATHS:
            auth = request.headers.get("Authorization", "")
            if auth != f"Bearer {api_key}":
                return _error("invalid API key", 401, "authentication_error")
        return await handler(request)

    app = web.Application(middlewares=[tracing_middleware, auth_middleware])
    model_name = engine.engine.model_name
    metrics = EngineMetrics(model_name)
    app["engine"] = engine
    app["metrics"] = metrics
    app["span_recorder"] = recorder

    def _record_engine_stages(
        request: web.Request,
        queue_time: Optional[float],
        prefill_time: Optional[float],
        decode_time: Optional[float],
    ) -> None:
        """Replay the Sequence's TTFT decomposition as spans: queue wait →
        prefill → decode, laid back-to-back ending now. Post-hoc so the
        step thread never touches the recorder."""
        trace = request.get("trace")
        if trace is None:
            return
        now = time.monotonic()
        end_prefill = now - (decode_time or 0.0)
        end_queue = end_prefill - (prefill_time or 0.0)
        if queue_time is not None:
            trace.record_span("engine_queue", queue_time, end_mono=end_queue)
        if prefill_time is not None:
            trace.record_span("prefill", prefill_time, end_mono=end_prefill)
        if decode_time is not None:
            trace.record_span("decode", decode_time, end_mono=now)

    def _attach_compile_events(request: web.Request, events) -> None:
        """Surface the XLA compiles a step absorbed as `compile` span
        events on the victim request's trace: the BENCH_r05 120 s p99 was
        a mid-run recompile no timeline could attribute."""
        trace = request.get("trace")
        if trace is None or not events:
            return
        for ev in events:
            trace.add_event("compile", **ev)

    def _lora_names() -> List[str]:
        mgr = engine.engine.lora_manager
        return [a.name for a in mgr.list_adapters()] if mgr else []

    def _resolve_lora(requested_model: str) -> Optional[str]:
        """Request model == a loaded adapter name → serve under that LoRA."""
        if requested_model and requested_model != model_name:
            mgr = engine.engine.lora_manager
            if mgr is not None and mgr.get(requested_model) is not None:
                return requested_model
        return None

    def _request_deadline(request: web.Request):
        """``(error_response, deadline)``: parse the router-propagated
        ``X-PST-Deadline-Ms`` budget. Already expired → instant 504 (the
        cheapest shed point — no tokenization, no scheduler admission);
        otherwise the monotonic expiry to carry on the Sequence so the
        scheduler can shed it if the budget dies while queued/running."""
        if not engine.engine.cfg.deadline_shedding:
            return None, None
        d = parse_deadline(request.headers)
        if d is None:
            return None, None
        if d.expired():
            metrics.deadline_shed_admission.inc()
            trace = request.get("trace")
            if trace is not None:
                trace.add_event("deadline_shed", stage="engine_admission")
            return _deadline_error(), None
        return None, d.expires_at

    def _request_tenant(request: web.Request):
        """``(tenant, tenant_class)`` from the router-stamped headers
        (docs/multi-tenancy.md). The router overwrites client-sent values
        at admission, so within a deployed stack these are trusted; an
        engine reached directly treats the caller as the default
        interactive tenant unless it self-declares."""
        if not engine.engine.cfg.tenant_fairness:
            return None, None
        tenant = request.headers.get("X-PST-Tenant")
        tier = request.headers.get("X-PST-Tenant-Class")
        return tenant, tier

    # -- model listing -------------------------------------------------

    async def list_models(request: web.Request) -> web.Response:
        now = int(time.time())
        data = [
            {"id": model_name, "object": "model", "created": now,
             "owned_by": "production-stack-tpu", "root": None, "parent": None}
        ] + [
            {"id": a, "object": "model", "created": now,
             "owned_by": "production-stack-tpu", "root": None, "parent": model_name}
            for a in _lora_names()
        ]
        return web.json_response({"object": "list", "data": data})

    # -- generation ----------------------------------------------------

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        try:
            req = ChatCompletionRequest(**await request.json())
        except Exception as e:  # noqa: BLE001
            return _error(f"invalid request body: {e}")
        if engine.sleeping:
            return _error("engine is sleeping", 503, "service_unavailable")
        if engine.draining:
            return _drain_error()
        if engine.warming:
            return _warming_error()
        # continue_final_message (vLLM parity, pydantic extra="allow"):
        # render the final message's turn OPEN so generation continues it
        # instead of starting a fresh assistant turn — what the router's
        # stream-resume continuation requests rely on.
        cfm = bool(getattr(req, "continue_final_message", False))
        prompt = engine.engine.tokenizer.apply_chat_template(
            req.messages, add_generation_prompt=not cfm,
            continue_final_message=cfm,
        )
        return await _serve_generation(request, req, prompt, is_chat=True)

    async def completions(request: web.Request) -> web.StreamResponse:
        try:
            req = CompletionRequest(**await request.json())
        except Exception as e:  # noqa: BLE001
            return _error(f"invalid request body: {e}")
        if engine.sleeping:
            return _error("engine is sleeping", 503, "service_unavailable")
        if engine.draining:
            return _drain_error()
        if engine.warming:
            return _warming_error()
        prompt = req.prompt
        # Normalize the four OpenAI prompt forms: str, [str, ...],
        # [int, ...] (one tokenized prompt), [[int, ...], ...] (a batch).
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            prompt = [prompt]
        prompts = prompt if isinstance(prompt, list) else [prompt]
        if not prompts:
            return _error("prompt must not be empty")
        if len(prompts) == 1:
            p = prompts[0]
            if isinstance(p, list):
                return await _serve_generation(
                    request, req, None, is_chat=False, prompt_ids=p
                )
            return await _serve_generation(request, req, str(p), is_chat=False)
        if req.stream:
            return _error("streaming is not supported for batched prompts")
        if (req.n or 1) > 1 or (req.best_of or 1) > 1:
            # Explicit rejection beats silently returning one unranked
            # sample per prompt.
            return _error("n/best_of > 1 is not supported for batched prompts")
        return await _serve_completion_batch(request, req, prompts)

    async def _serve_completion_batch(
        request: web.Request, req, prompts: List
    ) -> web.Response:
        """OpenAI batched completions: one choice per prompt, index-aligned."""
        tok = engine.engine.tokenizer
        max_len = engine.engine.cfg.max_model_len
        err, deadline = _request_deadline(request)
        if err is not None:
            return err
        created = int(time.time())
        rid = random_id("cmpl")
        start = time.time()
        tenant, tenant_class = _request_tenant(request)

        async def one(prompt) -> dict:
            if isinstance(prompt, list):
                try:
                    ids = [int(x) for x in prompt]
                except (TypeError, ValueError):
                    return {"error": "prompt token ids must be integers", "ids": []}
            else:
                ids = tok.encode(str(prompt))
            if len(ids) >= max_len:
                return {"error": f"prompt has {len(ids)} tokens (max {max_len})",
                        "ids": ids}
            try:
                sampling = build_sampling(req, max_len, len(ids), tok)
            except ValueError as e:
                return {"error": str(e), "ids": ids}
            parts, n_out, finish = [], 0, None
            async for out in engine.generate(
                prompt_token_ids=ids, sampling=sampling, deadline=deadline,
                tenant=tenant, tenant_class=tenant_class,
            ):
                parts.append(out.text_delta)
                n_out = out.num_output_tokens
                finish = out.finish_reason or finish
                if out.num_output_tokens == 1 and out.ttft is not None:
                    metrics.ttft.observe(out.ttft)
            return {"text": "".join(parts), "n_in": len(ids), "n_out": n_out,
                    "finish": finish}

        results = await asyncio.gather(*(one(p) for p in prompts))
        if any("error" in r for r in results):
            return _error(next(r["error"] for r in results if "error" in r))
        if any(r.get("finish") == "deadline" for r in results):
            # The budget died while part of the batch was still queued or
            # decoding: the batch cannot complete within its deadline.
            return _deadline_error()
        usage = {
            "prompt_tokens": sum(r["n_in"] for r in results),
            "completion_tokens": sum(r["n_out"] for r in results),
            "total_tokens": sum(r["n_in"] + r["n_out"] for r in results),
        }
        metrics.e2e.observe(time.time() - start)
        metrics.success.inc()
        metrics.prompt_tokens.inc(usage["prompt_tokens"])
        metrics.generation_tokens.inc(usage["completion_tokens"])
        return web.json_response(
            {
                "id": rid, "object": "text_completion", "created": created,
                "model": req.model,
                "choices": [
                    {"index": i, "text": r["text"], "logprobs": None,
                     "finish_reason": r["finish"]}
                    for i, r in enumerate(results)
                ],
                "usage": usage,
            },
            headers={"X-Request-Id": rid},
        )

    async def _serve_generation(
        request: web.Request,
        req,
        prompt: Optional[str],
        is_chat: bool,
        prompt_ids: Optional[List[int]] = None,
    ) -> web.StreamResponse:
        t_admission = time.monotonic()
        tok = engine.engine.tokenizer
        if prompt_ids is not None:
            try:  # malformed ids must 400 here, not poison the step thread
                ids = [int(x) for x in prompt_ids]
            except (TypeError, ValueError):
                return _error("prompt token ids must be integers")
        else:
            ids = tok.encode(prompt or "")
        max_len = engine.engine.cfg.max_model_len
        if len(ids) >= max_len:
            return _error(
                f"prompt has {len(ids)} tokens, exceeds max_model_len={max_len}"
            )
        if not engine.engine.scheduler.prompt_fits(len(ids)):
            # Scheduler.add's feasibility guard at the HTTP layer (shared
            # helper) so the client sees a 400, not an engine-thread error.
            return _error(
                f"prompt of {len(ids)} tokens needs more KV pages than the "
                f"engine has ({engine.engine.allocator.num_blocks})"
            )
        try:
            sampling = build_sampling(req, max_len, len(ids), tok)
        except ValueError as e:
            return _error(str(e))
        err, deadline = _request_deadline(request)
        if err is not None:
            return err
        trace = request.get("trace")
        if trace is not None:
            # Tokenization + validation + budget parse = engine admission.
            trace.record_span(
                "engine_admission", time.monotonic() - t_admission,
                attributes={"prompt_tokens": len(ids)},
            )
        rid = random_id("chatcmpl" if is_chat else "cmpl")
        created = int(time.time())
        start = time.time()
        obj = "chat.completion.chunk" if is_chat else "text_completion"
        n_choices = max(int(getattr(req, "n", 1) or 1), 1)
        # best_of is a completions-only OpenAI field; on chat it would be
        # an unvalidated extra (pydantic extra=\"allow\") — ignore it there
        # like every other unknown field.
        best_of = n_choices if is_chat else int(req.best_of or n_choices)
        if best_of < n_choices:
            return _error("best_of must be >= n")
        # best_of caps at 20 (OpenAI parity); n caps at 128 (OpenAI's own n
        # ceiling) — both double as this server's per-request fan-out bound.
        if best_of > 20 and best_of > n_choices:
            return _error("best_of must be <= 20")
        if n_choices > 128 or best_of > 128:
            return _error("n must be <= 128")
        echo = bool(getattr(req, "echo", False)) and not is_chat
        want_lp = sampling.logprobs is not None
        lora = _resolve_lora(getattr(req, "model", ""))

        if n_choices > 1 or best_of > 1:
            if req.stream:
                return _error("streaming with n/best_of > 1 is not supported")
            return await _serve_n_choices(
                request, req, ids, sampling, rid, created, is_chat, n_choices,
                echo, lora, best_of, deadline=deadline,
            )

        tenant, tenant_class = _request_tenant(request)
        kv_transfer = _kv_transfer_params(req)
        if kv_transfer is not None:
            # Consumer leg of a disagg handoff (docs/disagg.md): follow the
            # prefill's manifest and stage published pages in the host pool
            # WHILE the remote prefill still runs; admission proceeds when
            # the completion marker lands — the prompt is then a host-tier
            # prefix hit and the first decode step dispatches immediately.
            # Timeout / dead kvserver → plain admission (fused fallback:
            # this engine recomputes the prefill; no client-visible error).
            prefetcher = engine.engine.kv_prefetcher
            if prefetcher is not None and kv_transfer.get("role") == "consumer":
                t_fetch = time.monotonic()
                fetch = await asyncio.get_running_loop().run_in_executor(
                    None,
                    lambda: prefetcher.prefetch(
                        str(kv_transfer["request_id"]), deadline=deadline
                    ),
                )
                if trace is not None:
                    trace.add_event(
                        "kv_prefetch",
                        complete=fetch["complete"], blocks=fetch["blocks"],
                    )
                observe_stage(
                    "engine", "kv_prefetch", time.monotonic() - t_fetch
                )
        gen = engine.generate(
            prompt_token_ids=ids, sampling=sampling, request_id=rid,
            lora_name=lora, deadline=deadline,
            tenant=tenant, tenant_class=tenant_class,
            kv_transfer=kv_transfer,
        )

        if req.stream:
            resp = web.StreamResponse(status=200)
            resp.headers["Content-Type"] = "text/event-stream"
            resp.headers["Cache-Control"] = "no-cache"
            resp.headers["X-Request-Id"] = rid
            await resp.prepare(request)
            n_out = 0
            last_out = None
            try:
                if is_chat:
                    first = {
                        "id": rid, "object": obj, "created": created,
                        "model": req.model,
                        "choices": [{"index": 0, "delta": {"role": "assistant"},
                                     "finish_reason": None}],
                    }
                    await resp.write(f"data: {json.dumps(first)}\n\n".encode())
                first_chunk = True
                # Running char offset into the accumulated completion text
                # (echo prefix included) so streamed text_offset entries
                # stay globally consistent, not chunk-relative.
                char_off = len(engine.engine.tokenizer.decode(ids)) if echo else 0
                async for out in gen:
                    n_out = out.num_output_tokens
                    last_out = out
                    if out.compile_events:
                        _attach_compile_events(request, out.compile_events)
                    if out.num_output_tokens == 1 and out.ttft is not None:
                        metrics.ttft.observe(out.ttft)
                    lp_obj = None
                    if want_lp and out.logprobs:
                        if is_chat:
                            lp_obj = _fmt_chat_logprobs(
                                engine.engine.tokenizer, out.logprobs
                            )
                        else:
                            lp_obj = _fmt_completion_logprobs(
                                engine.engine.tokenizer, out.logprobs,
                                base_offset=char_off,
                            )
                    if is_chat:
                        delta = {"content": out.text_delta} if out.text_delta else {}
                        choice = {"index": 0, "delta": delta,
                                  "logprobs": lp_obj,
                                  "finish_reason": out.finish_reason}
                    else:
                        text = out.text_delta
                        if echo and first_chunk:
                            text = engine.engine.tokenizer.decode(ids) + text
                        choice = {"index": 0, "text": text,
                                  "logprobs": lp_obj,
                                  "finish_reason": out.finish_reason}
                    char_off += len(out.text_delta)
                    first_chunk = False
                    chunk = {"id": rid, "object": obj, "created": created,
                             "model": req.model, "choices": [choice]}
                    if out.finished and getattr(req, "stream_options", None) and (
                        req.stream_options or {}
                    ).get("include_usage"):
                        chunk["usage"] = {
                            "prompt_tokens": len(ids),
                            "completion_tokens": n_out,
                            "total_tokens": len(ids) + n_out,
                        }
                        # Streams learn their cost only at the end — the
                        # 200 headers are long gone, so the usage chunk
                        # is the streaming cost surface.
                        if out.cost is not None:
                            chunk["usage"]["pst_cost"] = out.cost
                    await resp.write(f"data: {json.dumps(chunk)}\n\n".encode())
                await resp.write(b"data: [DONE]\n\n")
            except (ConnectionResetError, asyncio.CancelledError):
                await engine.abort(rid)
                raise
            except ValueError as e:
                # Rejected on the engine thread (add-time validation not
                # mirrored by an HTTP precheck). The 200 headers are gone —
                # emit an OpenAI-style error event, then terminate. Abort
                # in case the failure happened mid-stream (the sequence
                # must not keep decoding for a dead client).
                await engine.abort(rid)
                # Stable machine-readable code: an in-band error frame is
                # an engine-*reported* failure (deliberate), which the
                # router's stream journal must never resume — unlike a
                # transport death, which it may.
                err = {"error": {"message": str(e),
                                 "type": "invalid_request_error",
                                 "code": "engine_rejected"}}
                await resp.write(f"data: {json.dumps(err)}\n\n".encode())
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
                return resp
            if last_out is not None:
                _record_engine_stages(
                    request, last_out.queue_time, last_out.prefill_time,
                    last_out.decode_time,
                )
            metrics.e2e.observe(time.time() - start)
            metrics.success.inc()
            metrics.prompt_tokens.inc(len(ids))
            metrics.generation_tokens.inc(n_out)
            await resp.write_eof()
            return resp

        # Non-streaming: accumulate.
        try:
            result = await _collect(gen)
        except asyncio.CancelledError:
            await engine.abort(rid)
            raise
        except ValueError as e:  # engine-thread rejection → HTTP 400
            await engine.abort(rid)
            return _error(str(e))
        if result["finish_reason"] == "deadline":
            # Shed by the scheduler (queued past its budget, or expired
            # mid-decode): nothing useful to return — 504, tagged.
            if trace is not None:
                trace.add_event("deadline_shed", stage="engine_scheduler")
            return _deadline_error()
        _record_engine_stages(
            request, result["queue_time"], result["prefill_time"],
            result["decode_time"],
        )
        _attach_compile_events(request, result.get("compile_events"))
        usage = {
            "prompt_tokens": len(ids),
            "completion_tokens": len(result["token_ids"]),
            "total_tokens": len(ids) + len(result["token_ids"]),
        }
        headers = {"X-Request-Id": rid}
        cost = result.get("cost")
        if cost is not None:
            # Cost attribution (docs/observability.md "Cost attribution"):
            # the request's device-seconds ride the response both as a
            # header (proxied through the router untouched) and as a usage
            # extension, so billing pipelines can consume either.
            usage["pst_cost"] = cost
            headers["X-PST-Cost"] = json.dumps(cost, separators=(",", ":"))
        metrics.e2e.observe(time.time() - start)
        metrics.success.inc()
        metrics.prompt_tokens.inc(len(ids))
        metrics.generation_tokens.inc(len(result["token_ids"]))
        choice = _build_choice(req, result, 0, is_chat, echo, ids)
        payload = {
            "id": rid,
            "object": "chat.completion" if is_chat else "text_completion",
            "created": created, "model": req.model,
            "choices": [choice], "usage": usage,
        }
        return web.json_response(payload, headers=headers)

    async def _collect(gen) -> dict:
        """Drain one generation stream into text/tokens/logprobs/finish
        (plus the Sequence's stage timings for span reconstruction)."""
        text_parts: List[str] = []
        token_ids: List[int] = []
        lp_entries: List[dict] = []
        compile_events: List[dict] = []
        finish_reason = None
        cost = None
        queue_time = prefill_time = decode_time = None
        async for out in gen:
            if out.num_output_tokens == 1 and out.ttft is not None:
                metrics.ttft.observe(out.ttft)
            text_parts.append(out.text_delta)
            token_ids.extend(out.new_token_ids)
            if out.logprobs:
                lp_entries.extend(out.logprobs)
            if out.compile_events:
                compile_events.extend(out.compile_events)
            finish_reason = out.finish_reason or finish_reason
            cost = out.cost if out.cost is not None else cost
            queue_time = out.queue_time if out.queue_time is not None else queue_time
            prefill_time = (
                out.prefill_time if out.prefill_time is not None else prefill_time
            )
            decode_time = (
                out.decode_time if out.decode_time is not None else decode_time
            )
        return {
            "text": "".join(text_parts), "token_ids": token_ids,
            "logprobs": lp_entries, "finish_reason": finish_reason,
            "queue_time": queue_time, "prefill_time": prefill_time,
            "decode_time": decode_time, "compile_events": compile_events,
            "cost": cost,
        }

    def _build_choice(req, result, index, is_chat, echo, prompt_ids) -> dict:
        tok = engine.engine.tokenizer
        lp_obj = None
        if result["logprobs"]:
            if is_chat:
                lp_obj = _fmt_chat_logprobs(tok, result["logprobs"])
            else:
                lp_obj = _fmt_completion_logprobs(
                    tok, result["logprobs"],
                    echo_ids=prompt_ids if echo else None,
                )
        if is_chat:
            return {
                "index": index,
                "message": {"role": "assistant", "content": result["text"]},
                "logprobs": lp_obj,
                "finish_reason": result["finish_reason"],
            }
        text = result["text"]
        if echo:
            text = tok.decode(prompt_ids) + text
        return {"index": index, "text": text, "logprobs": lp_obj,
                "finish_reason": result["finish_reason"]}

    async def _serve_n_choices(
        request, req, ids, sampling, rid, created, is_chat, n_choices, echo,
        lora, best_of=None, deadline=None,
    ) -> web.Response:
        """OpenAI `n` / `best_of`: sample ``best_of`` independent candidates
        of one prompt (the prompt prefix is KV-shared across them via the
        prefix cache); when ``best_of > n``, keep the n candidates with the
        highest mean token logprob (which forces logprobs on internally)."""
        import dataclasses as _dc

        start = time.time()
        n_sample = best_of or n_choices
        rank = n_sample > n_choices

        # Ranking needs per-token logprobs even when the client did not ask
        # for them in the response.
        lp_setting = (
            0 if rank and sampling.logprobs is None else sampling.logprobs
        )

        tenant, tenant_class = _request_tenant(request)

        async def one(i: int) -> dict:
            sp = _dc.replace(
                sampling,
                seed=(sampling.seed + i) if sampling.seed is not None else None,
                logprobs=lp_setting,
            )
            return await _collect(engine.generate(
                prompt_token_ids=ids, sampling=sp, request_id=f"{rid}-{i}",
                lora_name=lora, deadline=deadline,
                tenant=tenant, tenant_class=tenant_class,
            ))

        try:
            results = list(
                await asyncio.gather(*(one(i) for i in range(n_sample)))
            )
        except ValueError as e:
            # One candidate rejected on the engine thread: abort ALL
            # candidates (gather returned on the first failure — siblings
            # are still decoding for a request the client sees fail).
            for i in range(n_sample):
                await engine.abort(f"{rid}-{i}")
            return _error(str(e))
        if any(r["finish_reason"] == "deadline" for r in results):
            return _deadline_error()
        # Stage decomposition from the first candidate (all candidates
        # share admission and the KV-shared prompt prefill; recording one
        # keeps engine_queue/prefill/decode counts 1:1 with requests).
        _record_engine_stages(
            request, results[0]["queue_time"], results[0]["prefill_time"],
            results[0]["decode_time"],
        )
        _attach_compile_events(request, results[0].get("compile_events"))
        # OpenAI bills EVERY best_of candidate in completion_tokens.
        sampled_tokens = sum(len(r["token_ids"]) for r in results)
        if rank:
            def mean_lp(r):
                lps = [e["logprob"] for e in r["logprobs"]]
                return sum(lps) / max(len(lps), 1)

            results.sort(key=mean_lp, reverse=True)
            results = results[:n_choices]
            if sampling.logprobs is None:  # client didn't ask: strip
                for r in results:
                    r["logprobs"] = []
        usage = {
            "prompt_tokens": len(ids),
            "completion_tokens": sampled_tokens,
            "total_tokens": len(ids) + sampled_tokens,
        }
        metrics.e2e.observe(time.time() - start)
        metrics.success.inc()
        metrics.prompt_tokens.inc(len(ids))
        metrics.generation_tokens.inc(sampled_tokens)
        payload = {
            "id": rid,
            "object": "chat.completion" if is_chat else "text_completion",
            "created": created, "model": req.model,
            "choices": [
                _build_choice(req, r, i, is_chat, echo, ids)
                for i, r in enumerate(results)
            ],
            "usage": usage,
        }
        return web.json_response(payload, headers={"X-Request-Id": rid})

    # -- embeddings / rerank / score ----------------------------------

    async def embeddings(request: web.Request) -> web.Response:
        try:
            req = EmbeddingRequest(**await request.json())
        except Exception as e:  # noqa: BLE001
            return _error(f"invalid request body: {e}")
        if engine.draining:
            # Same admission gate as the generation endpoints: encode work
            # accepted after /drain would race the preStop SIGTERM.
            return _drain_error()
        if engine.warming:
            return _warming_error()
        err, _ = _request_deadline(request)
        if err is not None:
            return err
        tok = engine.engine.tokenizer
        inputs = req.input if isinstance(req.input, list) else [req.input]
        if inputs and isinstance(inputs[0], int):
            inputs = [inputs]  # single token-id list
        data = []
        total_tokens = 0
        for i, item in enumerate(inputs):
            ids = item if isinstance(item, list) else tok.encode(str(item))
            total_tokens += len(ids)
            vec = await asyncio.get_event_loop().run_in_executor(
                None, engine.engine.runner.encode, ids
            )
            data.append(
                {"object": "embedding", "index": i, "embedding": vec.tolist()}
            )
        return web.json_response(
            {
                "object": "list", "data": data, "model": req.model,
                "usage": {"prompt_tokens": total_tokens,
                          "total_tokens": total_tokens},
            }
        )

    async def _similarity(texts_a: List[str], texts_b: List[str]) -> List[float]:
        loop = asyncio.get_event_loop()
        tok = engine.engine.tokenizer

        async def emb(t: str):
            return await loop.run_in_executor(
                None, engine.engine.runner.encode, tok.encode(t)
            )

        scores = []
        for a, b in zip(texts_a, texts_b):
            va, vb = await emb(a), await emb(b)
            scores.append(float(np.dot(va, vb)))
        return scores

    # Scoring method surfaced in rerank/score responses. With a
    # --scoring-model loaded (bge-reranker-style checkpoint), (query, doc)
    # pairs are scored JOINTLY by the cross-encoder's classification head —
    # real reranking. Without one, relevance falls back to embedding cosine
    # similarity from the decoder's own hidden states; the explicit label
    # keeps clients from mistaking the approximation for the real thing.
    _SCORING_METHOD = (
        "cross_encoder" if cross_encoder else "embedding_cosine_similarity"
    )

    async def _pair_scores(
        texts_a: List[str], texts_b: List[str]
    ) -> List[float]:
        if cross_encoder is not None:
            return await asyncio.get_event_loop().run_in_executor(
                None, cross_encoder.score_pairs, list(zip(texts_a, texts_b))
            )
        return await _similarity(texts_a, texts_b)

    async def rerank(request: web.Request) -> web.Response:
        if engine.draining:
            return _drain_error()
        if engine.warming:
            return _warming_error()
        err, _ = _request_deadline(request)
        if err is not None:
            return err
        body = await request.json()
        query = body.get("query", "")
        docs = body.get("documents", [])
        top_n = body.get("top_n") or len(docs)
        scores = await _pair_scores([query] * len(docs), docs)
        order = sorted(range(len(docs)), key=lambda i: -scores[i])[:top_n]
        return web.json_response(
            {
                "id": random_id("rerank"),
                "model": body.get("model", model_name),
                "scoring_method": _SCORING_METHOD,
                "results": [
                    {"index": i, "document": {"text": docs[i]},
                     "relevance_score": scores[i]}
                    for i in order
                ],
            }
        )

    async def score(request: web.Request) -> web.Response:
        if engine.draining:
            return _drain_error()
        if engine.warming:
            return _warming_error()
        err, _ = _request_deadline(request)
        if err is not None:
            return err
        body = await request.json()
        t1 = body.get("text_1", "")
        t2 = body.get("text_2", "")
        l1 = t1 if isinstance(t1, list) else [t1]
        l2 = t2 if isinstance(t2, list) else [t2]
        if len(l1) == 1 and len(l2) > 1:
            l1 = l1 * len(l2)
        scores = await _pair_scores(l1, l2)
        return web.json_response(
            {
                "id": random_id("score"),
                "object": "list",
                "model": body.get("model", model_name),
                "scoring_method": _SCORING_METHOD,
                "data": [
                    {"index": i, "object": "score", "score": s}
                    for i, s in enumerate(scores)
                ],
                "usage": {},
            }
        )

    # -- tokenize ------------------------------------------------------

    async def tokenize(request: web.Request) -> web.Response:
        body = await request.json()
        tok = engine.engine.tokenizer
        if body.get("messages"):
            msgs = [ChatMessage(**m) for m in body["messages"]]
            text = tok.apply_chat_template(msgs)
        else:
            text = body.get("prompt") or ""
        ids = tok.encode(text, add_special_tokens=body.get("add_special_tokens", True))
        return web.json_response(
            {"tokens": ids, "count": len(ids),
             "max_model_len": engine.engine.cfg.max_model_len}
        )

    async def detokenize(request: web.Request) -> web.Response:
        body = await request.json()
        text = engine.engine.tokenizer.decode(body.get("tokens", []))
        return web.json_response({"prompt": text})

    # -- admin / health ------------------------------------------------

    async def health(request: web.Request) -> web.Response:
        if engine.is_healthy():
            # Draining and warming are still healthy (liveness: the pod
            # must not be restarted mid-drain or mid-precompile) — the
            # status string tells K8s dashboards and humans apart from a
            # routable engine.
            status = (
                "draining" if engine.draining
                else "warming" if engine.warming
                else "ok"
            )
            return web.json_response({"status": status})
        return web.json_response(
            {"status": "unhealthy", "error": engine.step_error}, status=503
        )

    async def ready(request: web.Request) -> web.Response:
        """Readiness (the K8s readinessProbe target and router discovery's
        warming probe): 200 only once the startup precompile pass has
        finished and the engine accepts work. Distinct from /health —
        a warming engine is alive but must receive no traffic, or its
        first requests absorb XLA compiles (the BENCH_r05 120 s p99)."""
        warmup = dict(engine.engine.warmup_summary or {})
        warmup["mode"] = engine.engine.cfg.warmup
        if engine.warmup_error:
            warmup["error"] = engine.warmup_error
        if engine.ready:
            return web.json_response({"ready": True, "warmup": warmup})
        # Reason mirrors AsyncLLMEngine.ready's conjuncts, in severity
        # order.
        reason = (
            "unhealthy" if not engine.is_healthy()
            else "warming" if engine.warming
            else "sleeping" if engine.sleeping
            else "draining"
        )
        return web.json_response(
            {"ready": False, "reason": reason, "warmup": warmup}, status=503
        )

    async def metrics_endpoint(request: web.Request) -> web.Response:
        stats = engine.engine.stats()
        metrics.refresh(stats)
        # KV occupancy / high watermark + preemption/swap counters for the
        # pst_engine_* surface refresh from the same stats snapshot.
        ENGINE_TELEMETRY.refresh_from_stats(stats)
        # pst_stage_duration_seconds lives in the shared observability
        # registry and pst_engine_* in the engine-telemetry registry
        # (docs/observability.md) — append both to the engine's own. A
        # scraper negotiating OpenMetrics gets the exemplar-carrying
        # exposition; plain scrapes stay byte-identical.
        body, content_type = render_registries(
            (metrics.registry, OBS_REGISTRY, ENGINE_TELEMETRY_REGISTRY),
            request.headers.get("Accept"),
        )
        if content_type == "text/plain":
            return web.Response(body=body, content_type="text/plain")
        return web.Response(
            body=body, headers={"Content-Type": content_type}
        )

    # On-demand profiling state: one capture at a time (jax.profiler is a
    # process-global singleton — a second start_trace would raise).
    profile_lock = asyncio.Lock()

    async def debug_profile(request: web.Request) -> web.Response:
        """Capture a ``jax.profiler`` trace for N ms into a directory
        (``--profile-dir``; TensorBoard-loadable). Guarded twice: the
        ``--profiling`` flag must be on, and when an API key is configured
        the endpoint requires it like the work endpoints. On CPU backends
        this is a graceful no-op — there is no device timeline worth the
        capture overhead."""
        if not profiling:
            return _error(
                "profiling is disabled (start the engine with --profiling)",
                403, "permission_error",
            )
        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:  # noqa: BLE001 — empty/garbage body = defaults
                body = {}
        if not isinstance(body, dict):  # e.g. a bare JSON list
            body = {}
        try:
            duration_ms = float(
                body.get("duration_ms")
                or request.query.get("duration_ms", 1000)
            )
        except (TypeError, ValueError):
            return _error("duration_ms must be a number")
        duration_ms = min(max(duration_ms, 10.0), 60_000.0)
        out_dir = str(body.get("dir") or profile_dir)

        import jax

        if jax.default_backend() == "cpu":
            return web.json_response({
                "status": "skipped",
                "reason": "no accelerator backend (cpu) — nothing to profile",
                "duration_ms": duration_ms,
            })
        if profile_lock.locked():
            return _error("a profile capture is already running", 409,
                          "conflict_error")
        async with profile_lock:
            import os

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            try:
                await asyncio.sleep(duration_ms / 1000.0)
            finally:
                jax.profiler.stop_trace()
        logger.info("profile captured: %.0f ms -> %s", duration_ms, out_dir)
        return web.json_response({
            "status": "ok", "dir": out_dir, "duration_ms": duration_ms,
        })

    async def debug_requests(request: web.Request) -> web.Response:
        """Engine-side timeline ring buffer (same shape as the router's
        GET /debug/requests, shared handler): per-request spans for
        admission, queue wait, prefill, decode — joinable to the router's
        timelines by trace id."""
        return debug_requests_response(recorder, request)

    async def debug_state(request: web.Request) -> web.Response:
        """One-shot engine introspection (docs/observability.md "Fleet
        debugging"): the scheduler/KV stats snapshot the metrics surface
        derives from, plus compile totals — what /debug/fleet shows for
        this engine, straight from the source for cross-validation."""
        stats = engine.engine.stats()
        return web.json_response({
            "model": model_name,
            "ready": engine.ready,
            "draining": engine.draining,
            "warming": engine.warming,
            "sleeping": engine.sleeping,
            "in_flight": engine.num_inflight(),
            "compiles_total": ENGINE_TELEMETRY.compile_count(),
            "flight": engine.engine.flight.stats(),
            "stats": {
                k: v for k, v in stats.items()
                if isinstance(v, (int, float, str, bool))
            },
        })

    async def debug_flight(request: web.Request) -> web.Response:
        """Flight-recorder dump (docs/observability.md "Flight
        recorder"): the last-N per-step records (``?n=``) or a time
        window (``?window_s=``), plus the retained auto-snapshots
        (tail outliers, live compiles, fatal steps). Guarded like the
        work endpoints when an API key is configured — step records
        carry request ids and tenant mix."""
        flight = engine.engine.flight
        try:
            n = int(request.query["n"]) if "n" in request.query else None
            window_s = (
                float(request.query["window_s"])
                if "window_s" in request.query else None
            )
        except (TypeError, ValueError):
            return _error("n and window_s must be numbers")
        # ?snapshots=1: include snapshots a PREVIOUS process persisted to
        # --flight-snapshot-dir — the post-mortem collection path.
        include_restored = request.query.get("snapshots") in ("1", "true")
        return web.json_response(flight.to_payload(
            n=n, window_s=window_s, include_restored=include_restored,
        ))

    async def is_sleeping(request: web.Request) -> web.Response:
        return web.json_response({"is_sleeping": engine.sleeping})

    async def sleep(request: web.Request) -> web.Response:
        level = int(request.query.get("level", "1"))
        engine.sleep(level)
        return web.json_response({"status": "sleeping", "level": level})

    async def wake_up(request: web.Request) -> web.Response:
        engine.wake_up()
        return web.json_response({"status": "awake"})

    async def drain(request: web.Request) -> web.Response:
        """Graceful drain: stop admitting new sequences, finish in-flight
        ones. ``?wait=1`` blocks (up to ``?timeout=`` seconds, default 30)
        until the engine is idle — the preStop-hook shape."""
        engine.drain()
        if request.query.get("wait"):
            try:
                timeout = float(request.query.get("timeout", "30"))
            except ValueError:
                timeout = 30.0
            deadline = time.time() + timeout
            while time.time() < deadline and engine.num_inflight() > 0:
                await asyncio.sleep(0.1)
        return web.json_response(
            {"status": "draining", "in_flight": engine.num_inflight()}
        )

    async def undrain(request: web.Request) -> web.Response:
        engine.undrain()
        return web.json_response(
            {"status": "accepting", "in_flight": engine.num_inflight()}
        )

    async def is_draining(request: web.Request) -> web.Response:
        return web.json_response(
            {"is_draining": engine.draining, "in_flight": engine.num_inflight()}
        )

    async def load_lora(request: web.Request) -> web.Response:
        """Parse the PEFT checkpoint and install it into a device bank slot
        (reference loadAdapter, loraadapter_controller.go:582-611). The
        safetensors read + device write run off the event loop."""
        body = await request.json()
        name = body.get("lora_name")
        if not name:
            return _error("lora_name required")
        if engine.engine.lora_manager is None:
            return _error("LoRA not enabled (--enable-lora)", 400)
        path = body.get("lora_path")
        try:
            ad = await asyncio.get_running_loop().run_in_executor(
                None, engine.engine.load_lora, name, path
            )
        except FileNotFoundError as e:
            return _error(str(e), 404, "not_found_error")
        except (ValueError, RuntimeError) as e:
            return _error(str(e), 400)
        return web.json_response(
            {"status": "ok", "name": ad.name, "rank": ad.rank, "slot": ad.slot}
        )

    async def unload_lora(request: web.Request) -> web.Response:
        body = await request.json()
        name = body.get("lora_name")
        if not name:
            return _error("lora_name required")
        removed = await asyncio.get_running_loop().run_in_executor(
            None, engine.engine.unload_lora, name
        )
        return web.json_response({"status": "ok", "removed": bool(removed)})

    async def version(request: web.Request) -> web.Response:
        return web.json_response({"version": __version__})

    app.router.add_get("/v1/models", list_models)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/rerank", rerank)
    app.router.add_post("/v1/rerank", rerank)
    app.router.add_post("/v2/rerank", rerank)
    app.router.add_post("/score", score)
    app.router.add_post("/v1/score", score)
    app.router.add_post("/tokenize", tokenize)
    app.router.add_post("/detokenize", detokenize)
    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_get("/metrics", metrics_endpoint)
    app.router.add_get("/debug/requests", debug_requests)
    app.router.add_get("/debug/state", debug_state)
    app.router.add_get("/debug/flight", debug_flight)
    app.router.add_post("/debug/profile", debug_profile)
    app.router.add_get("/is_sleeping", is_sleeping)
    app.router.add_post("/sleep", sleep)
    app.router.add_post("/wake_up", wake_up)
    app.router.add_post("/drain", drain)
    app.router.add_post("/undrain", undrain)
    app.router.add_get("/is_draining", is_draining)
    app.router.add_post("/v1/load_lora_adapter", load_lora)
    app.router.add_post("/v1/unload_lora_adapter", unload_lora)
    app.router.add_get("/version", version)
    return app


def parse_engine_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        description="production-stack-tpu serving engine (vllm-serve analogue)"
    )
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--model", default="tiny-llama-debug")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--max-model-len", type=int, default=4096)
    p.add_argument("--block-size", type=int, default=32)
    p.add_argument("--num-kv-blocks", type=int, default=None)
    p.add_argument(
        "--gpu-memory-utilization", "--hbm-utilization",
        dest="hbm_utilization", type=float, default=0.9,
    )
    p.add_argument("--max-num-seqs", type=int, default=64)
    p.add_argument(
        "--max-num-batched-tokens", dest="max_prefill_tokens", type=int, default=2048
    )
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--pipeline-parallel-size", type=int, default=1)
    p.add_argument("--data-parallel-size", type=int, default=1)
    p.add_argument("--sequence-parallel-size", type=int, default=1,
                   help="ring-attention context parallel (encode path)")
    p.add_argument("--expert-parallel-size", type=int, default=1,
                   help="MoE expert bank sharding over the ep mesh axis")
    p.add_argument("--moe-impl", default="auto",
                   choices=["auto", "ragged", "dense"])
    p.add_argument("--kv-cache-dtype", default=None)
    # Weight-only int8 (per-output-channel scales): the `vllm serve
    # --quantization` analogue; what fits an 8B model + KV on one 16 GiB v5e.
    p.add_argument("--quantization", default=None, choices=["int8", "int4"])
    p.add_argument("--attn-impl", default="auto", choices=["auto", "gather", "pallas"])
    p.add_argument("--enable-prefix-caching", action="store_true", default=True)
    p.add_argument(
        "--no-enable-prefix-caching", dest="enable_prefix_caching",
        action="store_false",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--api-key", default=None)
    p.add_argument("--sentry-dsn", default=None)
    # LoRA serving (vLLM --enable-lora analogue).
    p.add_argument("--enable-lora", action="store_true", default=False)
    p.add_argument("--max-loras", type=int, default=8)
    p.add_argument("--max-lora-rank", type=int, default=16)
    p.add_argument("--lora-dir", default="/adapters")
    # Decode burst + batch-shape floors.
    p.add_argument("--num-decode-steps", type=int, default=1)
    p.add_argument("--adaptive-decode-steps", type=int, default=0,
                   help="deep burst cap when the arrival stream is quiet")
    p.add_argument("--adaptive-decode-quiet-s", type=float, default=0.5)
    p.add_argument("--adaptive-decode-min-running", type=int, default=0)
    p.add_argument("--min-decode-bucket", type=int, default=1)
    # Overlapped decode pipeline (docs/engine.md "Overlapped decode
    # pipeline"): burst N+1 dispatches as soon as burst N's tokens are
    # fetched, N's host bookkeeping overlaps N+1's execution; engages only
    # under the adaptive-deepening arrival-safety gates so TTFT is
    # unaffected.
    p.add_argument("--overlap-decode", dest="overlap_decode",
                   action="store_true", default=True)
    p.add_argument("--no-overlap-decode", dest="overlap_decode",
                   action="store_false",
                   help="disable the arrival-gated overlapped decode "
                        "pipeline (synchronous hot loop)")
    # Speculative decoding (n-gram prompt lookup; 0 = off).
    p.add_argument("--speculative-ngram", type=int, default=0,
                   help="max draft tokens per step via n-gram prompt lookup")
    p.add_argument("--ngram-min", type=int, default=1)
    p.add_argument("--ngram-max", type=int, default=3)
    p.add_argument("--ngram-lookback", type=int, default=8192,
                   help="cap prompt-lookup scan to last N tokens (0 = all)")
    # Live-sequence KV swap (vLLM --swap-space analogue; engine/swap.py).
    p.add_argument("--kv-swap", action="store_true", default=True)
    p.add_argument("--no-kv-swap", dest="kv_swap", action="store_false")
    p.add_argument("--swap-quantum-tokens", type=int, default=256,
                   help="decode tokens before a running seq may rotate out "
                        "for parked/queued work (0 = only under pressure)")
    p.add_argument("--swap-stash-blocks", type=int, default=4096,
                   help="host-DRAM budget for stashed tail pages (KV pages)")
    # KV tiering / controller (LMCache env-var analogues).
    p.add_argument("--cpu-offload-blocks", type=int, default=0)
    p.add_argument("--remote-kv-url", default=None,
                   help="kvserver base URL; a comma-separated list makes "
                        "the engine a sharded-ring client "
                        "(docs/kvserver.md)")
    p.add_argument("--kv-replication", type=int, default=2,
                   help="replicas per KV block/manifest on the kvserver "
                        "ring (clamped to the shard count)")
    p.add_argument("--cache-controller-url", default=None)
    p.add_argument("--engine-url", default=None)
    p.add_argument(
        "--kv-role", default="none",
        choices=["none", "producer", "consumer", "both"],
    )
    # Streamed disagg KV handoff (docs/disagg.md): consumer prefetch
    # batching depth and the wall the decode engine waits for a prefill's
    # manifest completion before degrading to the fused path.
    p.add_argument("--kv-prefetch-depth", type=int, default=64,
                   help="max KV pages per batched GET while following a "
                        "disagg prefill's manifest")
    p.add_argument("--kv-transfer-timeout-s", type=float, default=10.0,
                   help="seconds the decode engine waits for a disagg "
                        "manifest's completion marker before recomputing "
                        "the prefill locally (fused fallback)")
    # Cross-encoder scoring sidecar for /rerank and /score (bge-reranker-
    # style HF dir or a bert preset). Without it those endpoints fall back
    # to embedding cosine similarity.
    p.add_argument("--scoring-model", default=None)
    # Deadline shedding (docs/resilience.md "Deadlines & hedging"): honor
    # the router-propagated X-PST-Deadline-Ms budget.
    p.add_argument("--deadline-shedding", dest="deadline_shedding",
                   action="store_true", default=True)
    p.add_argument("--no-deadline-shedding", dest="deadline_shedding",
                   action="store_false")
    # Tenant-aware scheduling (docs/multi-tenancy.md): honor the
    # router-stamped X-PST-Tenant / X-PST-Tenant-Class headers in the
    # ready queue (weighted-fair admission, batch preempted first).
    p.add_argument("--tenant-fairness", dest="tenant_fairness",
                   action="store_true", default=True)
    p.add_argument("--no-tenant-fairness", dest="tenant_fairness",
                   action="store_false")
    # Request tracing (docs/observability.md): engine-side spans for
    # admission / queue wait / prefill / decode, joined to the router's
    # trace via the propagated traceparent.
    p.add_argument("--tracing", dest="tracing", action="store_true",
                   default=True)
    p.add_argument("--no-tracing", dest="tracing", action="store_false")
    p.add_argument("--debug-requests-buffer", type=int, default=256,
                   help="completed request timelines kept for "
                        "GET /debug/requests (0 disables the endpoint)")
    p.add_argument("--log-format", choices=["text", "json"], default="text",
                   help="log output format: 'json' emits one JSON object "
                        "per line enriched with trace_id/request_id/"
                        "tenant/engine_id (docs/observability.md "
                        "\"Structured logging\")")
    # On-demand jax.profiler capture (docs/observability.md "Profiling").
    p.add_argument("--profiling", dest="profiling", action="store_true",
                   default=False,
                   help="enable POST /debug/profile (on-demand jax.profiler "
                        "trace capture; no-op on CPU backends)")
    p.add_argument("--profile-dir", default="/tmp/pst_profiles",
                   help="directory POST /debug/profile writes traces to")
    # Startup-phase decomposition (pst_engine_startup_seconds{phase}).
    p.add_argument("--startup-phases", dest="startup_phases",
                   action="store_true", default=True)
    p.add_argument("--no-startup-phases", dest="startup_phases",
                   action="store_false",
                   help="do not export pst_engine_startup_seconds")
    # Ahead-of-time precompilation + persistent compile cache
    # (docs/engine.md "Warmup & precompilation"). The helm chart deploys
    # with --warmup full; bare CLI runs default to off so dev loops and
    # embedded use stay instant.
    p.add_argument("--warmup", default="off",
                   choices=["off", "lazy", "full"],
                   help="shape-bucket precompilation before /ready flips: "
                        "full = entire lattice, lazy = the core set the "
                        "first requests hit, off = compile on demand")
    p.add_argument("--warmup-bucket-budget", type=int, default=0,
                   help="cap warmup to this many lattice buckets, "
                        "most-likely-first (0 = whole lattice)")
    p.add_argument("--compile-cache-dir", default=None,
                   help="persistent JAX compilation cache root; compiled "
                        "executables land in a subdirectory keyed on "
                        "model+mesh+dtype+code version so warm restarts "
                        "skip XLA entirely")
    # Flight recorder + cost attribution (docs/observability.md "Flight
    # recorder" / "Cost attribution").
    p.add_argument("--flight-buffer", type=int, default=512,
                   help="per-step flight-recorder ring capacity (GET "
                        "/debug/flight; auto-snapshots on tail outliers "
                        "and SIGTERM/fatal; 0 disables recording)")
    p.add_argument("--flight-snapshot-dir", default=None,
                   help="persist retained flight snapshots as JSON files "
                        "under this directory (bounded, oldest-first "
                        "eviction) and load them back into GET "
                        "/debug/flight?snapshots=1 after a restart — "
                        "tail-outlier post-mortems survive process death")
    p.add_argument("--cost-attribution", dest="cost_attribution",
                   action="store_true", default=True)
    p.add_argument("--no-cost-attribution", dest="cost_attribution",
                   action="store_false",
                   help="disable per-request device-seconds attribution "
                        "(X-PST-Cost header, pst_request_device_seconds, "
                        "pst_tenant_device_seconds)")
    return p.parse_args(argv)


def engine_config_from_args(args: argparse.Namespace) -> EngineConfig:
    return EngineConfig(
        model=args.model,
        tokenizer=args.tokenizer,
        served_model_name=args.served_model_name,
        max_model_len=args.max_model_len,
        block_size=args.block_size,
        num_kv_blocks=args.num_kv_blocks,
        hbm_utilization=args.hbm_utilization,
        max_num_seqs=args.max_num_seqs,
        max_prefill_tokens=args.max_prefill_tokens,
        tensor_parallel_size=args.tensor_parallel_size,
        pipeline_parallel_size=args.pipeline_parallel_size,
        data_parallel_size=args.data_parallel_size,
        sequence_parallel_size=args.sequence_parallel_size,
        expert_parallel_size=args.expert_parallel_size,
        kv_cache_dtype=args.kv_cache_dtype,
        quantization=args.quantization,
        attn_impl=args.attn_impl,
        moe_impl=args.moe_impl,
        enable_prefix_caching=args.enable_prefix_caching,
        seed=args.seed,
        enable_lora=args.enable_lora,
        max_loras=args.max_loras,
        max_lora_rank=args.max_lora_rank,
        lora_dir=args.lora_dir,
        num_decode_steps=args.num_decode_steps,
        adaptive_decode_steps=args.adaptive_decode_steps,
        adaptive_decode_quiet_s=args.adaptive_decode_quiet_s,
        adaptive_decode_min_running=args.adaptive_decode_min_running,
        overlap_decode=args.overlap_decode,
        min_decode_bucket=args.min_decode_bucket,
        speculative_ngram=args.speculative_ngram,
        ngram_min=args.ngram_min,
        ngram_max=args.ngram_max,
        ngram_lookback=args.ngram_lookback,
        kv_swap=args.kv_swap,
        swap_quantum_tokens=args.swap_quantum_tokens,
        swap_stash_blocks=args.swap_stash_blocks,
        cpu_offload_blocks=args.cpu_offload_blocks,
        remote_kv_url=args.remote_kv_url,
        kv_replication=args.kv_replication,
        cache_controller_url=args.cache_controller_url,
        engine_url=args.engine_url,
        kv_role=args.kv_role,
        kv_prefetch_depth=args.kv_prefetch_depth,
        kv_transfer_timeout_s=args.kv_transfer_timeout_s,
        deadline_shedding=args.deadline_shedding,
        tenant_fairness=args.tenant_fairness,
        warmup=args.warmup,
        warmup_bucket_budget=args.warmup_bucket_budget,
        compile_cache_dir=args.compile_cache_dir,
        flight_buffer=args.flight_buffer,
        flight_snapshot_dir=args.flight_snapshot_dir,
        cost_attribution=args.cost_attribution,
    )


async def controller_report_loop(
    engine: AsyncLLMEngine, controller_url: str, engine_url: str, interval: float
) -> None:
    """Snapshot-register resident chunk hashes with the cache controller
    (LMCACHE controller heartbeat analogue; feeds KV-aware routing)."""
    import aiohttp

    model = engine.engine.model_name
    while True:
        try:
            eng = engine.engine
            cutoff = time.time() - eng.CHUNK_CLAIM_TTL
            hashes = [
                h for h, t in list(eng.resident_chunk_hashes.items()) if t >= cutoff
            ]
            async with aiohttp.ClientSession() as sess:
                await sess.post(
                    f"{controller_url.rstrip('/')}/register",
                    json={
                        "url": engine_url,
                        "model": model,
                        "hashes": hashes,
                        "replace": True,
                    },
                    timeout=aiohttp.ClientTimeout(total=5),
                )
        except Exception as e:  # noqa: BLE001 — registration is best-effort
            logger.debug("controller registration failed: %s", e)
        await asyncio.sleep(interval)


def main(argv=None) -> None:
    # Honor JAX_PLATFORMS even when a sitecustomize already registered a
    # device plugin before this process's env was consulted (jax.config wins
    # over plugin registration as long as no backend has initialized yet).
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    args = parse_engine_args(argv)
    configure_logging(
        getattr(args, "log_format", "text") or "text",
        component="engine",
        engine_id=f"{args.host}:{args.port}",
    )
    cfg = engine_config_from_args(args)
    # Must be set before the engine constructs: the runner records the
    # load/shard phases during __init__.
    ENGINE_TELEMETRY.startup_enabled = args.startup_phases

    # Optional error reporting + tracing (no-ops without the SDKs; OTel
    # activates via the standard OTEL_* env contract the chart wires in).
    from ..utils_tracing import init_otel, init_sentry

    init_sentry(args.sentry_dsn)
    init_otel("pst-engine")

    # Multi-host boot (the ray-cluster head/worker analogue): every process
    # joins the jax.distributed runtime; host 0 serves HTTP, the rest mirror
    # device steps (SURVEY.md §7 hard part 3 — single-program serving).
    from ..parallel.distributed import is_primary, maybe_init_distributed

    multihost = maybe_init_distributed()
    if multihost and not is_primary():
        from .multihost import make_follower_runner, run_follower

        run_follower(make_follower_runner(cfg))
        return

    engine = AsyncLLMEngine(cfg)
    if multihost:
        from .multihost import StepPublisher

        engine.engine.runner.publisher = StepPublisher()
    cross_encoder = None
    if args.scoring_model:
        from .cross_encoder import CrossEncoder

        cross_encoder = CrossEncoder(args.scoring_model)
        logger.info(
            "cross-encoder scoring model loaded: %s", cross_encoder.cfg.name
        )
    app = create_engine_app(
        engine, api_key=args.api_key, cross_encoder=cross_encoder,
        tracing=args.tracing,
        debug_requests_buffer=args.debug_requests_buffer,
        profiling=args.profiling,
        profile_dir=args.profile_dir,
    )

    async def on_startup(app):
        engine.start(asyncio.get_event_loop())
        if cfg.cache_controller_url:
            engine_url = cfg.engine_url or f"http://{args.host}:{args.port}"
            app["controller_task"] = spawn_owned(
                controller_report_loop(
                    engine, cfg.cache_controller_url, engine_url, 10.0
                ),
                name="engine-controller-report",
            )

    async def on_cleanup(app):
        task = app.get("controller_task")
        if task:
            task.cancel()
        # SIGTERM lands here via aiohttp's graceful shutdown: freeze the
        # flight ring so the terminating pod leaves a post-mortem in its
        # logs (the /debug/flight endpoint dies with the process).
        try:
            snap = engine.engine.flight.snapshot("sigterm")
            if snap["records"]:
                logger.info(
                    "flight snapshot (sigterm): %d steps recorded, tail=%s",
                    snap["total_steps"], snap["records"][-3:],
                )
        except Exception:  # noqa: BLE001 — shutdown must proceed
            pass
        publisher = engine.engine.runner.publisher
        if publisher is not None:
            publisher.shutdown()  # release follower loops before exiting
        engine.shutdown()

    app.on_startup.append(on_startup)
    app.on_cleanup.append(on_cleanup)
    web.run_app(app, host=args.host, port=args.port, access_log=None)


if __name__ == "__main__":
    main()
