"""Speculative decoding: n-gram prompt-lookup drafting.

The TPU-native analogue of vLLM's ``[ngram]`` speculative model (which the
reference stack passes through to its engines via ``extraArgs``,
``helm/values.yaml:81``): no draft model — draft tokens are proposed by
matching the sequence's own recent suffix against its history (prompt +
generated text). Multi-round-QA-style workloads re-quote their history
constantly, so lookup drafts hit often; the target model then scores all K
drafts in ONE forward pass (``all_logits``) instead of K sequential decode
steps.

Exactness: the engine engages speculation only for greedy (temperature=0)
batches and accepts a draft prefix exactly as long as it matches the
model's own argmax at every position — output token-for-token identical to
non-speculative decoding. The paged KV design makes rollback free: rejected
positions' cache writes sit past the committed ``kv_len`` and are
overwritten when those positions are decoded for real.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


def propose_ngram(
    token_ids,
    k: int,
    min_n: int = 1,
    max_n: int = 3,
    lookback: int = 0,
) -> Optional[List[int]]:
    """Draft up to ``k`` tokens by prompt lookup.

    Finds the longest n-gram (``max_n`` down to ``min_n``) such that the
    sequence's last n tokens also occur earlier in the sequence; drafts the
    tokens that followed the MOST RECENT earlier occurrence. None if no
    n-gram recurs (the caller falls back to plain decoding).

    ``token_ids`` may be a list or an int numpy array (the engine caches
    one per sequence — rebuilding 32k-token arrays every decode step was
    measurable host time). ``lookback`` > 0 caps the scan to the last that
    many tokens, bounding per-step host work at long context.
    """
    a = np.asarray(token_ids, np.int64)
    if lookback > 0 and a.shape[0] > lookback:
        a = a[-lookback:]
    L = a.shape[0]
    if L < min_n + 1 or k <= 0:
        return None
    for n in range(min(max_n, L - 1), min_n - 1, -1):
        suf = a[-n:]
        # Match windows a[s : s+n] for starts s in [0, L-n) — vectorized
        # per-offset equality. The suffix itself (start L-n) lies past the
        # range, so every candidate is a genuine earlier (possibly
        # overlapping) occurrence.
        ok = np.ones(L - n, bool)
        for t in range(n):
            ok &= a[t : L - n + t] == suf[t]
        starts = np.flatnonzero(ok)
        if starts.size:
            s = int(starts[-1])  # most recent occurrence
            cont = a[s + n : s + n + k]
            if cont.size:
                return cont.astype(np.int64).tolist()
    return None


def count_accepted(draft: List[int], argmax_ids: np.ndarray) -> int:
    """Accepted draft prefix length: position j's draft survives iff it
    equals the model's argmax at position j-1 AND every earlier draft
    survived. ``argmax_ids`` is the verify step's [K+1] argmax row."""
    a = 0
    for j, d in enumerate(draft):
        if int(argmax_ids[j]) != int(d):
            break
        a += 1
    return a
