"""Cross-encoder scoring sidecar for `/rerank` and `/score`.

Wraps :class:`production_stack_tpu.models.bert.BertClassifier` with pair
tokenization and static-shape batching: pairs are padded into pow-2 (B, T)
buckets so repeat traffic reuses a handful of compiled programs, mirroring
the decoder engine's bucketing discipline. Enabled via the engine server's
``--scoring-model`` flag (the analogue of deploying a vLLM ``--task score``
pod for bge-reranker checkpoints in the reference stack).
"""

from __future__ import annotations

import os
import threading
from typing import List, Sequence, Tuple

import jax
import numpy as np

from ..logging_utils import init_logger
from ..models.bert import (
    BertClassifier,
    get_bert_config,
    load_hf_bert_params,
)
from .runner import _pow2
from .tokenizer import get_tokenizer

logger = init_logger(__name__)


class CrossEncoder:
    """Jointly scores (query, document) pairs with a classification head."""

    def __init__(self, model: str, max_len: int = 512, max_batch: int = 32):
        self.cfg = get_bert_config(model)
        self.model = BertClassifier(self.cfg)
        self.max_len = min(
            max_len,
            self.cfg.max_position_embeddings - self.cfg.position_offset,
        )
        self.max_batch = max_batch
        if os.path.isdir(model):
            self.params = load_hf_bert_params(self.cfg, model)
            tok_spec = model
        else:  # preset: random weights (tests / smoke)
            self.params = self.model.init_params(jax.random.PRNGKey(0))
            tok_spec = None
        self.tokenizer = get_tokenizer(tok_spec, self.cfg.vocab_size)
        # pstlint: disable=recompile-risk(cross-encoder rerank compiles once per padded pair-batch at first use; rerank is not on the TTFT-critical lattice and the one-time cost is accepted)
        self._fn = jax.jit(self.model.forward)
        self._lock = threading.Lock()  # one scoring dispatch at a time

    def score_pairs(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        """Relevance logits for each (query, document) pair."""
        out: List[float] = []
        for i in range(0, len(pairs), self.max_batch):
            out.extend(self._score_chunk(pairs[i : i + self.max_batch]))
        return out

    def _score_chunk(self, pairs: Sequence[Tuple[str, str]]) -> List[float]:
        encoded = []
        for a, b in pairs:
            ids, types = self.tokenizer.encode_pair(a, b, max_len=self.max_len)
            encoded.append((ids, types))
        B = len(encoded)
        Bb = _pow2(B, self.max_batch)
        Tb = _pow2(max(len(x) for x, _ in encoded), self.max_len)
        tokens = np.full((Bb, Tb), self.cfg.pad_token_id, np.int32)
        type_ids = np.zeros((Bb, Tb), np.int32)
        lengths = np.zeros(Bb, np.int32)
        for i, (x, ty) in enumerate(encoded):
            x = [min(t, self.cfg.vocab_size - 1) for t in x]
            tokens[i, : len(x)] = x
            type_ids[i, : len(ty)] = ty
            lengths[i] = len(x)
        with self._lock:
            scores = np.asarray(
                self._fn(self.params, tokens, lengths, type_ids)
            )
        return [float(s) for s in scores[:B]]
