"""LLMEngine: the synchronous serving core (add_request / step / outputs).

Equivalent role to the vLLM engine the reference stack drives over HTTP
(SURVEY.md §1 "Serving engine" row). One `step()` = one scheduler decision +
one (or a few) jitted device steps + host-side bookkeeping: detokenization,
stop handling, prefix-block commitment, and the counters the `/metrics`
endpoint exports under the `vllm:`-compatible names the router's stats
scraper parses (`stats/engine_stats.py:42-85` contract).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, List, Optional, Sequence as Seq, Union

import numpy as np

from ..kvcache.hashing import CHUNK_TOKENS
from ..logging_utils import init_logger
from ..models.registry import get_model_config
from ..obs.engine_telemetry import ENGINE_TELEMETRY
from ..obs.flight import NULL_FLIGHT_RECORDER, FlightRecorder
from .config import EngineConfig
from .kv_manager import BlockAllocator
from .runner import ModelRunner
from .scheduler import Scheduler, SchedulerConfig
from .sequence import SamplingParams, Sequence
from .tokenizer import get_tokenizer

logger = init_logger(__name__)


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    text_delta: str = ""
    new_token_ids: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: Optional[str] = None
    num_prompt_tokens: int = 0
    num_output_tokens: int = 0
    num_cached_prompt_tokens: int = 0
    ttft: Optional[float] = None
    # TTFT decomposition (monotonic durations, seconds): time queued before
    # the first scheduler admission, first admission → first token, and —
    # on the finished output — first token → completion. The server turns
    # these into engine_queue/prefill/decode spans + stage histograms.
    queue_time: Optional[float] = None
    prefill_time: Optional[float] = None
    decode_time: Optional[float] = None
    # One entry per new token when SamplingParams.logprobs is set:
    # {"token_id", "logprob", "top": [(token_id, logprob), ...]}.
    logprobs: Optional[List[dict]] = None
    # XLA compiles the step that produced this output absorbed
    # ({"kind", "shape_bucket", "seconds"}): the HTTP layer attaches them
    # as `compile` span events so a recompile shows up inside the victim
    # request's timeline (docs/observability.md "Engine telemetry").
    compile_events: Optional[List[dict]] = None
    # Per-request cost attribution (finished outputs only, when
    # cost_attribution is on): prefill/decode device-seconds, KV
    # page-seconds, queue wait — the X-PST-Cost header / usage extension
    # payload (docs/observability.md "Cost attribution").
    cost: Optional[dict] = None


class LLMEngine:
    def __init__(self, cfg: EngineConfig, mesh=None):
        t_init = time.perf_counter()
        self.cfg = cfg
        self.model_cfg = get_model_config(cfg.model)
        if cfg.compile_cache_dir:
            # Before the runner wires any jit: executables compiled earlier
            # are never written back to the persistent cache.
            from .precompile import configure_compile_cache

            configure_compile_cache(cfg, self.model_cfg)
        tok_spec = cfg.tokenizer or (cfg.model if os.path.isdir(cfg.model) else None)
        self.tokenizer = get_tokenizer(tok_spec, self.model_cfg.vocab_size)
        t_runner = time.perf_counter()
        self.runner = ModelRunner(cfg, self.model_cfg, mesh)
        t_runner_s = time.perf_counter() - t_runner
        if cfg.cpu_offload_blocks > 0 or cfg.remote_kv_url:
            from .cache_tiering import TieredAllocator, create_remote_client

            host_blocks = cfg.cpu_offload_blocks
            if (
                host_blocks == 0
                and cfg.remote_kv_url
                and cfg.kv_role in ("consumer", "both")
            ):
                # The consumer-side prefetch stages published pages in the
                # host pool so admission's match_prefix faults them up —
                # a consumer engine without an explicit offload budget
                # still needs a staging tier (docs/disagg.md).
                host_blocks = max(self.runner.num_blocks // 2, 1024)
            self.allocator: BlockAllocator = TieredAllocator(
                self.runner.num_blocks,
                cfg.block_size,
                page_io=self.runner,
                host_blocks=host_blocks,
                remote=create_remote_client(
                    cfg.remote_kv_url, replication=cfg.kv_replication
                )
                if cfg.remote_kv_url
                else None,
                enable_prefix_caching=cfg.enable_prefix_caching,
            )
        else:
            self.allocator = BlockAllocator(
                self.runner.num_blocks, cfg.block_size, cfg.enable_prefix_caching
            )
        # Streamed disagg KV handoff (docs/disagg.md): a producer engine
        # ships each prefill chunk's committed pages under the request's
        # kv_transfer id as the chunk completes (worker thread, batched
        # puts + manifest appends); a consumer engine follows manifests
        # and stages published pages in the host pool while the remote
        # prefill is still running.
        self.kv_publisher = None
        self.kv_prefetcher = None
        remote = getattr(self.allocator, "remote", None)
        if remote is not None and cfg.kv_role in ("producer", "both"):
            from .kv_handoff import KVHandoffPublisher

            self.kv_publisher = KVHandoffPublisher(remote)
        if (
            remote is not None
            and cfg.kv_role in ("consumer", "both")
            and getattr(self.allocator, "host_pool", None) is not None
        ):
            from .kv_handoff import KVHandoffPrefetcher

            self.kv_prefetcher = KVHandoffPrefetcher(
                remote,
                self.allocator.host_pool,
                timeout_s=cfg.kv_transfer_timeout_s,
                depth=cfg.kv_prefetch_depth,
            )
        if cfg.kv_swap:
            from .swap import KVSwapper

            self.swapper: Optional["KVSwapper"] = KVSwapper(
                self.runner, max_stash_blocks=cfg.swap_stash_blocks
            )
        else:
            self.swapper = None
        self.scheduler = Scheduler(
            SchedulerConfig(
                max_num_seqs=cfg.max_num_seqs,
                max_prefill_tokens=cfg.max_prefill_tokens,
                max_model_len=cfg.max_model_len,
                num_decode_steps=cfg.num_decode_steps,
                # The in-flight continuation writes one burst past the host
                # view, so its pages must already exist at dispatch time —
                # for unconditional pipelining (async_decode) AND for the
                # arrival-gated overlap (which can engage on any pass).
                # Spec engines never pipeline (_pipeline_ok defers to
                # speculation), so they keep the tighter reservation.
                decode_lookahead=(
                    2
                    if (
                        cfg.async_decode
                        or (cfg.overlap_decode and not cfg.speculative_ngram)
                    )
                    else 1
                ),
                spec_tokens=0 if cfg.async_decode else cfg.speculative_ngram,
                swap_quantum=cfg.swap_quantum_tokens,
                deadline_shedding=cfg.deadline_shedding,
                tenant_fairness=cfg.tenant_fairness,
            ),
            self.allocator,
            swapper=self.swapper,
        )
        if cfg.async_decode and cfg.speculative_ngram:
            # Pipelined bursts win every decode step, so the spec branch
            # would never run — surface the conflict instead of silently
            # reserving pages for it.
            logger.warning(
                "speculative_ngram is disabled while async_decode is on "
                "(pipelined bursts preempt the speculation path)"
            )
        # Speculative-decoding counters (engine.stats / observability).
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        # Pipelined-decode bookkeeping: membership of the in-flight burst
        # (original order, including members that finished meanwhile) and
        # sequences whose page release is deferred until the drain.
        self._burst_seqs: List[Sequence] = []
        self._burst_n = 0
        self._burst_deferred: List[Sequence] = []
        if cfg.enable_lora:
            from .lora import LoraManager

            self.lora_manager: Optional["LoraManager"] = LoraManager(
                self.model_cfg, cfg.max_loras, cfg.max_lora_rank, cfg.lora_dir
            )
        else:
            self.lora_manager = None
        # Unloaded-adapter slots awaiting their last in-flight sequence.
        self._retiring_slots: set = set()
        # Last request arrival (adaptive burst-depth + overlap gates) +
        # observability counters for deep/pipelined bursts actually executed.
        self._last_arrival = 0.0
        self.adaptive_deep_bursts_total = 0
        self.pipelined_bursts_total = 0
        # Flight recorder (docs/observability.md "Flight recorder"):
        # always-on bounded ring of per-step records, fed through
        # ENGINE_TELEMETRY's dispatch path; this engine's scheduler/KV
        # state rides each record via the probe closure. Attached last-
        # wins: a fresh engine in one process must own the sink.
        self.flight = (
            FlightRecorder(
                cfg.flight_buffer, snapshot_dir=cfg.flight_snapshot_dir
            )
            if cfg.flight_buffer > 0 else NULL_FLIGHT_RECORDER
        )
        if self.flight.enabled:
            # Only a live ring takes the probe: installing a bound method
            # on the shared null singleton would pin this whole engine
            # (params + KV) past its lifetime.
            self.flight.set_probe(self._flight_probe)
        ENGINE_TELEMETRY.attach_flight(self.flight)
        # Compile events awaiting an output-emitting step (see step()).
        self._pending_compile_events: List[dict] = []
        # Precompile summary (engine/precompile.py): populated by
        # precompile(); the server's /ready payload surfaces it.
        self.warmup_summary: Optional[dict] = None
        self._seqs: Dict[str, Sequence] = {}
        # Incremental detokenizer state per request:
        # emitted text + [prefix_offset, read_offset) decode window.
        self._detok: Dict[str, Dict[str, object]] = {}
        # Chunk hashes resident in this engine's tiers (controller
        # registration: hash -> last-commit time).
        self.resident_chunk_hashes: Dict[int, float] = {}
        # Cumulative counters for /metrics.
        self.kv_published_blocks_total = 0
        self.num_preempted_total = 0
        self.prompt_tokens_total = 0
        self.generation_tokens_total = 0
        # Startup decomposition, phase 3: everything around the runner —
        # tokenizer, allocator, swapper, scheduler, LoRA manager
        # (pst_engine_startup_seconds{phase="warmup"}; the runner records
        # load and shard itself).
        ENGINE_TELEMETRY.record_startup_phase(
            "warmup", time.perf_counter() - t_init - t_runner_s
        )

    @property
    def model_name(self) -> str:
        return self.cfg.served_model_name or self.model_cfg.name

    def _flight_probe(self) -> dict:
        """Scheduler/KV state attached to each flight record. Runs on the
        step thread (the thread that mutates the scheduler), right after
        a dispatch — plain reads, O(running)."""
        waiting, running, swapped, batch = self.scheduler.flight_depths()
        return {
            "waiting": waiting,
            "running": running,
            "swapped": swapped,
            "batch_tier_rows": batch,
            "kv_occupancy": self.allocator.usage,
            "preemptions": self.num_preempted_total,
        }

    def _finalize_cost(self, seq: Sequence) -> Optional[dict]:
        """Close a request's cost account exactly once: integrate the KV
        tail, export the per-phase histograms + tenant chip-time meter,
        and return the X-PST-Cost payload."""
        if not self.cfg.cost_attribution:
            return None
        if getattr(seq, "_cost_finalized", False):
            return getattr(seq, "_cost_final", None)
        now = time.monotonic()
        # BEFORE the scheduler releases block_ids: the tail residency
        # since the last charge point still belongs to this request.
        seq.charge_kv_pages(now)
        cost = seq.cost_snapshot(now)
        seq._cost_finalized = True
        seq._cost_final = cost
        ENGINE_TELEMETRY.record_request_cost(
            seq.tenant, seq.cost_prefill_s, seq.cost_decode_s
        )
        return cost

    # ------------------------------------------------------------------
    # Warmup precompilation (docs/engine.md "Warmup & precompilation")
    # ------------------------------------------------------------------

    def precompile(
        self, mode: Optional[str] = None, bucket_budget: Optional[int] = None
    ) -> dict:
        """Compile the padded shape-bucket lattice ahead of traffic.

        Runs on whatever thread calls it (the async engine's step thread,
        so HTTP probes stay responsive); records
        ``pst_engine_startup_seconds{phase="precompile"}`` and the
        coverage gauge, and returns the summary the server's ``/ready``
        payload exposes."""
        from .precompile import Precompiler

        t0 = time.perf_counter()
        summary = Precompiler(
            self.runner, self.cfg, mode=mode, bucket_budget=bucket_budget
        ).run()
        ENGINE_TELEMETRY.record_startup_phase(
            "precompile", time.perf_counter() - t0
        )
        self.warmup_summary = summary
        return summary

    # ------------------------------------------------------------------
    # Requests
    # ------------------------------------------------------------------

    def add_request(
        self,
        request_id: str,
        prompt: Optional[str] = None,
        prompt_token_ids: Optional[Seq[int]] = None,
        sampling: Optional[SamplingParams] = None,
        arrival_time: Optional[float] = None,
        lora_name: Optional[str] = None,
        deadline: Optional[float] = None,
        tenant: Optional[str] = None,
        tenant_class: Optional[str] = None,
        kv_transfer: Optional[dict] = None,
    ) -> Sequence:
        if prompt_token_ids is None:
            prompt_token_ids = self.tokenizer.encode(prompt or "")
        if not prompt_token_ids:
            prompt_token_ids = [0]
        lora_idx, lora_scale, salt = 0, 0.0, 0
        if lora_name:
            if self.lora_manager is None:
                raise ValueError("LoRA not enabled on this engine")
            ad = self.lora_manager.get(lora_name)
            if ad is None:
                raise ValueError(f"LoRA adapter {lora_name!r} not loaded")
            lora_idx, lora_scale = ad.slot, ad.scaling
            # KV under an adapter differs from base KV: salt the prefix
            # hash chain so cache hits never cross adapters.
            import xxhash

            salt = xxhash.xxh64(lora_name.encode()).intdigest() & 0x7FFF_FFFF_FFFF_FFFF
        seq = Sequence(
            request_id,
            prompt_token_ids,
            sampling or SamplingParams(),
            arrival_time=arrival_time,
            lora_idx=lora_idx,
            lora_scale=lora_scale,
            cache_salt=salt,
            deadline=deadline if self.cfg.deadline_shedding else None,
            tenant=tenant or "default",
            tenant_class=tenant_class or "interactive",
            kv_transfer=kv_transfer,
        )
        self._last_arrival = time.time()
        self.scheduler.add(seq)
        self._seqs[request_id] = seq
        self._detok[request_id] = {"emitted": "", "prefix": 0, "read": 0}
        self.prompt_tokens_total += len(prompt_token_ids)
        return seq

    def load_lora(self, name: str, path: Optional[str] = None):
        """Load a PEFT adapter into a device bank slot (operator flow:
        POST /v1/load_lora_adapter → here)."""
        if self.lora_manager is None:
            raise ValueError("LoRA not enabled on this engine (--enable-lora)")
        ad, arrays = self.lora_manager.load(name, path)
        if arrays is not None:  # freshly parsed (not already resident)
            self.runner.install_adapter(ad.slot, arrays)
        return ad

    def unload_lora(self, name: str) -> bool:
        """Unregister the adapter. New requests for it fail immediately;
        in-flight sequences finish under its weights — the device slot is
        zeroed and recycled only after the last one drains (step() sweeps
        ``_retiring_slots``). Matches the reference engines' drain-then-free
        semantics for /v1/unload_lora_adapter."""
        if self.lora_manager is None:
            return False
        ad = self.lora_manager.unload(name)
        if ad is None:
            return False
        self._retiring_slots.add(ad.slot)
        self._sweep_retiring_slots()
        return True

    def _sweep_retiring_slots(self) -> None:
        if not self._retiring_slots:
            return
        live = {s.lora_idx for s in self._seqs.values() if s.lora_idx}
        for slot in [s for s in self._retiring_slots if s not in live]:
            self._retiring_slots.discard(slot)
            self.runner.uninstall_adapter(slot)
            self.lora_manager.release_slot(slot)

    def abort_request(self, request_id: str) -> bool:
        # Bill the device time an aborted request already consumed (the
        # tenant chip-time meter must not have a free-abort loophole),
        # while its pages are still owned.
        live = self._seqs.get(request_id)
        if live is not None:
            self._finalize_cost(live)
        if self.runner.burst_in_flight and any(
            s.request_id == request_id for s in self._burst_seqs
        ):
            seq = self.scheduler.detach(request_id)
            if seq is not None:
                self._burst_deferred.append(seq)
        else:
            seq = self.scheduler.abort(request_id)
        self._seqs.pop(request_id, None)
        self._detok.pop(request_id, None)
        return seq is not None

    def has_work(self) -> bool:
        # An in-flight burst counts as work even with empty queues: its
        # results must be drained (and its deferred pages released).
        return self.scheduler.has_work() or self.runner.burst_in_flight

    def abort_all_requests(self) -> int:
        """Abort everything queued or running (sleep / fatal-error paths)."""
        if self.runner.burst_in_flight:
            self.runner.burst_drain()  # discard: everything is going away
            self._burst_seqs = []
            self._burst_n = 0
            self._release_burst_deferred()
        rids = list(self._seqs.keys())
        for rid in rids:
            self.abort_request(rid)
        return len(rids)

    def clear_kv_state(self) -> None:
        """Invalidate all HBM-resident KV bookkeeping. Must accompany any
        operation that discards cache contents (sleep level 2): otherwise the
        hash→page maps would serve zero-filled pages as prefix hits. Lower
        tiers (host pool / remote) keep their pages — their copies were
        written before the drop and stay valid, LMCache-style."""
        self.abort_all_requests()
        host_pool = getattr(self.allocator, "host_pool", None)
        remote = getattr(self.allocator, "remote", None)
        if host_pool is not None or remote is not None:
            from .cache_tiering import TieredAllocator

            old_shutdown = getattr(self.allocator, "shutdown", None)
            if old_shutdown is not None:
                old_shutdown()  # stop the old kv-remote-push worker thread
            new = TieredAllocator(
                self.runner.num_blocks,
                self.cfg.block_size,
                page_io=self.runner,
                host_blocks=0,
                remote=remote,
                enable_prefix_caching=self.cfg.enable_prefix_caching,
            )
            new.host_pool = host_pool  # preserve the warm host tier
            self.allocator = new
        else:
            self.allocator = BlockAllocator(
                self.runner.num_blocks,
                self.cfg.block_size,
                self.cfg.enable_prefix_caching,
            )
        self.scheduler.allocator = self.allocator
        self.resident_chunk_hashes.clear()

    # ------------------------------------------------------------------
    # Stepping
    # ------------------------------------------------------------------

    def _arrival_safe(self) -> bool:
        """The three arrival-safety rules shared by adaptive deepening and
        overlap engagement (proposals/adaptive-decode-bursts.md): PAST
        observations only — (1) the waiting queue is empty, (2) at least
        ``adaptive_decode_min_running`` sequences run (closed-loop traffic:
        a full running set means no client has a request left to send),
        (3) no arrival for ``adaptive_decode_quiet_s``. While arrivals
        flow, every gate-dependent optimization stays off and each arrival
        sees a fresh scheduling decision."""
        if self.scheduler.num_waiting:
            return False
        if self.scheduler.num_running < self.cfg.adaptive_decode_min_running:
            return False
        return (
            time.time() - self._last_arrival
            >= self.cfg.adaptive_decode_quiet_s
        )

    def _decode_depth_hint(self) -> Optional[int]:
        """Adaptive burst depth: deepen only when the arrival stream has
        been quiet (PAST arrivals only — a live request stream keeps bursts
        at the configured depth, so the deepening never costs tail latency
        it didn't already have)."""
        cap = self.cfg.adaptive_decode_steps
        if not cap or cap <= self.cfg.num_decode_steps:
            return None
        if not self._arrival_safe():
            return None
        return cap

    def step(self) -> List[RequestOutput]:
        outputs = self._step_impl()
        # A compile that landed inside this step delayed every request the
        # step served: attach the events so the HTTP layer can surface them
        # on the victim requests' traces. Compiles in output-less steps
        # (intermediate prefill chunks dispatch without emitting) are held
        # for the next emitting step — the same requests were waiting on
        # them.
        events = self._pending_compile_events + ENGINE_TELEMETRY.drain_compile_events()
        if outputs:
            if events:
                for out in outputs:
                    out.compile_events = list(events)
            self._pending_compile_events = []
        else:
            self._pending_compile_events = events[-8:]  # bounded
        return outputs

    def _step_impl(self) -> List[RequestOutput]:
        outputs: List[RequestOutput] = []
        hint = self._decode_depth_hint()
        if self.runner.burst_in_flight:
            locked = frozenset(s.request_id for s in self._burst_seqs)
            sched = self.scheduler.schedule(locked=locked, n_decode=hint)
            self.num_preempted_total += len(sched.preempted)
            outputs += self._finish_expired(sched.expired)
            if self._can_continue_burst(sched):
                self.pipelined_bursts_total += 1
                if self._burst_n > self.cfg.num_decode_steps:
                    self.adaptive_deep_bursts_total += 1
                rows = self.runner.burst_continue(self._burst_seqs)
                outputs += self._process_burst_rows(rows)
                self._sweep_retiring_slots()
                return outputs
            # A new arrival's prefill can slip in BEHIND the in-flight
            # burst: dispatch it first (the device serializes the two), then
            # drain the burst while the prefill executes — one combined wait
            # instead of drain-then-prefill round trips. Safe because the
            # prefill touches only its own freshly-allocated pages (locked
            # members could not be evicted by its allocation).
            prefill_handle = None
            if sched.prefills and not sched.blocked_on_locked:
                prefill_handle = self.runner.prefill_dispatch(sched.prefills)
            rows = self.runner.burst_drain()
            outputs += self._process_burst_rows(rows)
            self._release_burst_deferred()
            if prefill_handle is not None:
                prows = self.runner.prefill_fetch(
                    prefill_handle, len(sched.prefills)
                )
                outputs += self._process_prefill_rows(sched.prefills, prows)
                self._sweep_retiring_slots()
                return outputs
            sched = self.scheduler.schedule(n_decode=hint)
        else:
            sched = self.scheduler.schedule(n_decode=hint)
        self.num_preempted_total += len(sched.preempted)
        outputs += self._finish_expired(sched.expired)
        if sched.is_empty:
            self._sweep_retiring_slots()
            return outputs
        if sched.prefills:
            # Intermediate chunks sample nothing anyone reads: dispatch
            # without fetching (the round trip per chunk dominated cold
            # 20k-token prefills). Only a chunk that completes a fresh
            # prompt needs its sampled token back.
            any_completes = any(
                it.end == it.seq.num_prompt_tokens
                and not it.seq.output_token_ids
                for it in sched.prefills
            )
            if any_completes:
                rows = self.runner.execute_prefill_batch(sched.prefills)
                outputs += self._process_prefill_rows(sched.prefills, rows)
            else:
                self.runner.execute_prefill_batch_nofetch(sched.prefills)
                outputs += self._process_prefill_rows(sched.prefills, None)
        elif (
            drafts := self._spec_drafts(sched.decodes, sched.n_decode_steps)
        ) is not None:
            # Speculation first: when it engages it beats a burst on tokens
            # per round trip, and the pipeline below picks up whenever the
            # drafts dry out.
            outputs += self._spec_step(sched.decodes, drafts)
        elif self._pipeline_ok(sched):
            # First burst of a pipeline: dispatch only; its tokens surface
            # on the NEXT step, overlapped with the following burst.
            self._burst_seqs = list(sched.decodes)
            self._burst_n = sched.n_decode_steps
            self.pipelined_bursts_total += 1
            if sched.n_decode_steps > self.cfg.num_decode_steps:
                self.adaptive_deep_bursts_total += 1
            self.runner.burst_start(sched.decodes, sched.n_decode_steps)
        else:
            if (
                hint is not None
                and sched.decodes
                and sched.n_decode_steps > self.cfg.num_decode_steps
            ):
                self.adaptive_deep_bursts_total += 1
            bursts = self.runner.execute_decode_multi(
                sched.decodes, sched.n_decode_steps
            )
            for seq, rows in zip(sched.decodes, bursts):
                for row in rows:
                    seq.num_computed_tokens += 1
                    self._commit(seq)
                    out = self._append_token(seq, int(row[0]), lp_row=row)
                    if out is not None:
                        outputs.append(out)
                    if seq.is_finished:
                        break  # trim speculative tail of the burst
        self._sweep_retiring_slots()
        return outputs

    # -- speculative decoding (n-gram prompt lookup; engine/spec.py) ----

    def _spec_drafts(
        self, decodes, n_burst: int = 1
    ) -> "Optional[tuple[np.ndarray, np.ndarray]]":
        """Per-sequence draft tokens [B, K] for this decode batch, or None
        when speculation should not engage.

        Gating is PER ROW where possible: only greedy rows get drafts;
        sampled (temperature>0) rows ride the same verify step and have
        position 0 put through the full sampling pipeline — identical to a
        plain decode step for them. Batch-level bail-outs remain for
        penalties (accepted tokens would change the counts mid-step) and
        logprobs (verify returns no packed logprob rows), plus too few
        draft-carrying rows to beat a plain burst."""
        K = self.cfg.speculative_ngram
        if not K or self.cfg.async_decode or not decodes:
            return None
        from .spec import propose_ngram

        for s in decodes:
            if s.sampling.has_penalties or s.sampling.logprobs is not None:
                return None
        drafts = np.zeros((len(decodes), K), np.int32)
        lens = np.zeros(len(decodes), np.int32)
        for i, s in enumerate(decodes):
            if not s.sampling.greedy or s.sampling.guided_choice:
                continue  # rides along; sampled/masked at position 0 only
            if s.num_tokens + K > self.cfg.max_model_len:
                continue  # verify writes would run past the last page
            d = propose_ngram(
                self._spec_token_arr(s), K,
                self.cfg.ngram_min, self.cfg.ngram_max,
                lookback=self.cfg.ngram_lookback,
            )
            if d:
                drafts[i, : len(d)] = d
                lens[i] = len(d)
        # A verify pass costs ~one device round trip; worth it only when
        # enough rows carry drafts — AND when its best case (K+1 tokens per
        # draft row, 1 per other row) beats the n-step burst it replaces
        # (num_decode_steps>1 exists for dispatch-latency-bound setups; a
        # verify pass that yields fewer tokens per round trip would regress
        # exactly there).
        B = len(decodes)
        hits = int(np.count_nonzero(lens))
        if hits * 2 < B or hits * (K + 1) + (B - hits) < n_burst * B:
            return None
        return drafts, lens

    @staticmethod
    def _spec_token_arr(s) -> "np.ndarray":
        """Per-sequence token-id array for the n-gram scan, grown
        incrementally (tokens are append-only) — rebuilding the full list
        and array every decode step was O(context) host work per sequence."""
        total = s.num_tokens
        buf = getattr(s, "_spec_buf", None)
        n = getattr(s, "_spec_buf_n", 0)
        if buf is None or n > total:
            buf = np.empty(max(total * 2, 256), np.int64)
            n = 0
        elif buf.shape[0] < total:
            grown = np.empty(max(total * 2, buf.shape[0] * 2), np.int64)
            grown[:n] = buf[:n]
            buf = grown
        P = s.num_prompt_tokens
        prompt, output = s.prompt_token_ids, s.output_token_ids
        for idx in range(n, total):
            buf[idx] = prompt[idx] if idx < P else output[idx - P]
        s._spec_buf, s._spec_buf_n = buf, total
        return buf[:total]

    def _spec_step(self, decodes, spec) -> List[RequestOutput]:
        """One verify pass: commit each row's accepted draft prefix plus the
        model's own next token (exactly the greedy output)."""
        from .spec import count_accepted

        drafts, lens = spec
        rows, sampled0 = self.runner.execute_spec_verify(decodes, drafts)
        outputs: List[RequestOutput] = []
        for i, seq in enumerate(decodes):
            if lens[i] == 0:
                # Draftless (or sampled) row: position 0 went through the
                # full sampling pipeline — exactly one plain decode step.
                emitted = [int(sampled0[i])]
            else:
                draft = [int(t) for t in drafts[i][: lens[i]]]
                a = count_accepted(draft, rows[i])
                # Clamp: never emit past max_model_len.
                a = min(a, self.cfg.max_model_len - seq.num_tokens - 1)
                self.spec_proposed_total += len(draft)
                self.spec_accepted_total += a
                emitted = draft[:a] + [int(rows[i][a])]
            for tok in emitted:
                seq.num_computed_tokens += 1
                self._commit(seq)
                out = self._append_token(seq, tok)
                if out is not None:
                    outputs.append(out)
                if seq.is_finished:
                    break
        return outputs

    def _finish_expired(self, expired) -> List[RequestOutput]:
        """Surface scheduler deadline sheds to their waiting clients: the
        sequence is already finished (pages released, finish_reason
        "deadline"); emit the terminal RequestOutput so the HTTP layer can
        answer 504 (non-streaming) or close the stream (streaming)."""
        outs: List[RequestOutput] = []
        for seq in expired:
            if seq.request_id not in self._seqs:
                continue
            self._seqs.pop(seq.request_id, None)
            self._detok.pop(seq.request_id, None)
            outs.append(
                RequestOutput(
                    request_id=seq.request_id,
                    finished=True,
                    finish_reason="deadline",
                    num_prompt_tokens=seq.num_prompt_tokens,
                    num_output_tokens=len(seq.output_token_ids),
                    num_cached_prompt_tokens=seq.num_cached_prompt_tokens,
                    # Shed work still consumed device time: bill it.
                    cost=self._finalize_cost(seq),
                )
            )
        return outs

    def _process_prefill_rows(self, prefills, rows) -> List[RequestOutput]:
        """``rows is None`` for dispatch-only steps (no chunk completed a
        fresh prompt, so there is no sampled token to read)."""
        outputs: List[RequestOutput] = []
        for i, item in enumerate(prefills):
            seq = item.seq
            seq.num_computed_tokens = item.end
            self._commit(seq)
            # Streamed disagg handoff: this chunk's freshly committed
            # pages go out NOW, overlapped with the next chunk's compute
            # (docs/disagg.md) — not serially after the prefill response.
            self._stream_publish(
                seq, prefill_complete=item.end == seq.num_prompt_tokens
            )
            # Sample only when this chunk completes a *fresh* prompt;
            # recompute chunks (post-preemption) must not re-emit tokens.
            if item.end == seq.num_prompt_tokens and not seq.output_token_ids:
                assert rows is not None, "completing chunk needs its token"
                out = self._append_token(seq, int(rows[i][0]), lp_row=rows[i])
                if out is not None:
                    outputs.append(out)
        return outputs

    def _stream_publish(self, seq: Sequence, prefill_complete: bool) -> None:
        """Hand ``seq``'s newly committed pages to the handoff publisher
        (step-thread cost: device→host download + a deque append; all DCN
        runs on the publisher's worker thread). The completion marker —
        the decode side's "last block" signal — carries the full-block
        count of the prompt, which is exactly what the consumer's
        match_prefix can adopt."""
        pub = self.kv_publisher
        transfer = seq.kv_transfer
        if pub is None or not transfer:
            return
        if transfer.get("role") == "consumer":
            # The decode leg on a kv_role="both" engine: its prompt blocks
            # were just PREFETCHED from the store — re-publishing them
            # would re-download every page on the step thread and break
            # the one-copy-per-page contract.
            return
        rid = transfer.get("request_id")
        if not rid:
            return
        n = seq._committed_blocks
        if n > seq.kv_published_cursor:
            pages = []
            for i in range(seq.kv_published_cursor, n):
                k, v = self.runner.download_page(seq.block_ids[i])
                pages.append((seq.block_hashes[i], k, v))
            pub.publish(rid, pages)
            self.kv_published_blocks_total += len(pages)
            seq.kv_published_cursor = n
        if prefill_complete and not transfer.get("_completed"):
            transfer["_completed"] = True
            pub.complete(
                rid, seq.num_prompt_tokens // self.cfg.block_size
            )

    # -- pipelined decode internals ------------------------------------

    def _pipeline_ok(self, sched) -> bool:
        """May this pass start a pipelined burst? ``async_decode`` pipelines
        unconditionally (batch serving); ``overlap_decode`` — the default —
        engages only when the three arrival-safety rules certify that no
        arrival can be delayed (`_arrival_safe`), so live-traffic TTFT
        never pays for the overlap. Guided rows are excluded (their
        allowed-token mask is rebuilt per token host-side); penalty rows
        ride — their state lives in multi_step's scan carry."""
        if not sched.decodes:
            return False
        if any(s.sampling.guided_choice for s in sched.decodes):
            return False
        if self.cfg.async_decode:
            return True
        # Speculation and overlap are alternative round-trip amortizers;
        # when n-gram speculation is configured it wins outright (more
        # tokens per trip for greedy rows) and overlap stays out of its
        # way — deterministically, not by racing the quiet timer.
        if self.cfg.speculative_ngram:
            return False
        return self.cfg.overlap_decode and self._arrival_safe()

    def _can_continue_burst(self, sched) -> bool:
        """The in-flight burst may chain iff nothing about the step shape
        changed and the NEXT burst's writes are provably covered."""
        alive = [s for s in self._burst_seqs if not s.is_finished]
        n = self._burst_n
        return (
            not sched.prefills
            and not sched.blocked_on_locked
            and self.scheduler.num_waiting == 0  # drain so admission can run
            and alive
            and sched.decodes == alive
            and sched.n_decode_steps == n
            and self.runner.burst_width_stable(self._burst_seqs)
            # The continuation writes up to num_tokens + 2n (host view lags
            # one burst); past max_model_len its pages would not exist.
            and all(
                s.num_tokens + 2 * n <= self.cfg.max_model_len for s in alive
            )
        )

    def _process_burst_rows(self, rows) -> List[RequestOutput]:
        """Apply one fetched burst's tokens. Rows align with
        ``self._burst_seqs`` (original membership order); rows of members
        that finished earlier are speculative garbage and are skipped.
        While another burst is still in flight, page releases and dedup
        swaps are deferred — the device writes through these page ids."""
        outputs: List[RequestOutput] = []
        inflight = self.runner.burst_in_flight
        for seq, seq_rows in zip(self._burst_seqs, rows):
            if seq.is_finished:
                continue
            for row in seq_rows:
                seq.num_computed_tokens += 1
                self._commit(seq, allow_swap=not inflight)
                out = self._append_token(seq, int(row[0]), lp_row=row)
                if out is not None:
                    outputs.append(out)
                if seq.is_finished:
                    break  # trim speculative tail of the burst
        if not inflight:
            self._burst_seqs = []
            self._burst_n = 0
        return outputs

    def _release_burst_deferred(self) -> None:
        for seq in self._burst_deferred:
            self.allocator.release_all(seq.block_ids)
            seq.block_ids = []
        self._burst_deferred = []

    # Controller-registration hygiene: chunk claims older than the TTL (or
    # beyond the cap) are dropped so KV-aware routing doesn't chase KV that
    # LRU eviction already reclaimed, and the dict can't grow unboundedly.
    CHUNK_CLAIM_TTL = 20 * 60.0
    CHUNK_CLAIM_CAP = 200_000

    def _commit(self, seq: Sequence, allow_swap: bool = True) -> None:
        seq.commit_full_blocks(self.allocator, allow_swap=allow_swap)
        now = time.time()
        for h in seq.commit_full_chunks(CHUNK_TOKENS):
            self.resident_chunk_hashes.pop(h, None)  # refresh insertion order
            self.resident_chunk_hashes[h] = now
        if len(self.resident_chunk_hashes) > self.CHUNK_CLAIM_CAP:
            self._prune_chunk_claims(now)

    def _prune_chunk_claims(self, now: float) -> None:
        cutoff = now - self.CHUNK_CLAIM_TTL
        fresh = {h: t for h, t in self.resident_chunk_hashes.items() if t >= cutoff}
        if len(fresh) > self.CHUNK_CLAIM_CAP:
            # insertion order == recency (refreshed on re-commit): keep newest
            fresh = dict(list(fresh.items())[-self.CHUNK_CLAIM_CAP :])
        self.resident_chunk_hashes = fresh

    def _push_kv_to_remote(self, seq: Sequence) -> int:
        """Producer-side finish push: ship whatever committed pages the
        streamed publisher has NOT already sent (``kv_published_cursor``)
        in one batched round trip — the legacy role-based disagg path for
        requests without ``kv_transfer_params``, and the tail (decode-
        produced blocks) for streamed ones. One copy per page, ever."""
        remote = getattr(self.allocator, "remote", None)
        if remote is None:
            return 0
        start = seq.kv_published_cursor
        if seq.kv_transfer and seq.kv_transfer.get("role") == "consumer":
            # A consumer leg's cached prompt prefix CAME from the store
            # (the prefetch) — only blocks computed here are new.
            start = max(
                start, seq.num_cached_prompt_tokens // self.cfg.block_size
            )
        pages = [
            (h, *self.runner.download_page(blk))
            for blk, h in zip(seq.block_ids[start:], seq.block_hashes[start:])
        ]
        if not pages or not remote.put_blocks(pages):
            return 0
        seq.kv_published_cursor = start + len(pages)
        return len(pages)

    # ------------------------------------------------------------------
    # Token bookkeeping
    # ------------------------------------------------------------------

    def _append_token(
        self, seq: Sequence, token: int, lp_row=None
    ) -> Optional[RequestOutput]:
        sp = seq.sampling
        seq.output_token_ids.append(token)
        self.generation_tokens_total += 1
        now = time.monotonic()  # same clock as arrival_time (sequence.py)
        if seq.first_token_time is None:
            seq.first_token_time = now

        finish_reason: Optional[str] = None
        is_stop_token = False
        if not sp.ignore_eos and token in self.model_cfg.eos_token_ids:
            finish_reason = "stop"
            is_stop_token = True
        elif token in sp.stop_token_ids:
            finish_reason = "stop"
            is_stop_token = True
        elif sp.guided_done(seq.output_token_ids):
            finish_reason = "stop"  # output IS one of the guided choices
        elif len(seq.output_token_ids) >= sp.max_tokens:
            finish_reason = "length"
        elif seq.num_tokens >= self.cfg.max_model_len:
            finish_reason = "length"

        # Incremental detokenization: decode only a sliding window of recent
        # tokens (O(window) per step, not O(total)); hold back text while the
        # window ends in a partial multi-byte/multi-token character.
        delta = "" if is_stop_token else self._detok_delta(seq)
        st = self._detok[seq.request_id]
        if delta and sp.stop_strings():
            emitted = st["emitted"]
            full = emitted + delta
            for stop_s in sp.stop_strings():
                idx = full.find(stop_s, max(len(emitted) - len(stop_s), 0))
                if idx >= 0:
                    delta = full[:idx][len(emitted):]
                    finish_reason = "stop"
                    break
        st["emitted"] += delta

        logprobs_entry = None
        if (
            sp.logprobs is not None
            and lp_row is not None
            and lp_row.shape[-1] > 1  # width-1 rows: compiled without logprobs
        ):
            from ..ops.sampling import unpack_sampled

            _, chosen, top_lps, top_ids = unpack_sampled(lp_row)
            k = min(int(sp.logprobs), top_ids.shape[-1])
            logprobs_entry = {
                "token_id": token,
                "logprob": float(chosen),
                "top": [
                    (int(top_ids[j]), float(top_lps[j])) for j in range(k)
                ],
            }

        scheduled = seq.first_scheduled_time
        out = RequestOutput(
            request_id=seq.request_id,
            text_delta=delta,
            new_token_ids=[token],
            num_prompt_tokens=seq.num_prompt_tokens,
            num_output_tokens=len(seq.output_token_ids),
            num_cached_prompt_tokens=seq.num_cached_prompt_tokens,
            ttft=(seq.first_token_time - seq.arrival_time),
            queue_time=(
                scheduled - seq.arrival_time if scheduled is not None else None
            ),
            prefill_time=(
                seq.first_token_time - scheduled
                if scheduled is not None else None
            ),
            logprobs=[logprobs_entry] if logprobs_entry else None,
        )
        if finish_reason is not None:
            out.decode_time = now - seq.first_token_time
            # Cost account closes while the pages are still owned (the
            # scheduler releases them just below).
            out.cost = self._finalize_cost(seq)
            if self.cfg.kv_role in ("producer", "both"):
                sent = self._push_kv_to_remote(seq)
                if sent:
                    logger.debug(
                        "disagg: pushed %d KV pages for %s", sent, seq.request_id
                    )
            if self.runner.burst_in_flight and seq in self._burst_seqs:
                # The in-flight burst still writes through this sequence's
                # pages: detach now, release at drain.
                self.scheduler.detach(seq.request_id, finish_reason)
                self._burst_deferred.append(seq)
            else:
                self.scheduler.finish(seq, finish_reason)
            out.finished = True
            out.finish_reason = finish_reason
            self._seqs.pop(seq.request_id, None)
            self._detok.pop(seq.request_id, None)
        return out

    def _detok_delta(self, seq: Sequence) -> str:
        """vLLM-style incremental detokenization over a bounded window."""
        st = self._detok[seq.request_id]
        ids = seq.output_token_ids
        prefix, read = int(st["prefix"]), int(st["read"])  # type: ignore[arg-type]
        prefix_text = self.tokenizer.decode(ids[prefix:read])
        new_text = self.tokenizer.decode(ids[prefix:])
        if new_text.endswith("�") and len(ids) - read < 16:
            return ""  # partial character: hold until it completes (bounded —
            # genuinely invalid byte runs are force-emitted after 16 tokens)
        delta = new_text[len(prefix_text):]
        st["prefix"], st["read"] = read, len(ids)
        return delta

    # ------------------------------------------------------------------
    # Convenience (tests / bench)
    # ------------------------------------------------------------------

    def generate(
        self,
        prompts: Union[List[str], List[List[int]]],
        sampling: Optional[SamplingParams] = None,
    ) -> List[Dict[str, object]]:
        """Run prompts to completion; returns list of dicts with text/ids."""
        results: Dict[str, Dict[str, object]] = {}
        for i, p in enumerate(prompts):
            rid = f"gen-{i}"
            kwargs = {"prompt_token_ids": p} if isinstance(p, list) else {"prompt": p}
            self.add_request(rid, sampling=sampling, **kwargs)
            results[rid] = {"text": "", "token_ids": [], "finish_reason": None}
        while self.has_work():
            for out in self.step():
                r = results[out.request_id]
                r["text"] = str(r["text"]) + out.text_delta
                r["token_ids"].extend(out.new_token_ids)  # type: ignore[union-attr]
                if out.finished:
                    r["finish_reason"] = out.finish_reason
        return [results[f"gen-{i}"] for i in range(len(prompts))]

    # ------------------------------------------------------------------
    # Metrics snapshot for the server layer
    # ------------------------------------------------------------------

    def stats(self) -> Dict[str, float]:
        out = {
            "num_requests_running": float(self.scheduler.num_running),
            "num_requests_waiting": float(self.scheduler.num_waiting),
            "num_requests_swapped": float(self.scheduler.num_swapped),
            "num_preemptions_total": float(self.num_preempted_total),
            "prompt_tokens_total": float(self.prompt_tokens_total),
            "generation_tokens_total": float(self.generation_tokens_total),
            "kv_cache_usage_perc": self.allocator.usage,
            "prefix_cache_hit_rate": self.allocator.hit_rate,
            "prefix_cache_hits_total": float(self.allocator.hit_tokens),
            "prefix_cache_queries_total": float(self.allocator.query_tokens),
            "deadline_sheds_queued_total": float(
                self.scheduler.deadline_sheds_queued
            ),
            "deadline_sheds_running_total": float(
                self.scheduler.deadline_sheds_running
            ),
            # Cost-attribution audit scalar (docs/observability.md "Cost
            # attribution"): live-traffic device-busy wall; finished
            # request costs must sum to >= 90% of this.
            "device_busy_seconds_total": ENGINE_TELEMETRY.device_busy_seconds(),
        }
        if self.cfg.tenant_fairness:
            ages = self.scheduler.queue_age_by_tier()
            out["tenant_queue_age_interactive"] = ages["interactive"]
            out["tenant_queue_age_batch"] = ages["batch"]
            out["tenant_batch_preemptions_total"] = float(
                self.scheduler.batch_preemptions
            )
        if self.cfg.speculative_ngram:
            out["spec_decode_num_draft_tokens_total"] = float(
                self.spec_proposed_total
            )
            out["spec_decode_num_accepted_tokens_total"] = float(
                self.spec_accepted_total
            )
        if self.cfg.adaptive_decode_steps:
            out["adaptive_deep_bursts_total"] = float(
                self.adaptive_deep_bursts_total
            )
        if self.cfg.async_decode or self.cfg.overlap_decode:
            out["pipelined_bursts_total"] = float(self.pipelined_bursts_total)
        # Tiering KPIs (present when the LMCache-analogue layer is on).
        for attr in ("host_hit_blocks", "remote_hit_blocks", "spilled_blocks"):
            if hasattr(self.allocator, attr):
                out[f"kv_offload_{attr}"] = float(getattr(self.allocator, attr))
        # Streamed disagg handoff KPIs (docs/disagg.md).
        if self.kv_publisher is not None or self.kv_prefetcher is not None:
            out["kv_published_blocks_total"] = float(
                self.kv_published_blocks_total
            )
        if self.kv_publisher is not None:
            out["kv_publish_failures_total"] = float(
                self.kv_publisher.publish_failures
            )
        if self.kv_prefetcher is not None:
            out["kv_prefetched_blocks_total"] = float(
                self.kv_prefetcher.prefetched_blocks
            )
            out["kv_transfer_fallbacks_total"] = float(
                self.kv_prefetcher.fallbacks
            )
        # Remote-tier integrity/replication audit (docs/kvserver.md):
        # digest-verification failures, replica read-repairs and GET
        # retries, counted in the KV client (plain or sharded).
        remote_client = getattr(self.allocator, "remote", None)
        if remote_client is not None and hasattr(remote_client, "counters"):
            if hasattr(remote_client, "refresh_counters"):
                remote_client.refresh_counters()
            counters = remote_client.counters
            out["kv_integrity_failures_total"] = float(
                counters.get("integrity_failures", 0)
            )
            out["kv_read_repairs_total"] = float(
                counters.get("read_repairs", 0)
            )
            out["kv_remote_retries_total"] = float(
                counters.get("retries", 0)
            )
        if self.swapper is not None:
            out["kv_swap_out_total"] = float(self.swapper.swap_out_total)
            out["kv_swap_in_total"] = float(self.swapper.swap_in_total)
            out["kv_swap_tail_pages_total"] = float(
                self.swapper.tail_pages_moved
            )
            out["kv_swap_fallback_recompute_total"] = float(
                self.swapper.fallback_recompute_total
            )
            out["kv_swap_stash_blocks"] = float(self.swapper.stash_blocks)
        return out
