"""Async façade over :class:`LLMEngine` for the aiohttp server.

The device step loop runs on a dedicated thread (a jitted TPU step blocks);
request submission and streaming consumption happen on the asyncio loop.
Outputs cross threads via ``loop.call_soon_threadsafe`` into per-request
queues — the same engine-loop/frontend split vLLM's AsyncLLMEngine gives the
reference stack, minus multiprocessing.

Sleep/wake (reference `/sleep`, `/wake_up`, tutorial 19): sleeping pauses the
step loop; level 2 additionally drops the KV cache pages to free HBM (they
are re-zeroed on wake).
"""

from __future__ import annotations

import asyncio
import threading
import time
import uuid
from typing import AsyncIterator, Dict, List, Optional, Sequence as Seq

from ..logging_utils import init_logger
from .config import EngineConfig
from .engine import LLMEngine, RequestOutput
from .sequence import SamplingParams

logger = init_logger(__name__)

_SENTINEL = object()


class AsyncLLMEngine:
    def __init__(self, cfg: EngineConfig, mesh=None):
        self.engine = LLMEngine(cfg, mesh)
        self._lock = threading.Lock()  # guards scheduler/engine mutation
        self._work = threading.Event()
        self._stop = False
        self._sleeping = False
        self._sleep_level = 0
        self._draining = False
        self._queues: Dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        # Submission/abort mailboxes drained by the step thread, so the
        # asyncio loop never contends for the engine lock (a jitted step can
        # hold it for hundreds of ms — taking it on the loop would stall
        # every connection, including /health).
        self._submit_lock = threading.Lock()
        self._pending_adds: list = []
        self._pending_aborts: list = []
        # Step-loop health for the composite /health check.
        self.last_step_time = time.time()
        self.step_error: Optional[str] = None
        # Warmup precompilation gate (engine/precompile.py): the step
        # thread compiles the shape-bucket lattice before its first step;
        # /ready reports 503 and router discovery keeps the engine
        # unroutable until this flips. Requests submitted meanwhile queue
        # in the mailboxes — /health stays green (liveness != readiness).
        self._warming = cfg.warmup != "off"
        self.warmup_error: Optional[str] = None

    # -- lifecycle --------------------------------------------------------

    def start(self, loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        self._loop = loop or asyncio.get_event_loop()
        self._thread = threading.Thread(
            target=self._run, name="engine-step-loop", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop = True
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if getattr(self.engine, "kv_publisher", None) is not None:
            self.engine.kv_publisher.shutdown()

    def is_healthy(self) -> bool:
        return (
            self.step_error is None
            and self._thread is not None
            and self._thread.is_alive()
        )

    @property
    def warming(self) -> bool:
        """True while the startup precompile pass is still running."""
        return self._warming

    @property
    def ready(self) -> bool:
        """Readiness (the /ready contract): healthy, warmed, awake, and
        accepting work. Distinct from liveness — a warming, sleeping, or
        draining engine is alive but must receive no new traffic."""
        return (
            self.is_healthy()
            and not self._warming
            and not self._sleeping
            and not self._draining
        )

    # -- sleep / wake -----------------------------------------------------

    @property
    def sleeping(self) -> bool:
        return self._sleeping

    def sleep(self, level: int = 1) -> None:
        self._sleeping = True
        self._sleep_level = level
        if level >= 2:
            with self._lock:
                # Dropping HBM pages invalidates every block the prefix maps
                # point at — clear them (and abort in-flight work) or later
                # prompts would adopt zeroed pages as cache hits.
                self.engine.clear_kv_state()
                self.engine.runner.drop_kv_cache()
            self._sentinel_all()
        logger.info("engine sleeping (level %d)", level)

    def wake_up(self) -> None:
        if self._sleep_level >= 2:
            with self._lock:
                self.engine.runner.restore_kv_cache()
        self._sleeping = False
        self._sleep_level = 0
        self._work.set()
        logger.info("engine awake")

    # -- drain ------------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Stop accepting new sequences; in-flight ones keep decoding to
        completion (the step loop is untouched — only the HTTP admission
        gate closes). Router-side discovery marks draining engines
        unroutable; /undrain reverses."""
        self._draining = True
        logger.info("engine draining (in-flight sequences will finish)")

    def undrain(self) -> None:
        self._draining = False
        logger.info("engine accepting new sequences again")

    def num_inflight(self) -> int:
        # Swapped (preempted) sequences are still pending work — a drain
        # that ignored them would let preStop complete with generations
        # parked mid-flight.
        stats = self.engine.stats()
        return int(
            stats.get("num_requests_running", 0)
            + stats.get("num_requests_waiting", 0)
            + stats.get("num_requests_swapped", 0)
        )

    # -- submission -------------------------------------------------------

    async def generate(
        self,
        prompt: Optional[str] = None,
        prompt_token_ids: Optional[Seq[int]] = None,
        sampling: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        lora_name: Optional[str] = None,
        deadline: Optional[float] = None,
        tenant: Optional[str] = None,
        tenant_class: Optional[str] = None,
        kv_transfer: Optional[dict] = None,
    ) -> AsyncIterator[RequestOutput]:
        if self.step_error is not None:
            raise RuntimeError(f"engine is failed: {self.step_error}")
        rid = request_id or f"req-{uuid.uuid4().hex[:16]}"
        queue: asyncio.Queue = asyncio.Queue()
        self._queues[rid] = queue
        finished = False
        try:
            with self._submit_lock:
                self._pending_adds.append(
                    (
                        rid,
                        dict(
                            prompt=prompt,
                            prompt_token_ids=prompt_token_ids,
                            sampling=sampling,
                            # Monotonic, matching Sequence queue/TTFT
                            # bookkeeping and deadline shedding.
                            arrival_time=time.monotonic(),
                            lora_name=lora_name,
                            deadline=deadline,
                            tenant=tenant,
                            tenant_class=tenant_class,
                            kv_transfer=kv_transfer,
                        ),
                    )
                )
            self._work.set()
            while True:
                item = await queue.get()
                if item is _SENTINEL:
                    break
                if isinstance(item, Exception):
                    finished = True  # never admitted: nothing to reclaim
                    raise item
                yield item
                if item.finished:
                    finished = True
                    break
        finally:
            self._queues.pop(rid, None)
            if not finished:  # client went away mid-stream: reclaim pages
                with self._submit_lock:
                    self._pending_aborts.append(rid)
                self._work.set()

    async def abort(self, request_id: str) -> bool:
        with self._submit_lock:
            self._pending_aborts.append(request_id)
        self._work.set()
        q = self._queues.get(request_id)
        if q is not None:
            q.put_nowait(_SENTINEL)
        return True

    # -- engine thread ----------------------------------------------------

    def _drain_mailboxes(self) -> None:
        with self._submit_lock:
            adds, self._pending_adds = self._pending_adds, []
            aborts, self._pending_aborts = self._pending_aborts, []
        with self._lock:
            for rid in aborts:
                self.engine.abort_request(rid)
            for rid, kwargs in adds:
                if rid in self._queues:  # skip if the client already left
                    try:
                        self.engine.add_request(rid, **kwargs)
                    except Exception as e:  # noqa: BLE001 — per-request error
                        logger.warning("add_request %s failed: %s", rid, e)
                        # Surface the error to the waiting client (HTTP 400
                        # for ValueError) instead of an empty 200 stream.
                        self._error_one(rid, e)

    def _sentinel_one(self, rid: str) -> None:
        if self._loop is None:
            return
        self._loop.call_soon_threadsafe(
            lambda: self._queues.get(rid) and self._queues[rid].put_nowait(_SENTINEL)
        )

    def _error_one(self, rid: str, exc: Exception) -> None:
        if self._loop is None:
            return
        self._loop.call_soon_threadsafe(
            lambda: self._queues.get(rid) and self._queues[rid].put_nowait(exc)
        )

    def _run(self) -> None:
        logger.info("engine step loop started")
        if self._warming:
            # Precompile on the step thread: the asyncio loop keeps
            # serving /health and /ready while the lattice compiles, and
            # no device step can interleave with a warmup dispatch.
            try:
                self.engine.precompile()
            except Exception as e:  # noqa: BLE001 — serve anyway: the
                # lattice shapes that did compile are warm, the rest
                # compile on demand (the pre-warmup behavior); readiness
                # still flips so the pod is not wedged forever.
                logger.exception("warmup precompile failed")
                self.warmup_error = str(e)
            self._warming = False
            self._work.set()
        while not self._stop:
            self._drain_mailboxes()
            if self._sleeping or not self.engine.has_work():
                self._work.wait(timeout=0.05)
                self._work.clear()
                self.last_step_time = time.time()
                continue
            try:
                with self._lock:
                    outputs = self.engine.step()
                self.last_step_time = time.time()
            except Exception as e:  # noqa: BLE001 — surface via /health
                logger.exception("engine step failed")
                # Post-mortem BEFORE teardown: freeze the flight ring with
                # the failing step still at its tail (served at
                # GET /debug/flight for as long as the pod lives, and in
                # the log for after it doesn't).
                try:
                    snap = self.engine.flight.snapshot(
                        "fatal", detail={"error": str(e)}
                    )
                    tail = snap["records"][-3:]
                    logger.error(
                        "flight snapshot (fatal): %d steps recorded, tail=%s",
                        snap["total_steps"], tail,
                    )
                except Exception:  # noqa: BLE001 — never mask the real error
                    pass
                self.step_error = str(e)
                with self._lock:
                    # Drain the scheduler so the loop doesn't spin hot on the
                    # same failure; queued requests get sentinels (callers see
                    # truncated streams) and new submissions are refused.
                    self.engine.abort_all_requests()
                self._sentinel_all()
                continue
            if outputs and self._loop is not None:
                self._loop.call_soon_threadsafe(self._dispatch, outputs)

    def _dispatch(self, outputs: List[RequestOutput]) -> None:
        for out in outputs:
            q = self._queues.get(out.request_id)
            if q is not None:
                q.put_nowait(out)

    def _sentinel_all(self) -> None:
        if self._loop is None:
            return

        def _do():
            for q in self._queues.values():
                q.put_nowait(_SENTINEL)

        self._loop.call_soon_threadsafe(_do)
