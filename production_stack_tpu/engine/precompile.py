"""Ahead-of-time shape-bucket precompilation + persistent compile cache.

The runner pads every device step into a small set of power-of-two bucket
shapes (docs/engine.md "Static-shape discipline"), which makes the full
set of executables live traffic can ever demand *enumerable from config
alone*. This module enumerates that lattice — prefill (rows x chunk),
decode rows, decode bursts, spec-verify, encode — and drives every jitted
dispatch in :mod:`runner` through it with all-padding dummy batches at
warmup, before the server's ``/ready`` flips. The result is the
prevention half of PR 5's detection machinery: after a ``full`` warmup a
live-traffic XLA recompile (the BENCH_r05 120 s p99) is impossible for
any shape the lattice covers, and ``pst_engine_compile_total`` staying
flat under traffic proves it.

Underneath sits a **persistent JAX compilation cache**: executables are
serialized to ``compile_cache_dir/<key>`` where ``<key>`` hashes model +
mesh + dtypes + code version, so a warm restart (or a rolling-deploy
replacement pod on the same PVC/hostPath mount) deserializes instead of
rebuilding — ``pst_engine_compile_cache_{hits,misses}_total`` count the
outcomes via jax's monitoring events, and
``pst_engine_startup_seconds{phase="precompile"}`` shrinks accordingly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import List, Optional

from .. import __version__
from ..logging_utils import init_logger
from ..obs.engine_telemetry import ENGINE_TELEMETRY
from .config import EngineConfig

logger = init_logger(__name__)

# Kind walk order when a bucket budget truncates the lattice: decode
# shapes serve every live token, prefill shapes gate TTFT, bursts/spec are
# throughput paths, encode only serves /v1/embeddings.
_KIND_RANK = {
    "decode": 0,
    "decode_burst": 1,
    "prefill": 2,
    "spec_verify": 3,
    "encode": 4,
}


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One compiled-executable-worth of padded shape + static jit flags."""

    kind: str  # decode | decode_burst | prefill | spec_verify | encode
    rows: int = 0  # padded batch rows (decode/prefill/spec)
    tokens: int = 0  # prefill chunk bucket / encode length / spec K
    width: int = 0  # block-table width bucket
    n_steps: int = 0  # burst depth (decode_burst)
    want_lp: bool = False
    greedy: bool = True
    # Penalty-bearing multi-step variant (decode_burst only): the dense
    # [rows, V] penalty_seen/counts state keeps these shapes derivable
    # from config alone, so — unlike the pow2-length id arrays of the
    # single-step path — they ARE enumerable and warmed.
    penalized: bool = False

    @property
    def label(self) -> str:
        """The telemetry ``shape_bucket`` label this bucket compiles."""
        if self.kind == "decode":
            return f"b{self.rows}"
        if self.kind == "decode_burst":
            return f"b{self.rows}xn{self.n_steps}"
        if self.kind == "prefill":
            return f"b{self.rows}xt{self.tokens}"
        if self.kind == "spec_verify":
            return f"b{self.rows}xk{self.tokens}"
        return f"t{self.tokens}"

    def sort_key(self) -> tuple:
        # Greedy-no-logprobs-unpenalized first (the overwhelmingly common
        # flag set), then ascending size so coverage climbs fastest per
        # second.
        return (
            _KIND_RANK[self.kind],
            (self.want_lp, not self.greedy, self.penalized),
            self.rows,
            self.n_steps,
            self.tokens,
            self.width,
        )


def _pow2_buckets(n: int) -> List[int]:
    """Every power-of-two bucket a real count in 1..n can pad into."""
    out, b = [], 1
    while True:
        out.append(b)
        if b >= n:
            return out
        b <<= 1


def _pow2(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def decode_row_buckets(cfg: EngineConfig) -> List[int]:
    """Mirror of ``ModelRunner._row_bucket`` over all batch sizes."""
    floor = max(cfg.data_parallel_size, cfg.min_decode_bucket, 1)
    return sorted({max(p, floor) for p in _pow2_buckets(cfg.max_num_seqs)})


def table_width_buckets(cfg: EngineConfig) -> List[int]:
    """Mirror of ``ModelRunner._table_bucket`` over all sequence lengths."""
    from .runner import _MIN_TABLE_BUCKET

    max_table_width = -(-cfg.max_model_len // cfg.block_size)
    cap = _pow2(max_table_width)
    floor = min(_MIN_TABLE_BUCKET, cap)
    return sorted({max(p, floor) for p in _pow2_buckets(max_table_width)})


def prefill_shape_buckets(cfg: EngineConfig) -> List[tuple]:
    """Feasible (row bucket, chunk bucket) pairs under the scheduler's
    per-step token budget: a batch of B chunks with the longest C has
    B-1 (one-token rows) + C real tokens at minimum, which must fit
    ``max_prefill_tokens`` — infeasible bucket pairs can never be emitted
    and are excluded so coverage means what it says."""
    budget = cfg.max_prefill_tokens
    pairs = []
    for rb in _pow2_buckets(min(cfg.max_num_seqs, budget)):
        min_rows = 1 if rb == 1 else rb // 2 + 1
        for cb in _pow2_buckets(budget):
            min_chunk = 1 if cb == 1 else cb // 2 + 1
            if min_rows - 1 + min_chunk <= budget:
                pairs.append((rb, cb))
    return pairs


def encode_buckets(cfg: EngineConfig) -> List[int]:
    """Mirror of ``ModelRunner.encode``: pow2 length, rounded up to a
    multiple of the ring-encode shard count."""
    sp = max(cfg.sequence_parallel_size, 1)
    return sorted({-(-p // sp) * sp for p in _pow2_buckets(cfg.max_model_len)})


def burst_depths(cfg: EngineConfig) -> List[int]:
    """Burst depths the engine dispatches at steady state: the configured
    depth and the adaptive deep depth — plus, when a pipelining mode is on
    (``async_decode`` or the default arrival-gated ``overlap_decode``),
    the configured depth even at 1: the pipeline runs the multi-step
    executable (``b{B}xn{n}``) at whatever depth the scheduler emits, so
    a depth-1 engine overlaps through ``b{B}xn1`` shapes. (The
    per-sequence clamp near max_model_len can shrink n through arbitrary
    values on the last few tokens of a context-limit sequence — that long
    tail is deliberately NOT enumerated; it is one compile per engine
    lifetime at worst.)"""
    depths = {
        n
        for n in (cfg.num_decode_steps, cfg.adaptive_decode_steps)
        if n and n > 1
    }
    # Mirrors LLMEngine._pipeline_ok: overlap defers to configured n-gram
    # speculation, so spec engines never dispatch the depth-1 variant.
    if cfg.async_decode or (cfg.overlap_decode and not cfg.speculative_ngram):
        depths.add(max(cfg.num_decode_steps, 1))
    return sorted(depths)


# The (want_lp, greedy) static-flag sets warmed by default. Logprob
# variants compile distinct executables too but are rare enough in live
# traffic that doubling warmup for them is the wrong default; a logprobs
# request pays one compile, attributed by the PR 5 trace events.
_FLAG_SETS = ((False, True), (False, False))


def enumerate_lattice(cfg: EngineConfig) -> List[Bucket]:
    """The full padded shape-bucket lattice for this engine config, in
    priority order (what a bucket budget truncates from the tail)."""
    rows = decode_row_buckets(cfg)
    widths = table_width_buckets(cfg)
    buckets: List[Bucket] = []
    for lp, greedy in _FLAG_SETS:
        for r in rows:
            for w in widths:
                buckets.append(
                    Bucket("decode", rows=r, width=w, want_lp=lp, greedy=greedy)
                )
        for n in burst_depths(cfg):
            for r in rows:
                for w in widths:
                    for pen in (False, True):
                        # Penalized variants are real burst executables
                        # now (scheduler no longer clamps penalty rows to
                        # n=1): their dense [rows, V] state is config-
                        # derivable, so the first penalized request after
                        # warmup must not be a live compile.
                        buckets.append(
                            Bucket(
                                "decode_burst", rows=r, width=w, n_steps=n,
                                want_lp=lp, greedy=greedy, penalized=pen,
                            )
                        )
        for rb, cb in prefill_shape_buckets(cfg):
            for w in widths:
                buckets.append(
                    Bucket(
                        "prefill", rows=rb, tokens=cb, width=w,
                        want_lp=lp, greedy=greedy,
                    )
                )
    if cfg.speculative_ngram:
        for r in rows:
            for w in widths:
                buckets.append(
                    Bucket(
                        "spec_verify", rows=r, tokens=cfg.speculative_ngram,
                        width=w,
                    )
                )
    for t in encode_buckets(cfg):
        buckets.append(Bucket("encode", tokens=t))
    buckets.sort(key=Bucket.sort_key)
    return buckets


_LAZY_CAP = 8


def lazy_core(lattice: List[Bucket], cfg: EngineConfig) -> List[Bucket]:
    """The minimal set the very first requests hit: smallest decode
    row/table buckets (single step + configured burst) and the single-row
    full-chunk prefill shapes — dev runs come up in seconds with the cold
    paths still covered."""
    decode_rows = [b.rows for b in lattice if b.kind == "decode"]
    if not decode_rows:
        return lattice[:_LAZY_CAP]
    min_r = min(decode_rows)
    min_w = min(b.width for b in lattice if b.kind == "decode")
    max_chunk = max(
        (b.tokens for b in lattice if b.kind == "prefill"), default=0
    )
    core = [
        b
        for b in lattice
        if b.greedy
        and not b.want_lp
        and not b.penalized
        and (
            (b.kind in ("decode", "decode_burst") and b.rows == min_r
             and b.width == min_w)
            or (b.kind == "prefill" and b.rows == 1 and b.width == min_w
                and b.tokens == max_chunk)
        )
    ]
    return core[:_LAZY_CAP]


# ----------------------------------------------------------------------
# Persistent compilation cache
# ----------------------------------------------------------------------


def compile_cache_key(cfg: EngineConfig, model_cfg) -> str:
    """Stable key for the executable cache directory. Everything that
    changes the compiled programs is in here — model architecture, mesh
    shape, dtypes, quantization, kernel selection, and code versions —
    so a mismatched restart gets a fresh (empty) subdirectory instead of
    deserializing stale executables."""
    import jax

    parts = (
        f"model={model_cfg.name}",
        f"layers={model_cfg.num_layers}",
        f"kv_heads={model_cfg.num_kv_heads}",
        f"head_dim={model_cfg.head_dim}",
        f"vocab={model_cfg.vocab_size}",
        f"dtype={model_cfg.dtype}",
        f"kv_dtype={cfg.kv_cache_dtype or model_cfg.dtype}",
        f"quant={cfg.quantization}",
        f"tp={cfg.tensor_parallel_size}",
        f"dp={cfg.data_parallel_size}",
        f"pp={cfg.pipeline_parallel_size}",
        f"sp={cfg.sequence_parallel_size}",
        f"ep={cfg.expert_parallel_size}",
        f"block={cfg.block_size}",
        f"attn={cfg.attn_impl}",
        f"moe={cfg.moe_impl}",
        f"code={__version__}",
        f"jax={jax.__version__}",
    )
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


_cache_listener_installed = False


def _install_cache_listener() -> None:
    """Feed jax's compilation-cache monitoring events into the telemetry
    hit/miss counters. Process-global and idempotent."""
    global _cache_listener_installed
    if _cache_listener_installed:
        return
    try:
        from jax._src import monitoring
    except ImportError:  # pragma: no cover — future jax relayout
        logger.warning("jax monitoring unavailable; cache hit/miss "
                       "counters will stay at 0")
        return

    def _on_event(name: str, **kwargs) -> None:
        if name.endswith("/compilation_cache/cache_hits"):
            ENGINE_TELEMETRY.record_cache_event(True)
        elif name.endswith("/compilation_cache/cache_misses"):
            ENGINE_TELEMETRY.record_cache_event(False)

    monitoring.register_event_listener(_on_event)
    _cache_listener_installed = True


def configure_compile_cache(cfg: EngineConfig, model_cfg) -> Optional[str]:
    """Point jax's persistent compilation cache at the keyed directory.

    Must run before the runner wires its jits (compiles that happen
    earlier are never written back). Returns the resolved directory, or
    None when persistence is off."""
    if not cfg.compile_cache_dir:
        return None
    import jax

    path = os.path.join(
        cfg.compile_cache_dir, compile_cache_key(cfg, model_cfg)
    )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # Persist everything: the lattice is full of sub-second debug-model
    # compiles that the default 1 s / 4 KiB thresholds would silently skip
    # — and a skipped entry is a fresh compile on every restart.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    # jax initializes its cache object AT MOST ONCE per process, latching
    # "disabled" if any compile ran before the dir was configured (e.g. a
    # previous engine in this process, or an import-time jit). Reset to
    # pristine so the next compile initializes against the new directory.
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:  # pragma: no cover — private API moved; the config
        pass  # settings above still work for fresh processes
    _install_cache_listener()
    logger.info("persistent compilation cache: %s", path)
    return path


# ----------------------------------------------------------------------
# The warmup driver
# ----------------------------------------------------------------------


class Precompiler:
    """Walks the lattice through the runner's warmup dispatches, keeping
    the coverage gauge current so a half-warm engine is visible."""

    def __init__(
        self,
        runner,
        cfg: EngineConfig,
        mode: Optional[str] = None,
        bucket_budget: Optional[int] = None,
    ):
        self.runner = runner
        self.cfg = cfg
        self.mode = mode if mode is not None else cfg.warmup
        if self.mode not in ("off", "lazy", "full"):
            raise ValueError(f"unknown warmup mode {self.mode!r}")
        self.bucket_budget = (
            cfg.warmup_bucket_budget if bucket_budget is None else bucket_budget
        )

    def select(self, lattice: List[Bucket]) -> List[Bucket]:
        if self.mode == "off":
            return []
        selected = (
            lazy_core(lattice, self.cfg) if self.mode == "lazy" else lattice
        )
        if self.bucket_budget and len(selected) > self.bucket_budget:
            selected = selected[: self.bucket_budget]
        return selected

    def run(self, progress=None) -> dict:
        lattice = enumerate_lattice(self.cfg)
        total = len(lattice)
        selected = self.select(lattice)
        ENGINE_TELEMETRY.set_warmup_coverage(0, total)
        t0 = time.perf_counter()
        compiled = 0
        for bucket in selected:
            self.runner.warmup_bucket(bucket)
            compiled += 1
            ENGINE_TELEMETRY.set_warmup_coverage(compiled, total)
            if progress is not None:
                progress(compiled, total, bucket)
        seconds = time.perf_counter() - t0
        skipped = total - compiled
        if skipped:
            # No silent caps: an uncompiled bucket is a future live-traffic
            # compile — say so at startup, not in a p99 postmortem. A
            # truncated FULL warmup warns (the operator asked for complete
            # coverage and is not getting it); lazy/off skip by design and
            # log at info.
            done = set(selected)
            log = (
                logger.warning
                if self.mode == "full" and self.bucket_budget
                else logger.info
            )
            log(
                "warmup left %d/%d lattice buckets uncompiled "
                "(mode=%s, budget=%d): first skipped %s",
                skipped, total, self.mode, self.bucket_budget,
                next((b.label for b in lattice if b not in done), "-"),
            )
        logger.info(
            "precompile: %d/%d buckets in %.1fs (mode=%s)",
            compiled, total, seconds, self.mode,
        )
        return {
            "mode": self.mode,
            "buckets_total": total,
            "buckets_compiled": compiled,
            "buckets_skipped": skipped,
            "coverage": round(compiled / total, 4) if total else 1.0,
            "seconds": round(seconds, 3),
        }
