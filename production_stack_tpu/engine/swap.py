"""Live-sequence KV swap: preempt by parking KV, not by recompute.

Reference mechanism: vLLM's swap space (``--swap-space``, preemption mode
``swap``) copies a preempted sequence's entire KV to CPU RAM and back; the
reference stack leans on it (plus LMCache CPU offload,
``helm/templates/deployment-vllm-multi.yaml:301-308``) to serve more
concurrent users than accelerator memory holds.

TPU-native redesign — almost nothing moves. The engine content-addresses
every filled page (``Sequence.commit_full_blocks``), so when a sequence is
parked:

- its **committed pages stay where they are**: released to the allocator's
  reusable set they keep their content and hash addressing, serve prefix
  hits for other requests meanwhile, and — under HBM pressure — spill down
  the existing HBM→host→remote tier (``cache_tiering.TieredAllocator``),
  from which resume faults them back up;
- only the **uncommitted tail** (at most one partial page, plus pages
  reserved ahead of the write cursor) is physically downloaded into a
  host-DRAM stash.

Resume re-acquires the committed chain by hash (``acquire_resident`` —
free for pages that never left HBM), uploads the stashed tail, and decode
continues at the exact token it stopped at. If part of the chain is
unrecoverable (evicted with no lower tier), the sequence falls back to the
recompute path from the longest recovered prefix — strictly no worse than
classic recompute preemption.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..logging_utils import init_logger
from .kv_manager import BlockAllocator, NoFreeBlocksError
from .sequence import Sequence, SequenceStatus

logger = init_logger(__name__)


@dataclasses.dataclass
class _SwapRecord:
    hashes: List[int]  # committed-prefix block hashes (in order)
    # (K page, V page) per page past the committed chain, in sequence
    # order — the tail is contiguous starting at len(hashes).
    tail: List[Tuple[np.ndarray, np.ndarray]]
    num_computed_tokens: int
    num_blocks: int  # pages holding computed KV at swap-out


class KVSwapper:
    """Parks/resumes live sequences' KV. ``page_io`` is the runner adapter
    (``download_page``/``upload_page`` — the device DMA endpoints)."""

    def __init__(self, page_io, max_stash_blocks: int = 4096):
        self.page_io = page_io
        self.max_stash_blocks = max_stash_blocks
        self._stash: Dict[str, _SwapRecord] = {}
        self._stash_blocks = 0
        # KPIs (engine.stats → /metrics).
        self.swap_out_total = 0
        self.swap_in_total = 0
        self.tail_pages_moved = 0
        self.fallback_recompute_total = 0

    def __contains__(self, request_id: str) -> bool:
        return request_id in self._stash

    @property
    def stash_blocks(self) -> int:
        return self._stash_blocks

    @staticmethod
    def _tail_range(seq: Sequence, allocator: BlockAllocator) -> Tuple[int, int]:
        """(committed, used) page bounds for a swap: pages in
        [committed, used) must be physically stashed. Pages ≥ ``used`` are
        lookahead reserve holding no computed KV — resume re-reserves them
        instead of moving garbage. With prefix caching off nothing is
        hash-recoverable, so everything up to ``used`` is tail."""
        bs = allocator.block_size
        used = -(-seq.num_computed_tokens // bs)
        committed = (
            min(seq._committed_blocks, used)
            if allocator.enable_prefix_caching
            else 0
        )
        return committed, used

    def can_stash(self, seq: Sequence, allocator: BlockAllocator) -> bool:
        committed, used = self._tail_range(seq, allocator)
        return self._stash_blocks + (used - committed) <= self.max_stash_blocks

    def swap_out(self, seq: Sequence, allocator: BlockAllocator) -> None:
        """Download the uncommitted tail, release all pages, park the
        sequence. The committed prefix needs no copying — content-addressed
        pages survive release (reusable set / lower tiers)."""
        committed, used = self._tail_range(seq, allocator)
        tail: List[Tuple[np.ndarray, np.ndarray]] = []
        for i in range(committed, used):
            tail.append(self.page_io.download_page(seq.block_ids[i]))
        self._stash[seq.request_id] = _SwapRecord(
            hashes=list(seq.block_hashes[:committed]),
            tail=tail,
            num_computed_tokens=seq.num_computed_tokens,
            num_blocks=used,
        )
        self._stash_blocks += len(tail)
        allocator.release_all(seq.block_ids)
        seq.block_ids = []
        seq.status = SequenceStatus.SWAPPED
        self.swap_out_total += 1
        self.tail_pages_moved += len(tail)
        logger.debug(
            "swapped out %s: %d committed pages stay addressed, %d tail "
            "pages stashed", seq.request_id, committed, len(tail),
        )

    def swap_in(self, seq: Sequence, allocator: BlockAllocator) -> bool:
        """Resurrect a parked sequence. True → seq is RUNNING-ready with its
        full KV resident and ``num_computed_tokens`` restored. False → could
        not (no free pages): caller keeps it parked and retries later.

        An unrecoverable committed page (evicted, no lower tier) downgrades
        to recompute-from-longest-prefix: the stash is dropped, the sequence
        re-enters the classic preempted flow — correctness is unaffected.
        In that case the sequence is left WAITING with the recovered prefix
        adopted and True is returned (it is schedulable)."""
        rec = self._stash.get(seq.request_id)
        assert rec is not None, f"no swap record for {seq.request_id}"
        acquired: List[int] = []
        for h in rec.hashes:
            blk = allocator.acquire_resident(h)
            if blk is None:
                break
            acquired.append(blk)
        if len(acquired) < len(rec.hashes):
            # Part of the chain is gone. Keep what survives as an adopted
            # prefix and recompute the rest (chunked-prefill path).
            self._drop_record(seq.request_id, rec)
            self.fallback_recompute_total += 1
            seq.reset_for_recompute()
            if acquired:
                seq.adopt_cached_prefix(
                    acquired, rec.hashes[: len(acquired)]
                )
                seq.num_computed_tokens = (
                    len(acquired) * allocator.block_size
                )
            seq.status = SequenceStatus.WAITING
            logger.warning(
                "swap-in of %s lost %d/%d committed pages; recomputing "
                "from token %d", seq.request_id,
                len(rec.hashes) - len(acquired), len(rec.hashes),
                seq.num_computed_tokens,
            )
            return True
        # Allocate + upload the stashed tail.
        fresh: List[int] = []
        try:
            for _ in rec.tail:
                fresh.append(allocator.allocate())
        except NoFreeBlocksError:
            for blk in fresh:
                allocator.release(blk)
            for blk in acquired:
                allocator.release(blk)
            return False
        for (k, v), blk in zip(rec.tail, fresh):
            self.page_io.upload_page(blk, k, v)
        seq.block_ids = acquired + fresh
        seq.block_hashes = list(rec.hashes)
        seq._committed_blocks = len(rec.hashes)
        seq._last_hash = rec.hashes[-1] if rec.hashes else seq.cache_salt
        seq.num_computed_tokens = rec.num_computed_tokens
        seq.status = SequenceStatus.RUNNING
        self._drop_record(seq.request_id, rec)
        self.swap_in_total += 1
        return True

    def blocks_needed(self, seq: Sequence) -> int:
        """Worst-case fresh pages a swap-in may allocate (committed pages
        that fault up from a lower tier + the stashed tail)."""
        rec = self._stash.get(seq.request_id)
        return rec.num_blocks if rec is not None else 0

    def drop(self, request_id: str) -> None:
        """Forget a parked sequence's stash (abort/finish)."""
        rec = self._stash.pop(request_id, None)
        if rec is not None:
            self._stash_blocks -= len(rec.tail)

    def _drop_record(self, request_id: str, rec: _SwapRecord) -> None:
        self._stash.pop(request_id, None)
        self._stash_blocks -= len(rec.tail)
