"""Multi-host engine execution: primary broadcasts steps, followers mirror.

The reference runs multi-host engines as a Ray cluster — `vllm serve` on the
head, workers joined via Ray, NCCL moving tensors
(`helm/templates/ray-cluster.yaml:3-15,520,560-566`). TPU-native, a
multi-host engine is ONE jitted SPMD program over a mesh that spans hosts:
every process must enter the same XLA computation in the same order, and XLA
moves tensors over ICI/DCN. The only asymmetry is the control plane:

- **Host 0** (``is_primary()``): runs the scheduler, the HTTP server, and the
  KV bookkeeping. Before each device call, the logical batch (a dict of small
  numpy arrays) is published over the :class:`HostBridge`.
- **Other hosts**: run :func:`run_follower` — receive each step description
  and issue the identical device call on their mesh shard.

Everything device-side (params, KV pages, collectives) is already global via
the shared mesh; only step *descriptions* cross the control plane, and they
are tiny (the token ids and tables for one step).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..logging_utils import init_logger
from ..parallel.distributed import HostBridge, is_primary

logger = init_logger(__name__)


class StepPublisher:
    """Primary-side hook: mirrors every runner device call to the followers.

    Installed on the :class:`~production_stack_tpu.engine.runner.ModelRunner`
    as ``runner.publisher``; the runner calls :meth:`announce` immediately
    before each jitted dispatch, keeping all processes' XLA program order
    identical (a diverged order deadlocks the collectives — this ordering
    contract is the whole design).
    """

    def __init__(self, bridge: Optional[HostBridge] = None):
        self.bridge = bridge or HostBridge()

    def announce(self, kind: str, payload) -> None:
        self.bridge.publish((kind, payload))

    def shutdown(self) -> None:
        try:
            self.announce("shutdown", None)
        except Exception as e:  # noqa: BLE001 — best-effort at teardown
            logger.warning("follower shutdown broadcast failed: %s", e)


def run_follower(runner, bridge: Optional[HostBridge] = None) -> None:
    """Follower main loop: mirror the primary's device calls until shutdown.

    ``runner`` must be constructed identically to the primary's (same
    EngineConfig → same mesh, same seed/checkpoint → same params), which the
    deterministic construction guarantees.
    """
    import jax

    assert not is_primary(), "run_follower must not run on host 0"
    bridge = bridge or HostBridge()
    logger.info("follower loop up (process %d)", jax.process_index())
    while True:
        try:
            kind, payload = bridge.publish(None)  # blocks on host-0 broadcast
        except Exception:  # noqa: BLE001
            # Python-level broadcast failure (e.g. a payload that fails to
            # deserialize): exit so the pod restarts instead of wedging.
            # NOTE a DEAD PRIMARY does not reach this handler — the JAX
            # distributed runtime detects the lost coordinator and
            # hard-terminates the process at the C++ layer (fatal in
            # client.h), which equally gets the pod restarted; this except
            # covers the failures that stay inside Python. Traceback logged
            # so either class stays diagnosable.
            logger.error(
                "follower broadcast failed (primary lost?); exiting",
                exc_info=True,
            )
            return
        if kind == "shutdown":
            logger.info("follower shutting down")
            return
        if kind == "step":
            runner._dispatch_step(*payload)
        elif kind == "step_nofetch":
            runner._dispatch_step_nofetch(payload)
        elif kind == "multi_step":
            runner._dispatch_multi_step(*payload)
        elif kind == "encode":
            toks, length = payload
            runner._dispatch_encode(toks, length)
        elif kind == "download_page":
            runner._dispatch_download_page(int(payload))
        elif kind == "upload_page":
            blk, k_np, v_np = payload
            runner._dispatch_upload_page(int(blk), k_np, v_np)
        elif kind == "drop_kv":
            runner._dispatch_drop_kv()
        elif kind == "restore_kv":
            runner._dispatch_restore_kv()
        elif kind == "install_adapter":
            slot, arrays = payload
            runner._dispatch_install_adapter(int(slot), arrays)
        elif kind == "uninstall_adapter":
            runner._dispatch_uninstall_adapter(int(payload))
        elif kind == "burst_start":
            runner._dispatch_burst_start(*payload)
        elif kind == "burst_cont":
            tables, kv_lens = payload
            runner._dispatch_burst_continue(tables, kv_lens)
        elif kind == "spec_verify":
            runner._dispatch_spec_verify(payload)
        else:  # future-proof: unknown step kinds are fatal (order contract)
            raise RuntimeError(f"unknown multihost step kind: {kind!r}")


def make_follower_runner(cfg):
    """Build the runner exactly as the primary does (no scheduler/server)."""
    from .runner import ModelRunner

    return ModelRunner(cfg)
