"""Continuous-batching scheduler: admission, chunked prefill, preemption.

The reference's engines get this behavior from vLLM (`--enable-chunked-prefill`,
`--max-num-seqs` pass-throughs in `helm/values.yaml:71-81`); here it is native.
Each call to :meth:`Scheduler.schedule` emits one device step: either a set of
prefill chunks (token-budget bounded) or one decode batch over all running
sequences. Out-of-pages decode preempts the youngest sequence (free its pages,
recompute later) — same policy family as vLLM's recompute preemption.

Static-shape discipline: the scheduler emits *logical* work; the runner pads
each step into a small set of compiled bucket shapes, so nothing here needs to
care about XLA.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, List, Optional

from ..logging_utils import init_logger
from .kv_manager import BlockAllocator, NoFreeBlocksError
from .sequence import Sequence, SequenceStatus

logger = init_logger(__name__)


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    max_num_seqs: int = 64
    max_prefill_tokens: int = 2048  # per-step chunked-prefill token budget
    max_model_len: int = 4096
    num_decode_steps: int = 1  # decode burst length per device call
    # Bursts of page reservation per decode pass. 2 when the engine
    # pipelines bursts (the in-flight continuation writes one burst past
    # what the host has seen, so its pages must exist at dispatch time).
    decode_lookahead: int = 1
    # Extra per-sequence page reservation for speculative decoding: a verify
    # step writes KV at up to spec_tokens positions past the committed
    # length, so those pages must exist before dispatch.
    spec_tokens: int = 0
    # Fair timeslicing when more live users than HBM holds (needs a
    # swapper): after a running sequence has decoded this many tokens since
    # its last (re)admission, it may rotate out in favor of a parked or
    # waiting one. 0 = rotate only under allocation pressure.
    swap_quantum: int = 0
    # Deadline shedding: drop sequences whose end-to-end budget
    # (Sequence.deadline, monotonic) expired — queued ones before they
    # consume a prefill step, running ones between decode steps.
    deadline_shedding: bool = True
    # Tenant-aware scheduling (docs/multi-tenancy.md): admit the waiting
    # queue weighted-fair across tenants with strict tier priority
    # (interactive before batch) and preempt batch-tier sequences first
    # — swap/shed — when an interactive tenant is waiting for pages.
    # With homogeneous traffic (one tenant/tier) behavior is identical
    # to plain FIFO.
    tenant_fairness: bool = True


@dataclasses.dataclass
class PrefillItem:
    seq: Sequence
    start: int  # first token index processed this step
    end: int  # one past the last token index


@dataclasses.dataclass
class SchedulerOutput:
    prefills: List[PrefillItem] = dataclasses.field(default_factory=list)
    decodes: List[Sequence] = dataclasses.field(default_factory=list)
    preempted: List[Sequence] = dataclasses.field(default_factory=list)
    # Sequences shed this pass because their deadline expired (pages
    # already released): the engine must surface finish_reason="deadline"
    # to their waiting clients.
    expired: List[Sequence] = dataclasses.field(default_factory=list)
    n_decode_steps: int = 1
    # A locked (in-flight-burst) sequence needed pages it could not get
    # without evicting another locked sequence: the engine must drain the
    # burst and re-schedule.
    blocked_on_locked: bool = False

    @property
    def is_empty(self) -> bool:
        return not self.prefills and not self.decodes


class Scheduler:
    def __init__(
        self,
        config: SchedulerConfig,
        allocator: BlockAllocator,
        swapper=None,
    ):
        self.config = config
        self.allocator = allocator
        # Optional engine/swap.KVSwapper: preemption parks KV host-side and
        # resumes without recompute; quantum rotation timeslices more live
        # users than HBM holds.
        self.swapper = swapper
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []
        self.swapped: Deque[Sequence] = deque()
        # Monotonic admission stamp: ``waiting`` and ``swapped`` form ONE
        # logical FIFO (else rotation would free pages for a waiting request
        # only for the rotated-out sequence to reclaim them — livelock).
        # Involuntary preemption/swap keeps the original stamp (front of
        # line); voluntary rotation takes a fresh one (back of line).
        self._stamp = 0
        self._n_decode_hint: Optional[int] = None
        # (request_id, num_free) of the last head-of-line admission failure:
        # until the free-page count changes there is no point re-running the
        # prefix match every step (it is O(prompt) hashing and would skew the
        # prefix-cache hit metrics with repeated counted hits).
        self._admit_blocked: Optional[tuple] = None
        # Deadline-shed counters (engine stats → pst:deadline_shed_*).
        self.deadline_sheds_queued = 0  # shed before any prefill step
        self.deadline_sheds_running = 0  # shed between decode steps
        # Tenant QoS (docs/multi-tenancy.md): DRR credit across tenant
        # classes for waiting-queue admission order, and counters/ages
        # the server exports as pst:tenant_* metrics.
        from ..resilience.tenancy import DeficitScheduler

        self._tenant_drr = DeficitScheduler()
        self.batch_preemptions = 0  # batch seqs preempted for interactive

    # -- queue ops --------------------------------------------------------

    def prompt_fits(self, n_prompt_tokens: int) -> bool:
        """Whether a prompt (plus its first decode token) can EVER be
        scheduled in this pool. Shared by add() and the server's HTTP-layer
        400 precheck so the two cannot drift."""
        bs = self.allocator.block_size
        return (
            -(-(n_prompt_tokens + 1) // bs) <= self.allocator.num_blocks
        )

    def add(self, seq: Sequence) -> None:
        if seq.num_prompt_tokens >= self.config.max_model_len:
            raise ValueError(
                f"prompt of {seq.num_prompt_tokens} tokens exceeds "
                f"max_model_len={self.config.max_model_len}"
            )
        if not self.prompt_fits(seq.num_prompt_tokens):
            # Infeasible outright (prompt + its first decode token exceed
            # the whole pool): full-prompt admission would queue it forever,
            # and admitting it would self-preempt in a zero-progress loop.
            # Fail loudly (HTTP 400) instead. (Auto-sized pools always hold
            # a full max_model_len sequence plus one page —
            # config.resolve_num_kv_blocks — so this fires only on
            # explicitly undersized num_kv_blocks.)
            raise ValueError(
                f"prompt of {seq.num_prompt_tokens} tokens needs more KV "
                f"pages than the engine has ({self.allocator.num_blocks})"
            )
        seq.queue_stamp = self._next_stamp()
        self.waiting.append(seq)

    def _next_stamp(self) -> int:
        self._stamp += 1
        return self._stamp

    @staticmethod
    def _insert_by_stamp(dq: "Deque[Sequence]", seq: Sequence) -> None:
        """Insert keeping the deque ascending by queue_stamp. Involuntary
        preemption re-queues with the ORIGINAL stamp, and after rotate/
        resume cycles the running list is no longer stamp-ordered — a plain
        appendleft could put a newer victim in front of an older one,
        breaking the one-logical-FIFO invariant _admit relies on."""
        if not dq or dq[-1].queue_stamp <= seq.queue_stamp:
            dq.append(seq)
            return
        for i, s in enumerate(dq):
            if s.queue_stamp > seq.queue_stamp:
                dq.insert(i, seq)
                return

    def abort(self, request_id: str) -> Optional[Sequence]:
        for q in (self.waiting, self.running, self.swapped):
            for seq in list(q):
                if seq.request_id == request_id:
                    q.remove(seq)
                    self._finish(seq, "abort")
                    return seq
        return None

    def detach(self, request_id: str, reason: str = "abort") -> Optional[Sequence]:
        """Remove a sequence from the queues WITHOUT releasing its pages.

        For sequences referenced by an in-flight pipelined burst: the device
        is still writing through their block tables, so the pages must stay
        owned until the burst drains (the engine releases them then)."""
        for q in (self.waiting, self.running, self.swapped):
            for seq in list(q):
                if seq.request_id == request_id:
                    q.remove(seq)
                    seq.status = SequenceStatus.FINISHED
                    seq.finish_reason = reason
                    return seq
        return None

    def finish(self, seq: Sequence, reason: str) -> None:
        if seq in self.running:
            self.running.remove(seq)
        self._finish(seq, reason)

    def _finish(self, seq: Sequence, reason: str) -> None:
        seq.status = SequenceStatus.FINISHED
        seq.finish_reason = reason
        self.allocator.release_all(seq.block_ids)
        seq.block_ids = []
        if self.swapper is not None:
            self.swapper.drop(seq.request_id)

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    @property
    def num_swapped(self) -> int:
        return len(self.swapped)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running or self.swapped)

    # -- the step ---------------------------------------------------------

    def schedule(
        self,
        locked: frozenset = frozenset(),
        n_decode: Optional[int] = None,
    ) -> SchedulerOutput:
        """``locked``: request ids whose pages an in-flight burst references;
        they must not be preempted this pass (the engine drains the burst
        and re-schedules when that constraint binds).

        ``n_decode``: burst-depth override for this pass (the engine's
        adaptive-depth hint — deeper bursts amortize the fixed per-step
        dispatch+fetch latency when the arrival stream is quiet); clamped
        by the same per-sequence limits as the configured depth."""
        self._locked = locked
        self._n_decode_hint = n_decode
        out = SchedulerOutput()
        # Deadline sweep FIRST: an expired sequence must never consume a
        # device step — not a prefill chunk, not a decode slot, not even an
        # admission that pins pages.
        self._shed_expired(out)
        self._admit(out)
        # Fair timeslicing: if parked/queued work remains after admission,
        # rotate out the running sequence with the most decode progress past
        # the quantum — next pass admits the beneficiary into its pages.
        if (
            self.swapper is not None
            and (self.swapped or self.waiting)
            and len(self.running) > 1
        ):
            self._rotate(out)

        # Phase 1: sequences needing prompt (or post-preemption recompute)
        # work get chunks, oldest first, bounded by the step token budget.
        # A preempted sequence that already has outputs recomputes KV up to
        # its last token exclusive — that token is re-processed by decode.
        budget = self.config.max_prefill_tokens
        for seq in list(self.running):
            if budget <= 0:
                break
            if seq not in self.running:  # evicted by an earlier _ensure_blocks
                continue
            target = (
                seq.num_prompt_tokens
                if not seq.output_token_ids
                else seq.num_tokens - 1
            )
            remaining = target - seq.num_computed_tokens
            if remaining <= 0:
                continue
            chunk = min(remaining, budget)
            start = seq.num_computed_tokens
            end = start + chunk
            if not self._ensure_blocks(seq, end, out):
                continue
            out.prefills.append(PrefillItem(seq=seq, start=start, end=end))
            budget -= chunk
        if out.prefills:
            return out

        # Phase 2: a decode burst for every running sequence. Burst length is
        # bounded so no sequence writes KV past max_model_len; early stops
        # are trimmed host-side (≤ n-1 wasted tokens per finishing request).
        n = max(self._n_decode_hint or self.config.num_decode_steps, 1)
        for seq in self.running:
            n = min(n, max(self.config.max_model_len - seq.num_tokens, 1))
            if seq.sampling.guided_choice:
                # Guided decoding needs its allowed-token mask rebuilt per
                # token host-side. (Penalty rows ride bursts at full depth:
                # the occurrence counts live in multi_step's scan carry —
                # ops/sampling.py apply_penalties_counts.)
                n = 1
        look = max(self.config.decode_lookahead, 1)
        for seq in list(self.running):
            if seq not in self.running:  # lost pages to an earlier preemption
                continue
            reserve = min(
                seq.num_tokens + max(look * n - 1, self.config.spec_tokens),
                self.config.max_model_len,
            )
            if not self._ensure_blocks(seq, reserve, out, protect=seq):
                continue
            out.decodes.append(seq)
        out.n_decode_steps = n
        return out

    # -- internals --------------------------------------------------------

    def _shed_expired(self, out: SchedulerOutput) -> None:
        """Drop sequences whose deadline budget is gone — the point of the
        whole deadline subsystem is that this happens *before* a TPU step
        is spent on them. Queued/parked sequences shed from the line
        (``deadline_sheds_queued``); running ones shed between decode
        steps (``deadline_sheds_running``). Sequences referenced by an
        in-flight pipelined burst are skipped (the device still writes
        through their pages) and caught on the post-drain pass."""
        if not self.config.deadline_shedding:
            return
        now = time.monotonic()
        locked = getattr(self, "_locked", frozenset())
        for q, running in ((self.waiting, False), (self.swapped, False),
                           (self.running, True)):
            for seq in [s for s in q if s.deadline_expired(now)]:
                if seq.request_id in locked:
                    continue
                q.remove(seq)
                self._finish(seq, "deadline")
                if running:
                    self.deadline_sheds_running += 1
                else:
                    self.deadline_sheds_queued += 1
                    self._admit_blocked = None  # free pages changed
                out.expired.append(seq)
                logger.info(
                    "shedding request %s (deadline exceeded while %s)",
                    seq.request_id, "running" if running else "queued",
                )

    def _rotate(self, out: SchedulerOutput) -> None:
        """Swap out at most ONE quantum-expired running sequence per pass
        (bounds thrash; steady state rotates every ``swap_quantum`` tokens)."""
        q = self.config.swap_quantum
        if q <= 0:
            return
        locked = getattr(self, "_locked", frozenset())
        best: Optional[Sequence] = None
        for seq in self.running:
            if seq.request_id in locked or seq.in_prefill:
                continue
            progress = seq.num_tokens - seq.resume_marker
            if progress >= q and (
                best is None
                or progress > best.num_tokens - best.resume_marker
            ):
                best = seq
        if best is not None and self.swapper.can_stash(best, self.allocator):
            self.running.remove(best)
            self.swapper.swap_out(best, self.allocator)
            best.queue_stamp = self._next_stamp()  # back of the line
            self.swapped.append(best)
            self._admit_blocked = None  # free pages changed

    def flight_depths(self) -> tuple:
        """(waiting, running, swapped, batch_tier_rows) for the flight
        recorder's per-step record (obs/flight.py). Called on the step
        thread right after a dispatch — the same thread that mutates the
        queues, so plain reads are safe; cost is O(running) over a list
        bounded by max_num_seqs."""
        running = self.running
        batch = sum(1 for s in running if s.tier_rank)
        return (len(self.waiting), len(running), len(self.swapped), batch)

    def queue_age_by_tier(self, now: Optional[float] = None) -> dict:
        """Oldest waiting sequence's queue age per tier (seconds) — the
        per-tenant starvation signal behind ``pst:tenant_queue_age_*``.
        The flood-isolation contract is asserted on these: batch pressure
        must never grow the interactive queue age."""
        now = now if now is not None else time.monotonic()
        ages = {"interactive": 0.0, "batch": 0.0}
        # list(deque) is a single C-level copy (atomic under the GIL):
        # this reader runs on the HTTP/stats thread while the step thread
        # mutates the queues, and iterating the live deque would raise
        # "deque mutated during iteration" mid-scrape.
        for q in (list(self.waiting), list(self.swapped)):
            for seq in q:
                tier = "batch" if seq.tier_rank else "interactive"
                ages[tier] = max(ages[tier], now - seq.arrival_time)
        return ages

    def _next_waiting_index(self) -> int:
        """Which waiting sequence admits next. Plain FIFO (index 0) when
        tenant fairness is off or the queue is homogeneous; otherwise the
        best tier admits first (interactive strictly before batch) and
        tenants within that tier take turns by deficit round robin —
        stamp order is preserved *within* each (tier, tenant) class, so
        no tenant's own requests ever reorder."""
        if not self.config.tenant_fairness or len(self.waiting) < 2:
            return 0
        keys = {(s.tier_rank, s.tenant) for s in self.waiting}
        if len(keys) == 1:
            return 0
        best_rank = min(rank for rank, _ in keys)
        heads: dict = {}
        for i, s in enumerate(self.waiting):
            if s.tier_rank == best_rank and s.tenant not in heads:
                heads[s.tenant] = i
        pick = self._tenant_drr.pick({t: 1.0 for t in heads})
        return heads.get(pick, 0)

    def _preempt_batch_for(self, seq: Sequence, out: SchedulerOutput) -> bool:
        """An interactive sequence is blocked on pages while batch-tier
        work holds them: preempt ONE batch-tier running sequence
        (swap-first — ``_preempt`` parks KV host-side when it can, sheds
        to recompute otherwise) and report whether pages were freed.
        Batch work is throughput-oriented by contract; trading its decode
        progress for interactive TTFT is the whole point of the tiers."""
        locked = getattr(self, "_locked", frozenset())
        victim: Optional[Sequence] = None
        for cand in reversed(self.running):  # youngest batch first
            if cand.request_id in locked or cand.tier_rank != 1:
                continue
            victim = cand
            break
        if victim is None:
            return False
        self._preempt(victim, out)
        self.batch_preemptions += 1
        self._admit_blocked = None  # free pages changed
        logger.info(
            "preempting batch-tier request %s for waiting interactive %s",
            victim.request_id, seq.request_id,
        )
        return True

    def _promised_pages(self) -> int:
        """Pages already-admitted sequences will still allocate to finish
        their prompts. Admission allocates nothing itself, so gating each
        candidate against raw ``num_free`` would admit several long prompts
        into the same pages — re-creating prefill thrash one level up."""
        bs = self.allocator.block_size
        return sum(
            s.blocks_needed(s.num_prompt_tokens, bs) for s in self.running
        )

    def _admit(self, out: SchedulerOutput) -> None:
        # ``swapped`` and ``waiting`` admit as one stamp-ordered FIFO.
        # Swap-in is gated by a worst-case page check so a blocked resume
        # does not churn fault-up I/O every pass; resume is nearly free
        # when the parked pages never left HBM.
        promised = self._promised_pages()
        while self.swapped and len(self.running) < self.config.max_num_seqs:
            seq = self.swapped[0]
            if self.waiting and (
                self.waiting[0].queue_stamp
                < getattr(seq, "queue_stamp", 0)
            ):
                break  # an older waiting request admits first
            # Headroom beyond the bare resume need: each running sequence
            # may grow a page within a few steps, and a resume that leaves
            # zero slack gets swapped right back out (I/O churn: resumed →
            # victim → resumed, downloading its tail every pass). With
            # NOTHING running the gate must not hold (a sequence that once
            # filled the whole pool has worst-case need == pool size, and
            # gating it forever would deadlock the engine) — attempt the
            # resume; swap_in itself degrades safely if pages are short.
            reserve = len(self.running) + 1
            if self.running and (
                self.swapper.blocks_needed(seq) + reserve + promised
                > self.allocator.num_free
            ):
                return  # no room for the line's head: nobody jumps it
            self.swapped.popleft()
            if not self.swapper.swap_in(seq, self.allocator):
                self._insert_by_stamp(self.swapped, seq)
                return
            if seq.status == SequenceStatus.RUNNING:
                seq.resume_marker = seq.num_tokens
                if seq.first_scheduled_time is None:
                    seq.first_scheduled_time = time.monotonic()
                self.running.append(seq)
            else:
                # Fallback: part of the committed chain was unrecoverable;
                # the sequence recomputes from its longest surviving prefix.
                self._insert_by_stamp(self.waiting, seq)
        while self.waiting and len(self.running) < self.config.max_num_seqs:
            idx = self._next_waiting_index()
            seq = self.waiting[idx]
            if self.swapped and (
                getattr(self.swapped[0], "queue_stamp", 0) < seq.queue_stamp
                and self.swapped[0].tier_rank <= seq.tier_rank
            ):
                # A parked sequence is older but could not resume (page
                # gate above): hold the line rather than jump it. A
                # waiting sequence of a STRICTLY better tier does jump a
                # parked batch one — interactive admission must not queue
                # behind preempted batch work.
                break
            if self._admit_blocked == (
                seq.request_id,
                self.allocator.num_free,
                self.config.max_prefill_tokens,
            ):
                break  # nothing changed since the last failed attempt
            # Prefix-cache lookup at admission; never match the full token
            # list — at least one token must be computed to produce logits.
            # (all_token_ids, not just the prompt: a preempted-with-outputs
            # sequence can re-match KV for its own generated tokens too.)
            if not seq.block_ids:
                toks = seq.all_token_ids
                matchable = toks[: len(toks) - 1]
                blocks, hashes = self.allocator.match_prefix(
                    matchable, salt=getattr(seq, "cache_salt", 0),
                    deadline=seq.deadline,
                )
                if blocks:
                    seq.adopt_cached_prefix(blocks, hashes)
                    seq.num_computed_tokens = len(blocks) * self.allocator.block_size
                    seq.num_cached_prompt_tokens = seq.num_computed_tokens
            # Admission requires pages for the FULL prompt (vLLM-style), not
            # just the first chunk: chunk-level admission of a long prompt
            # overcommits the pool, and its later chunks then preempt
            # fully-prefilled sequences — which re-prefill and evict others
            # in turn (prefill thrash at near-capacity).
            need = seq.blocks_needed(
                seq.num_prompt_tokens, self.allocator.block_size
            )
            if need + promised > self.allocator.num_free:
                # Engine full; stays queued (vllm:num_requests_waiting). The
                # prefix blocks adopted above must be released: they are
                # refcounted and nothing in the preemption path reclaims
                # pages pinned by *waiting* sequences, so holding them here
                # could wedge admission permanently. Re-matched next attempt.
                if seq.block_ids:
                    self.allocator.release_all(seq.block_ids)
                    seq.reset_for_recompute()
                    seq.status = SequenceStatus.WAITING
                # Batch-tier preemption (docs/multi-tenancy.md): before
                # declaring the pool full for a waiting INTERACTIVE
                # sequence, evict one running batch-tier sequence
                # (swap-first) and retry — batch work never starves
                # interactive prefills on pages.
                if (
                    self.config.tenant_fairness
                    and seq.tier_rank == 0
                    and self._preempt_batch_for(seq, out)
                ):
                    promised = self._promised_pages()
                    continue
                self._admit_blocked = (
                    seq.request_id,
                    self.allocator.num_free,
                    self.config.max_prefill_tokens,
                )
                break
            del self.waiting[idx]
            self._admit_blocked = None
            self._tenant_drr.charge(seq.tenant)
            seq.status = SequenceStatus.RUNNING
            seq.resume_marker = seq.num_tokens
            # Queue-wait end marker (first admission only: a preempted
            # sequence's re-admission is not queue wait — its TTFT
            # decomposition keeps the original boundary).
            if seq.first_scheduled_time is None:
                seq.first_scheduled_time = time.monotonic()
            self.running.append(seq)
            promised += need  # this admission's unprefilled pages

    def _ensure_blocks(
        self,
        seq: Sequence,
        up_to_tokens: int,
        out: SchedulerOutput,
        protect: Optional[Sequence] = None,
    ) -> bool:
        """Allocate pages for ``seq`` up to ``up_to_tokens``, preempting the
        youngest other sequence on exhaustion. False if ``seq`` itself lost."""
        locked = getattr(self, "_locked", frozenset())
        while True:
            try:
                for _ in range(seq.blocks_needed(up_to_tokens, self.allocator.block_size)):
                    seq.block_ids.append(self.allocator.allocate())
                return True
            except NoFreeBlocksError:
                victim = self._pick_victim(exclude=protect or seq)
                if victim is None:
                    if seq.request_id in locked:
                        # Cannot self-preempt a sequence whose pages an
                        # in-flight burst still writes through: signal the
                        # engine to drain and retry.
                        out.blocked_on_locked = True
                        out.decodes[:] = [s for s in out.decodes if s is not seq]
                        return False
                    # Nothing left to evict but this sequence itself.
                    self._preempt(seq, out)
                    return False
                self._preempt(victim, out)

    def _pick_victim(self, exclude: Sequence) -> Optional[Sequence]:
        locked = getattr(self, "_locked", frozenset())
        if self.config.tenant_fairness:
            # Batch-tier sequences are preemptible first: an interactive
            # sequence only loses pages when no batch victim remains.
            for seq in reversed(self.running):  # youngest batch first
                if (
                    seq is not exclude
                    and seq.request_id not in locked
                    and seq.tier_rank == 1
                ):
                    return seq
        for seq in reversed(self.running):  # youngest first (vLLM policy)
            if seq is not exclude and seq.request_id not in locked:
                return seq
        return None

    def _preempt(self, seq: Sequence, out: SchedulerOutput) -> None:
        if seq in self.running:
            self.running.remove(seq)
        # The victim may already have been granted work this step — revoke it
        # (its pages are about to be surrendered).
        out.decodes[:] = [s for s in out.decodes if s is not seq]
        out.prefills[:] = [it for it in out.prefills if it.seq is not seq]
        if (
            self.swapper is not None
            and not seq.in_prefill
            and self.swapper.can_stash(seq, self.allocator)
        ):
            # Park KV instead of recompute: the committed prefix stays
            # content-addressed in place; only the tail pages move host-side.
            logger.info(
                "swapping out request %s (out of KV pages)", seq.request_id
            )
            self.swapper.swap_out(seq, self.allocator)
            # Involuntary: keeps its original (old) stamp, so the sorted
            # insert lands it at/near the front of the resume line.
            self._insert_by_stamp(self.swapped, seq)
            return
        logger.warning("preempting request %s (out of KV pages)", seq.request_id)
        self.allocator.release_all(seq.block_ids)
        seq.reset_for_recompute()
        self._insert_by_stamp(self.waiting, seq)
        out.preempted.append(seq)
