"""Paged KV-cache block manager with content-hash prefix caching.

The reference gets this from vLLM's PagedAttention block manager plus
LMCache's chunk-hash dedup (`SURVEY.md` §2.4 "KV-cache tiering"). Here the
manager is host-side bookkeeping only — device pages live in the stacked
``[L, nb, bs, KH, hd]`` cache arrays owned by the runner; this class decides
*which page index* each sequence writes/reads, and which full pages are
shareable across requests via the prefix-committing block hashes of
:mod:`production_stack_tpu.kvcache.hashing` (the same scheme the router's
KV-aware policy and the remote cache tier speak, so routing and reuse agree).

Eviction is LRU over reusable pages (refcount 0 but content intact). An
``on_evict`` hook lets the tiering layer capture pages on their way out
(HBM → host DRAM → remote, LMCache-style).
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..kvcache.hashing import block_hashes
from ..logging_utils import init_logger

logger = init_logger(__name__)


class NoFreeBlocksError(RuntimeError):
    pass


class BlockAllocator:
    """Reference-counted page allocator with hash-addressed reuse."""

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        on_evict: Optional[Callable[[int, int], None]] = None,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.on_evict = on_evict
        self._refcount = [0] * num_blocks
        self._hash_of_block: Dict[int, int] = {}
        self._block_of_hash: Dict[int, int] = {}
        # refcount-0 blocks with intact, hash-addressed content (LRU order).
        self._reusable: "collections.OrderedDict[int, int]" = collections.OrderedDict()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        # Prefix-cache KPIs exported as vllm:gpu_prefix_cache_* gauges.
        self.hit_tokens = 0
        self.query_tokens = 0

    # -- capacity ---------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._reusable)

    @property
    def usage(self) -> float:
        return 1.0 - self.num_free / max(self.num_blocks, 1)

    # -- allocation -------------------------------------------------------

    def allocate(self) -> int:
        """Take one writable page (evicting the LRU reusable page if needed)."""
        if self._free:
            blk = self._free.pop()
            self._refcount[blk] = 1
            return blk
        if self._reusable:
            blk, h = self._reusable.popitem(last=False)
            del self._block_of_hash[h]
            del self._hash_of_block[blk]
            if self.on_evict is not None:
                self.on_evict(blk, h)
            self._refcount[blk] = 1
            return blk
        raise NoFreeBlocksError("out of KV blocks")

    def acquire_cached(self, h: int) -> Optional[int]:
        """Reuse the page holding hash ``h``, if resident. Increfs."""
        if not self.enable_prefix_caching:
            return None
        blk = self._block_of_hash.get(h)
        if blk is None:
            return None
        if blk in self._reusable:
            del self._reusable[blk]
        self._refcount[blk] += 1
        return blk

    def incref(self, blk: int) -> None:
        self._refcount[blk] += 1

    def acquire_resident(self, h: int) -> Optional[int]:
        """Reacquire the page holding hash ``h`` from wherever it survives.
        Base allocator: HBM residency only; the tiered allocator overrides
        this to also fault pages back up from host DRAM / the remote store.
        Used by the swap path to resurrect a parked sequence's committed
        prefix without copying bytes that never left."""
        return self.acquire_cached(h)

    def commit(self, blk: int, h: int, allow_swap: bool = True) -> int:
        """Mark a freshly-written full page as content-addressed by ``h``.

        If another request concurrently committed the same content, dedup to
        the existing page: the caller must swap to the returned id.
        ``allow_swap=False`` suppresses that (and the release of the
        duplicate) — required while the page is referenced by an in-flight
        pipelined decode burst, whose device block table still points at it.
        """
        if not self.enable_prefix_caching:
            return blk
        existing = self._block_of_hash.get(h)
        if existing is not None and existing != blk:
            if not allow_swap:
                return blk  # keep our copy un-addressed; existing stays owner
            self.release(blk)
            self.incref(existing)
            if existing in self._reusable:
                del self._reusable[existing]
            return existing
        self._hash_of_block[blk] = h
        self._block_of_hash[h] = blk
        return blk

    def release(self, blk: int) -> None:
        self._refcount[blk] -= 1
        assert self._refcount[blk] >= 0, f"double free of block {blk}"
        if self._refcount[blk] == 0:
            h = self._hash_of_block.get(blk)
            if h is not None:
                self._reusable[blk] = h  # keep content for future hits
            else:
                self._free.append(blk)

    def release_all(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self.release(b)

    # -- prefix lookup ----------------------------------------------------

    def match_prefix(
        self,
        token_ids: Sequence[int],
        salt: int = 0,
        deadline: Optional[float] = None,
    ) -> Tuple[List[int], List[int]]:
        """Longest resident prefix of ``token_ids`` at block granularity.

        ``salt`` seeds the hash chain (LoRA adapters salt by adapter name so
        base-model KV never serves adapter requests and vice versa).
        ``deadline`` (monotonic; used by the tiered allocator) bounds
        lower-tier fetches to the request's remaining budget — the base
        allocator is HBM-only and ignores it.
        Returns (matched block ids — increfed, their hashes). Callers start
        computing at ``len(matched) * block_size``.
        """
        self.query_tokens += len(token_ids)
        if not self.enable_prefix_caching:
            return [], []
        hashes = block_hashes(token_ids, self.block_size, parent=salt)
        matched: List[int] = []
        matched_hashes: List[int] = []
        for h in hashes:
            blk = self.acquire_cached(h)
            if blk is None:
                break
            matched.append(blk)
            matched_hashes.append(h)
        self.hit_tokens += len(matched) * self.block_size
        return matched, matched_hashes

    @property
    def hit_rate(self) -> float:
        return self.hit_tokens / self.query_tokens if self.query_tokens else 0.0

    def reset_metrics(self) -> None:
        self.hit_tokens = 0
        self.query_tokens = 0
