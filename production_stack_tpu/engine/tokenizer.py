"""Tokenizer abstraction: local HF tokenizers + a byte-level fallback.

The environment is zero-egress, so tokenizers load only from local
directories; tests, benchmarks, and the fake fleet use :class:`ByteTokenizer`
(utf-8 bytes as ids — reversible, vocab-compatible with the tiny debug
models). Mirrors the tokenize/chat-template duties vLLM's OpenAI server
performs behind the reference stack (`/tokenize`, chat templating).
"""

from __future__ import annotations

from typing import List, Optional, Protocol, Sequence, Tuple

from ..logging_utils import init_logger
from ..protocols import ChatMessage

logger = init_logger(__name__)


class Tokenizer(Protocol):
    vocab_size: int
    eos_token_ids: Tuple[int, ...]

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...

    def apply_chat_template(
        self,
        messages: List[ChatMessage],
        add_generation_prompt: bool = True,
        continue_final_message: bool = False,
    ) -> str: ...


def _fallback_chat_template(
    messages: List[ChatMessage],
    add_generation_prompt: bool,
    continue_final_message: bool = False,
) -> str:
    parts = [f"<|{m.role}|>\n{m.text()}\n" for m in messages]
    if continue_final_message:
        # Leave the final message's turn OPEN (no terminator, no new
        # generation prompt) so the model continues it mid-sentence —
        # the contract stream resumption relies on: the continuation is
        # the suffix of the final assistant message, not a fresh turn.
        if parts:
            parts[-1] = parts[-1][:-1]
        return "".join(parts)
    if add_generation_prompt:
        parts.append("<|assistant|>\n")
    return "".join(parts)


class ByteTokenizer:
    """utf-8 bytes as token ids 1..256; id 0 is EOS/pad."""

    def __init__(self, vocab_size: int = 512):
        self.vocab_size = vocab_size
        self.eos_token_ids: Tuple[int, ...] = (0,)

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        return [b + 1 for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i - 1 for i in ids if 1 <= i <= 256).decode(
            "utf-8", errors="replace"
        )

    def encode_pair(
        self, a: str, b: str, max_len: Optional[int] = None
    ) -> Tuple[List[int], List[int]]:
        # 258 = synthetic separator (outside the byte id range 1..256).
        # Segment ids: 0 for the first text (+sep), 1 for the second.
        # longest-first truncation keeps the pair template intact (ADVICE
        # r3: tail-slicing dropped the final separator on long documents).
        ia, ib = self.encode(a), self.encode(b)
        if max_len is not None:
            budget = max_len - 1  # separator
            while len(ia) + len(ib) > budget:
                if len(ia) >= len(ib):
                    ia.pop()
                else:
                    ib.pop()
        return ia + [258] + ib, [0] * (len(ia) + 1) + [1] * len(ib)

    def apply_chat_template(
        self,
        messages: List[ChatMessage],
        add_generation_prompt: bool = True,
        continue_final_message: bool = False,
    ) -> str:
        return _fallback_chat_template(
            messages, add_generation_prompt, continue_final_message
        )


class HFTokenizer:
    """transformers.AutoTokenizer over a local directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        eos = self._tok.eos_token_id
        self.eos_token_ids: Tuple[int, ...] = tuple(
            eos if isinstance(eos, (list, tuple)) else [eos] if eos is not None else []
        )

    def encode(self, text: str, add_special_tokens: bool = True) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def encode_pair(
        self, a: str, b: str, max_len: Optional[int] = None
    ) -> Tuple[List[int], List[int]]:
        """Sentence-pair encoding with the model's own pair template
        (RoBERTa: <s> a </s></s> b </s>; BERT: [CLS] a [SEP] b [SEP] with
        segment ids) — what cross-encoders were trained on. Tokenizer-side
        ``longest_first`` truncation preserves the final special tokens
        (ADVICE r3: tail-slicing silently degraded long-document scores)."""
        kwargs = {}
        if max_len is not None:
            kwargs = {"truncation": "longest_first", "max_length": max_len}
        enc = self._tok(a, b, **kwargs)
        ids = enc["input_ids"]
        types = enc.get("token_type_ids") or [0] * len(ids)
        return ids, types

    def apply_chat_template(
        self,
        messages: List[ChatMessage],
        add_generation_prompt: bool = True,
        continue_final_message: bool = False,
    ) -> str:
        dicts = [{"role": m.role, "content": m.text()} for m in messages]
        kwargs = {"tokenize": False,
                  "add_generation_prompt": add_generation_prompt}
        if continue_final_message:
            # Older transformers silently swallow unknown kwargs into
            # **tokenizer_kwargs — which would render the final turn
            # CLOSED with no error. Verify real support; degrade loudly
            # to the manual template (open turn guaranteed) otherwise.
            import inspect

            params = inspect.signature(
                self._tok.apply_chat_template
            ).parameters
            if "continue_final_message" not in params:
                logger.warning(
                    "tokenizer lacks continue_final_message; rendering "
                    "the continuation with the fallback chat template"
                )
                return _fallback_chat_template(
                    messages, add_generation_prompt, continue_final_message
                )
            kwargs["continue_final_message"] = True
        try:
            return self._tok.apply_chat_template(dicts, **kwargs)
        except Exception:
            return _fallback_chat_template(
                messages, add_generation_prompt, continue_final_message
            )


def get_tokenizer(spec: Optional[str], vocab_size: int = 512) -> Tokenizer:
    """``spec``: local HF dir, or None/"byte" for the byte fallback."""
    if spec and spec != "byte":
        try:
            return HFTokenizer(spec)
        except Exception as e:
            logger.warning("HF tokenizer load failed (%s); using byte tokenizer", e)
    return ByteTokenizer(vocab_size)
