"""Engine configuration — the TPU analogue of the reference's vLLM flag set.

Field ↔ reference mapping (`helm/values.yaml:71-81`, CRD
`operator/api/v1alpha1/vllmruntime_types.go:67-95`):
``tensor_parallel_size`` ↔ ``--tensor-parallel-size``; ``max_model_len`` ↔
``--max-model-len``; ``max_num_seqs`` ↔ ``--max-num-seqs``;
``enable_prefix_caching`` ↔ ``--enable-prefix-caching``;
``max_prefill_tokens`` ↔ chunked-prefill token budget;
``hbm_utilization`` ↔ ``--gpu-memory-utilization``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax

from ..logging_utils import init_logger
from ..models.llama import LlamaConfig

logger = init_logger(__name__)


@dataclasses.dataclass
class EngineConfig:
    model: str = "tiny-llama-debug"
    tokenizer: Optional[str] = None  # default: model dir, or byte tokenizer
    served_model_name: Optional[str] = None
    max_model_len: int = 4096
    block_size: int = 32
    num_kv_blocks: Optional[int] = None  # None: size from HBM budget
    hbm_utilization: float = 0.9
    max_num_seqs: int = 64
    max_prefill_tokens: int = 2048
    tensor_parallel_size: int = 1
    data_parallel_size: int = 1
    # Layer-stage parallelism over the pp mesh axis (the reference's
    # Ray-cluster `--pipeline-parallel-size`, `ray-cluster.yaml:560-566`).
    # Stages hold L/pp layers + their KV pages; activations hop via ppermute.
    pipeline_parallel_size: int = 1
    # Ring (context-parallel) attention over the sp mesh axis for the
    # full-attention encode path (/v1/embeddings at contexts beyond one
    # device group's attention memory). See ops/ring_attention.py.
    sequence_parallel_size: int = 1
    # Expert parallel (MoE models): the expert bank shards over the ep mesh
    # axis; the combine reduction is the one ep all-reduce XLA inserts.
    expert_parallel_size: int = 1
    kv_cache_dtype: Optional[str] = None  # default: model dtype
    # Weight-only quantization: "int8" stores matmul weights as int8 with
    # per-output-channel scales (models/llama.py quantize_leaf). Halves
    # weight HBM and decode's weight-read bandwidth — what fits Llama-3-8B
    # plus its KV on one 16 GiB v5e chip (the reference serves the same 8B
    # benchmark model on a 40 GiB A100). "int4" packs two group-wise-scaled
    # (g=128, AWQ/GPTQ-family) nibbles per byte for the per-layer matmuls
    # (embed/lm_head stay int8): quarters weight HBM, freeing room for
    # ~2x the resident KV — 8 concurrent 20k-context users on one chip.
    # None = native dtype.
    quantization: Optional[str] = None  # None | int8 | int4
    attn_impl: str = "auto"  # auto | gather | pallas
    # MoE execution strategy: ragged (dropless lax.ragged_dot grouped
    # matmul — FLOP-proportional, the single-shard default) | dense
    # (expert-batched einsums, GSPMD-shardable over ep/tp) | auto.
    moe_impl: str = "auto"
    enable_prefix_caching: bool = True
    # Decode tokens generated per device call (lax.scan over steps inside one
    # jit). Amortizes host⇄device dispatch — the dominant cost for small
    # models and remote-attached chips. Stop conditions are applied host-side
    # after the burst; at most n-1 speculatively-decoded tokens are discarded
    # per finished request. 1 = classic per-token stepping.
    num_decode_steps: int = 1
    # Adaptive burst depth: when the arrival stream has been quiet for
    # ``adaptive_decode_quiet_s`` and nothing is waiting, decode bursts
    # deepen to this many steps (amortizing the fixed per-dispatch
    # host<->device latency — ~73 ms on tunnel-attached chips — over more
    # tokens). Gated on PAST arrivals only, so a live Poisson stream keeps
    # bursts at num_decode_steps and tail latency is unaffected; saturated
    # decode (batch/offline phases) runs at the deep setting. 0 = off.
    adaptive_decode_steps: int = 0
    adaptive_decode_quiet_s: float = 0.5
    # Additional deepening gate: require at least this many running
    # sequences. In closed-loop/multi-round traffic a full running set
    # means no client has a request left to send — exactly when a deep
    # burst cannot delay anyone's TTFT. 0 = no constraint.
    adaptive_decode_min_running: int = 0
    # Floor for the decode-batch row bucket. Serving workloads whose active
    # set fluctuates otherwise walk through every power-of-two width,
    # compiling each one the first time it appears (an XLA compile mid-burst
    # is a multi-second TTFT outlier). Padding rows carry kv_len=0 and cost
    # ~nothing — the pallas kernel streams zero pages for them.
    min_decode_bucket: int = 1
    # Speculative decoding via n-gram prompt lookup (engine/spec.py): draft
    # up to this many tokens per greedy sequence per step and verify them in
    # one forward pass. 0 = off. Output is exactly the non-speculative
    # greedy output; sampled (temperature>0) batches bypass speculation.
    speculative_ngram: int = 0
    ngram_min: int = 1  # shortest suffix n-gram to match
    ngram_max: int = 3  # longest suffix n-gram to match
    # Cap the prompt-lookup scan to the last N tokens (0 = whole history).
    # Bounds the per-step host-side draft cost at long context.
    ngram_lookback: int = 8192
    # Pipelined decode: keep one burst in flight and overlap its token fetch
    # with the next burst's execution (hides the host<->device round trip).
    # Raises decode throughput on dispatch-latency-bound setups but ADDS up
    # to one extra in-flight burst of queueing delay before a new arrival's
    # prefill can run — measured on the 20k-context protocol bench it trades
    # ~35% decode throughput for ~60% worse p50 TTFT, so it is off by
    # default and meant for throughput-oriented (batch) serving.
    async_decode: bool = False
    # Overlapped decode pipeline (docs/engine.md "Overlapped decode
    # pipeline"): the arrival-gated form of pipelining. As soon as burst
    # N's token ids are fetched, burst N+1 is dispatched and burst N's host
    # bookkeeping (detokenization, stop scans, stream frames, stats,
    # scheduler accounting) runs WHILE N+1 executes — but a pipeline only
    # STARTS when the same three arrival-safety rules as adaptive
    # deepening hold (waiting queue empty, min-running floor met, arrival
    # stream quiet), so live-traffic TTFT never queues behind an in-flight
    # burst it didn't already have. Saturated decode gets async_decode's
    # throughput; paced traffic keeps the synchronous loop's latency.
    overlap_decode: bool = True
    enforce_eager: bool = False  # reserved; XLA always compiles
    seed: int = 0
    # KV tiering (LMCache-analogue knobs; SURVEY.md §2.4).
    cpu_offload_blocks: int = 0
    # One kvserver base URL, or a comma-separated shard list — the latter
    # builds the replicated ShardedKVClient over the consistent-hash ring
    # (docs/kvserver.md).
    remote_kv_url: Optional[str] = None
    # Replicas per block/manifest on the kvserver ring (clamped to the
    # shard count; meaningful only with a multi-URL remote_kv_url).
    kv_replication: int = 2
    # Cache-controller registration (KV-aware routing; LMCACHE_CONTROLLER_URL
    # analogue). engine_url is what this pod reports itself as.
    cache_controller_url: Optional[str] = None
    engine_url: Optional[str] = None
    # LoRA serving (reference: vLLM --enable-lora + the operator's
    # load/unload HTTP flow, `loraadapter_controller.go:582-611`). Adapters
    # live in a stacked device bank; any mix serves in one compiled step.
    enable_lora: bool = False
    max_loras: int = 8
    max_lora_rank: int = 16
    lora_dir: str = "/adapters"
    # Live-sequence KV swap (engine/swap.py; vLLM --swap-space analogue).
    # Preemption parks KV host-side instead of recomputing, and the
    # scheduler timeslices more concurrent 20k-context users than HBM
    # holds. Committed pages never move (content-addressed in place /
    # existing tier); only uncommitted tail pages are stashed.
    kv_swap: bool = True
    # Rotate a running sequence out after this many decoded tokens when
    # parked/queued work exists (0 = only swap under allocation pressure).
    swap_quantum_tokens: int = 256
    # Host-DRAM budget for stashed tail pages, in KV pages.
    swap_stash_blocks: int = 4096
    # Disaggregated prefill role (reference: --kv-transfer-config
    # kv_producer/kv_consumer, `deployment-vllm-multi.yaml:180-189`).
    # producer: push each completed prefill's KV pages to the remote store
    # (device→host DMA then DCN — the NIXL-sender analogue).
    # consumer: fault pages up from the remote store at admission
    # (TieredAllocator.match_prefix — the NIXL-receiver analogue).
    kv_role: str = "none"  # none | producer | consumer | both
    # Streamed disagg KV handoff (docs/disagg.md). Consumer-side prefetch:
    # max blocks per batched GET while following a prefill's manifest
    # (bounds one response's host memory), and the wall-clock window the
    # decode engine will wait for the manifest's completion marker before
    # degrading to the fused path (recompute the prefill locally).
    kv_prefetch_depth: int = 64
    kv_transfer_timeout_s: float = 10.0
    # Deadline shedding (docs/resilience.md "Deadlines & hedging"): honor
    # the router-propagated X-PST-Deadline-Ms budget — 504 expired work at
    # admission, drop expired queued sequences before they consume a
    # prefill step, and stop decoding expired running sequences.
    deadline_shedding: bool = True
    # Tenant-aware scheduling (docs/multi-tenancy.md): honor the
    # router-stamped X-PST-Tenant / X-PST-Tenant-Class headers — the
    # ready queue admits weighted-fair across tenants with strict tier
    # priority (interactive before batch), and batch-tier sequences are
    # preempted first (swap/shed) when an interactive tenant is waiting
    # for pages. With every request untagged (or this off) scheduling is
    # byte-for-byte the plain FIFO behavior.
    tenant_fairness: bool = True
    # Ahead-of-time shape-bucket precompilation (engine/precompile.py;
    # docs/engine.md "Warmup & precompilation"). "full" compiles the whole
    # padded shape-bucket lattice before /ready flips; "lazy" compiles only
    # the core set the first requests hit; "off" skips warmup (compile on
    # demand — the pre-PR-6 behavior, and the embedded/test default; the
    # helm chart deploys engines with "full").
    warmup: str = "off"  # off | lazy | full
    # Cap on buckets compiled at warmup (0 = the entire lattice). Buckets
    # are walked most-likely-first, so a small budget still covers the
    # common traffic shapes; the coverage gauge reports what was skipped.
    warmup_bucket_budget: int = 0
    # Persistent JAX compilation cache root (vLLM VLLM_CACHE_ROOT
    # analogue). Executables land in a subdirectory keyed on model + mesh
    # + dtype + code version, so a warm restart (or a rolling-deploy
    # replacement pod on a PVC/hostPath mount) deserializes them instead
    # of paying the 46-138 s XLA cold start again. None = no persistence.
    compile_cache_dir: Optional[str] = None
    # Flight recorder (docs/observability.md "Flight recorder"): always-on
    # bounded ring of per-device-step records (kind, bucket, step wall,
    # host gap, queue depths, KV occupancy, tier mix, compile events),
    # served at GET /debug/flight and auto-snapshotted on tail outliers
    # and SIGTERM/fatal. The value is the ring capacity in steps; 0
    # disables recording (the endpoint then serves an empty ring).
    flight_buffer: int = 512
    # Flight-snapshot persistence (docs/observability.md "Flight
    # recorder"): every retained snapshot (tail outlier, live compile,
    # SIGTERM/fatal) is also written as one JSON file under this
    # directory, bounded with oldest-first eviction, and loaded back into
    # GET /debug/flight?snapshots=1 after a restart — so a forensics
    # collector can harvest the post-mortem even when the engine died
    # before anyone scraped it. None = in-memory retention only.
    flight_snapshot_dir: Optional[str] = None
    # Per-request cost attribution (docs/observability.md "Cost
    # attribution"): accumulate each request's prefill device-seconds,
    # active-row share of decode-burst device-seconds, KV page-seconds
    # and queue wait; surfaced as the X-PST-Cost response header + usage
    # extension and the pst_request_device_seconds /
    # pst_tenant_device_seconds metrics (chip-time billing).
    cost_attribution: bool = True


# Known per-chip HBM for backends whose memory_stats() is empty (the tunnel-
# attached chips used for bench runs report none). Public TPU specs.
_HBM_BY_DEVICE_KIND = {
    "TPU v5 lite": 16 * 1024**3,
    "TPU v5e": 16 * 1024**3,
    "TPU v4": 32 * 1024**3,
    "TPU v5p": 95 * 1024**3,
    "TPU v6 lite": 32 * 1024**3,
    "TPU v6e": 32 * 1024**3,
}


def resolve_num_kv_blocks(
    cfg: EngineConfig, model_cfg: LlamaConfig, param_bytes_per_device: int
) -> int:
    """Page count from the HBM budget (``--gpu-memory-utilization`` analogue).

    bytes/page = 2 (K+V) * L * bs * KH * hd * itemsize, divided by tp (kv
    heads sharded over the tensor axis) and pp (layers sharded over stages).
    """
    if cfg.num_kv_blocks is not None:
        return cfg.num_kv_blocks
    dtype_size = jax.numpy.dtype(cfg.kv_cache_dtype or model_cfg.dtype).itemsize
    tp = max(cfg.tensor_parallel_size, 1)
    pp = max(cfg.pipeline_parallel_size, 1)
    page_bytes = (
        2
        * max(model_cfg.num_layers // pp, 1)
        * cfg.block_size
        * max(model_cfg.num_kv_heads // tp, 1)
        * model_cfg.head_dim
        * dtype_size
    )
    # local_devices, not devices: on a multi-host mesh devices()[0] may be
    # non-addressable here, and a swallowed memory_stats failure would give
    # followers a different page count than the primary (shape divergence).
    dev = jax.local_devices()[0]
    stats = {}
    try:
        stats = dev.memory_stats() or {}
    except Exception:
        pass
    hbm = stats.get("bytes_limit")
    if not hbm:
        # Some backends (e.g. remote-attached chips) report no memory stats;
        # fall back to the known HBM of the device kind.
        hbm = _HBM_BY_DEVICE_KIND.get(getattr(dev, "device_kind", ""))
    if not hbm:
        # Virtual CPU devices: keep the cache modest (tests override anyway).
        budget = 512 * 1024 * 1024
    else:
        budget = int(hbm * cfg.hbm_utilization) - param_bytes_per_device
    n = max(budget // page_bytes, cfg.max_num_seqs * 2)
    # Never fewer pages than one full-length sequence needs.
    n = max(n, -(-cfg.max_model_len // cfg.block_size) + 1)
    logger.info(
        "KV cache: %d pages x %d tokens (%.1f MiB/device)",
        n, cfg.block_size, n * page_bytes / 2**20,
    )
    return int(n)
