"""Request-side state for the serving engine: sampling params + sequences.

Plays the role of vLLM's ``SamplingParams``/``Sequence`` (which the reference
stack drives over HTTP). A :class:`Sequence` owns its token ids, its KV page
list, and the prefix-cache commit cursor; all device state lives in the
runner's cache arrays.
"""

from __future__ import annotations

import dataclasses
import time
from enum import Enum
from typing import List, Optional, Sequence as Seq, Tuple, Union

from ..kvcache.hashing import block_hashes
from .kv_manager import BlockAllocator


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 16
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1
    min_p: float = 0.0
    stop: Union[str, List[str], None] = None
    stop_token_ids: Tuple[int, ...] = ()
    ignore_eos: bool = False
    seed: Optional[int] = None
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    logprobs: Optional[int] = None
    # OpenAI logit_bias: additive per-token-id logit offsets, applied before
    # sampling (and before greedy argmax).
    logit_bias: Tuple[Tuple[int, float], ...] = ()
    # Guided choice (vLLM extra-body `guided_choice` analogue): the output
    # must be exactly one of these token-id sequences; each step's logits
    # are masked to the tokens that continue a still-viable choice.
    guided_choice: Tuple[Tuple[int, ...], ...] = ()

    @property
    def greedy(self) -> bool:
        return self.temperature <= 1e-5

    def guided_allowed(
        self, output_so_far: Seq[int], eos_ids: Seq[int] = ()
    ) -> Optional[List[int]]:
        """Token ids allowed next under guided_choice (None = unconstrained).
        A choice stays viable while the output equals its prefix. When the
        output already IS a complete choice, ``eos_ids`` are also allowed —
        otherwise a choice that is a strict prefix of another ("yes" vs
        "yes!") could never be produced: the mask would force continuation
        into the longer one."""
        if not self.guided_choice:
            return None
        out = tuple(output_so_far)
        n = len(out)
        allowed = []
        for c in self.guided_choice:
            if len(c) > n and c[:n] == out and c[n] not in allowed:
                allowed.append(c[n])
        if out in self.guided_choice:
            for e in eos_ids:
                if e not in allowed:
                    allowed.append(e)
        return allowed

    def guided_done(self, output_so_far: Seq[int]) -> bool:
        """True when no choice continuation remains — the completed-choice
        case, and also any dead end (e.g. EOS emitted under ignore_eos at a
        completed prefix choice): stopping beats serving a fully-masked
        logit row whose argmax would be garbage token 0."""
        if not self.guided_choice:
            return False
        return self.guided_allowed(output_so_far) == []

    @property
    def has_penalties(self) -> bool:
        return (
            self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
            or self.repetition_penalty != 1.0
        )

    def stop_strings(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class SequenceStatus(Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    SWAPPED = "swapped"  # live KV parked host-side (engine/swap.py)
    FINISHED = "finished"


class Sequence:
    """One request's lifecycle through the engine."""

    def __init__(
        self,
        request_id: str,
        prompt_token_ids: Seq[int],
        sampling: SamplingParams,
        arrival_time: Optional[float] = None,
        lora_idx: int = 0,
        lora_scale: float = 0.0,
        cache_salt: int = 0,
        deadline: Optional[float] = None,
        tenant: str = "default",
        tenant_class: str = "interactive",
        kv_transfer: Optional[dict] = None,
    ):
        self.request_id = request_id
        self.prompt_token_ids: List[int] = list(prompt_token_ids)
        self.output_token_ids: List[int] = []
        self.sampling = sampling
        self.status = SequenceStatus.WAITING
        # Queue/TTFT bookkeeping rides time.monotonic(), same clock as
        # `deadline` and the admission token bucket: stage durations
        # (queue wait, prefill, decode) must survive wall-clock steps —
        # an NTP adjustment mid-request would otherwise corrupt TTFT and
        # the per-stage decomposition.
        self.arrival_time = arrival_time or time.monotonic()
        self.first_scheduled_time: Optional[float] = None  # queue-wait end
        self.first_token_time: Optional[float] = None  # TTFT marker
        self.finish_reason: Optional[str] = None
        # LoRA bank slot serving this request (0 = base model) and its
        # alpha/r scaling; cache_salt seeds the block-hash chain so KV
        # produced under one adapter never serves as a prefix hit for
        # another (the KV itself differs).
        self.lora_idx = lora_idx
        self.lora_scale = lora_scale
        self.cache_salt = cache_salt
        # Monotonic (time.monotonic) expiry of the request's end-to-end
        # latency budget; None = no deadline. The scheduler sheds expired
        # sequences before they consume device steps.
        self.deadline = deadline
        # Tenant identity and tier, stamped by the router at admission
        # (X-PST-Tenant / X-PST-Tenant-Class). The scheduler admits
        # weighted-fair across tenants and preempts batch-tier work first.
        self.tenant = tenant
        self.tenant_class = (
            tenant_class if tenant_class == "batch" else "interactive"
        )

        # KV bookkeeping.
        self.block_ids: List[int] = []
        self.num_computed_tokens = 0  # tokens whose KV is resident
        self.num_cached_prompt_tokens = 0  # prefix-cache hits at admission
        self.block_hashes: List[int] = []  # hash per committed block
        self._committed_blocks = 0
        self._last_hash = cache_salt
        # Chunk-hash cursor (controller registration granularity).
        self._chunk_cursor = 0
        self._chunk_last_hash = 0
        # Token count at admission / last swap-in: the scheduler's rotation
        # quantum measures decode progress since this marker.
        self.resume_marker = 0
        # Admission-FIFO stamp across waiting+swapped (scheduler._admit).
        self.queue_stamp = 0
        # Disagg KV handoff (docs/disagg.md): the router-stamped
        # kv_transfer_params for this request ({"request_id", "role"?}),
        # or None. On a producer engine the streamed publisher ships this
        # sequence's pages per prefill chunk under that id; the cursor
        # tracks how many committed blocks have been handed to it.
        self.kv_transfer = kv_transfer
        self.kv_published_cursor = 0

        # Per-request cost attribution (docs/observability.md "Cost
        # attribution"): device-seconds this request was charged — prefill
        # steps charge a token-weighted share, decode bursts/spec verifies
        # charge an active-row share (shares sum to the step wall, so a
        # mixed run's request costs sum to the device-busy wall and
        # pipelined continuations can never double-count). kv page-seconds
        # integrate len(block_ids) over wall time between charge points.
        self.cost_prefill_s = 0.0
        self.cost_decode_s = 0.0
        self.cost_kv_page_s = 0.0
        self._kv_cost_mark: Optional[float] = None

    # -- lengths ----------------------------------------------------------

    @property
    def tier_rank(self) -> int:
        """0 = interactive (served first), 1 = batch."""
        return 1 if self.tenant_class == "batch" else 0

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_token_ids)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_token_ids) + len(self.output_token_ids)

    @property
    def all_token_ids(self) -> List[int]:
        return self.prompt_token_ids + self.output_token_ids

    @property
    def in_prefill(self) -> bool:
        return self.num_computed_tokens < self.num_prompt_tokens and not (
            self.output_token_ids
        )

    @property
    def is_finished(self) -> bool:
        return self.status == SequenceStatus.FINISHED

    def deadline_expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (now if now is not None else time.monotonic()) >= self.deadline

    # -- cost attribution -------------------------------------------------

    def charge_kv_pages(self, now: Optional[float] = None) -> None:
        """Integrate KV residency since the last charge point:
        ``kv_page_s += pages_held * elapsed``. Called at every step that
        touches this sequence and once more at finish, so the integral
        tracks page-count changes at step granularity."""
        now = now if now is not None else time.monotonic()
        mark = self._kv_cost_mark
        if mark is not None and self.block_ids:
            self.cost_kv_page_s += len(self.block_ids) * max(now - mark, 0.0)
        self._kv_cost_mark = now

    def cost_snapshot(self, now: Optional[float] = None) -> dict:
        """The request's accumulated cost, for the ``X-PST-Cost`` header /
        usage extension and the tenant chip-time meter."""
        now = now if now is not None else time.monotonic()
        queue_s = (
            self.first_scheduled_time - self.arrival_time
            if self.first_scheduled_time is not None
            else now - self.arrival_time
        )
        return {
            "prefill_device_s": round(self.cost_prefill_s, 6),
            "decode_device_s": round(self.cost_decode_s, 6),
            "device_s": round(self.cost_prefill_s + self.cost_decode_s, 6),
            "kv_page_s": round(self.cost_kv_page_s, 3),
            "queue_s": round(max(queue_s, 0.0), 6),
        }

    # -- KV paging --------------------------------------------------------

    def blocks_needed(self, up_to_tokens: int, block_size: int) -> int:
        """How many new pages are needed to hold KV for ``up_to_tokens``."""
        want = -(-up_to_tokens // block_size)
        return max(0, want - len(self.block_ids))

    def commit_full_blocks(
        self, allocator: BlockAllocator, allow_swap: bool = True
    ) -> None:
        """Content-address every newly-filled page (enables prefix sharing).
        ``allow_swap=False`` while this sequence is part of an in-flight
        pipelined burst (the device still writes through these page ids)."""
        bs = allocator.block_size
        toks = self.all_token_ids
        n_full = self.num_computed_tokens // bs
        while self._committed_blocks < n_full:
            i = self._committed_blocks
            h = block_hashes(toks[i * bs : (i + 1) * bs], bs, parent=self._last_hash)[0]
            self.block_ids[i] = allocator.commit(
                self.block_ids[i], h, allow_swap=allow_swap
            )
            self.block_hashes.append(h)
            self._last_hash = h
            self._committed_blocks += 1

    def commit_full_chunks(self, chunk_tokens: int) -> List[int]:
        """Chunk-granularity hashes of newly computed prefix (controller
        registration — the router's KV-aware lookup speaks these)."""
        toks = self.all_token_ids
        n_full = self.num_computed_tokens // chunk_tokens
        new: List[int] = []
        while self._chunk_cursor < n_full:
            i = self._chunk_cursor
            h = block_hashes(
                toks[i * chunk_tokens : (i + 1) * chunk_tokens],
                chunk_tokens,
                parent=self._chunk_last_hash,
            )[0]
            new.append(h)
            self._chunk_last_hash = h
            self._chunk_cursor += 1
        return new

    def adopt_cached_prefix(self, blocks: List[int], hashes: List[int]) -> None:
        """Install prefix-cache-hit pages found at admission time."""
        assert not self.block_ids
        self.block_ids = list(blocks)
        self.block_hashes = list(hashes)
        self._committed_blocks = len(blocks)
        self._last_hash = hashes[-1] if hashes else 0
        # caller sets num_computed_tokens (= len(blocks) * block_size)

    def reset_for_recompute(self) -> None:
        """Preemption: KV pages were surrendered; recompute from scratch."""
        # Close the KV cost clock: pages were charged up to the last
        # dispatch, and the preempted gap holds ZERO pages — leaving the
        # mark set would bill the post-recompute page count over the
        # whole wait (systematic overcharge of preempted tenants).
        self._kv_cost_mark = None
        self.block_ids = []
        self.num_computed_tokens = 0
        self.num_cached_prompt_tokens = 0
        self.block_hashes = []
        self._committed_blocks = 0
        self._last_hash = self.cache_salt
        self._chunk_cursor = 0
        self._chunk_last_hash = 0
        self.status = SequenceStatus.PREEMPTED
