"""KV tiering: HBM → host DRAM → remote store (the LMCache-analogue layer).

Reference mechanism (SURVEY.md §2.4 "KV-cache tiering"): LMCache hooks vLLM's
paged allocator and spills KV to CPU RAM (`cpuOffloadingBufferSize` →
`LMCACHE_LOCAL_CPU`, `deployment-vllm-multi.yaml:301-308`), local disk, and a
remote TCP server (`LMCACHE_REMOTE_URL`, `:313-318`). TPU-native version:

- :class:`HostKVPool` — pinned host-DRAM page pool keyed by the same
  prefix-committing block hashes the HBM allocator uses (one hashing scheme
  across tiers, router, and controller — ``kvcache/hashing.py``).
- :class:`RemoteKVClient` — HTTP client for the remote block store
  (:mod:`production_stack_tpu.kvserver.server`); device→host DMA then DCN,
  the TPU replacement for NIXL/GPUDirect.
- :class:`TieredAllocator` — a :class:`BlockAllocator` whose evictions spill
  down-tier and whose ``match_prefix`` faults pages back *up*-tier (host or
  remote hit → allocate HBM page → upload → extend the match). The scheduler
  is tier-oblivious.
"""

from __future__ import annotations

import collections
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..kvcache.hashing import block_hashes
from ..logging_utils import init_logger
from ..obs.metrics import note_integrity_failure, observe_stage
from .kv_manager import BlockAllocator, NoFreeBlocksError

logger = init_logger(__name__)

# Bounded retry for idempotent GETs (docs/kvserver.md "Degradation"):
# one extra attempt with a jittered pause, still under the caller's
# per-call deadline — a transient kvserver blip (restart, dropped
# connection) no longer forces a whole-prompt recompute fallback. Puts
# stay single-shot: the publisher/spill paths have their own retry-free
# best-effort contract and replication covers them.
GET_RETRY_ATTEMPTS = 2
_RETRY_BACKOFF_S = (0.02, 0.08)


def create_remote_client(
    url: str, replication: int = 2, timeout: float = 5.0
):
    """The engine's remote-KV client factory: a single base URL builds the
    plain :class:`RemoteKVClient`; a comma-separated shard list builds the
    replicated :class:`~production_stack_tpu.kvserver.sharded.ShardedKVClient`
    over per-shard clients (same call surface — the allocator, publisher
    and prefetcher are shard-oblivious)."""
    urls = [u.strip() for u in (url or "").split(",") if u.strip()]
    if not urls:
        return None
    if len(urls) == 1:
        return RemoteKVClient(urls[0], timeout=timeout)
    from ..kvserver.sharded import ShardedKVClient

    return ShardedKVClient(urls, replication=replication, timeout=timeout)


class HostKVPool:
    """LRU pool of KV pages in host DRAM, keyed by block hash."""

    def __init__(self, max_blocks: int):
        self.max_blocks = max_blocks
        self._pages: "collections.OrderedDict[int, Tuple[np.ndarray, np.ndarray]]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()
        self.bytes_used = 0

    def __len__(self) -> int:
        return len(self._pages)

    def put(self, h: int, k: np.ndarray, v: np.ndarray) -> None:
        with self._lock:
            if h in self._pages:
                self._pages.move_to_end(h)
                return
            while len(self._pages) >= self.max_blocks:
                _, (ek, ev) = self._pages.popitem(last=False)
                self.bytes_used -= ek.nbytes + ev.nbytes
            self._pages[h] = (k, v)
            self.bytes_used += k.nbytes + v.nbytes

    def get(self, h: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            item = self._pages.get(h)
            if item is not None:
                self._pages.move_to_end(h)
            return item

    def contains(self, h: int) -> bool:
        with self._lock:
            return h in self._pages


class RemoteKVClient:
    """Blocking HTTP client for the remote KV block server (engine thread).

    Every call is bounded by ``timeout`` (connect + read — a hung kvserver
    must surface as a tier miss, never hang the engine step thread), and
    callers on a request deadline can tighten it per call so a block fetch
    never outlives the request's remaining budget.
    """

    def __init__(self, base_url: str, timeout: float = 5.0):
        import requests

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._session = requests.Session()
        # Plain-int audit counters surfaced through LLMEngine.stats()
        # (kv_integrity_failures_total / kv_remote_retries_total);
        # read_repairs stays 0 here — repair needs replicas, which the
        # ShardedKVClient wrapper owns.
        self.counters: Dict[str, int] = {
            "integrity_failures": 0,
            "retries": 0,
            "read_repairs": 0,
        }

    def _effective_timeout(self, timeout: Optional[float]) -> float:
        if timeout is None:
            return self.timeout
        return max(min(self.timeout, timeout), 0.001)

    def _retry_pause(self, deadline: float) -> bool:
        """Jittered backoff before a GET's second attempt; False when the
        remaining per-call budget cannot cover the pause."""
        backoff = random.uniform(*_RETRY_BACKOFF_S)
        if deadline - time.monotonic() <= backoff:
            return False
        self.counters["retries"] += 1
        # pstlint: disable=async-blocking(20-80 ms retry backoff inside the blocking RemoteKVClient, which engine code only calls from step/worker/executor threads — never on an event loop; the pause is pre-checked against the caller's per-call deadline)
        time.sleep(backoff)
        return True

    def _quarantine(self, hashes: Sequence[int]) -> None:
        """Tell the server to drop copies a digest check proved rotten —
        best-effort (the store also LRU-ages them out eventually)."""
        try:
            self._session.post(
                f"{self.base_url}/admin/quarantine",
                json={"hashes": [int(h) for h in hashes]},
                timeout=min(self.timeout, 2.0),
            )
        except Exception as e:  # noqa: BLE001
            logger.debug("quarantine report failed: %s", e)

    def _note_corrupt(self, hashes: Sequence[int], source: str) -> None:
        self.counters["integrity_failures"] += len(hashes)
        note_integrity_failure(source, len(hashes))
        logger.warning(
            "remote KV digest mismatch on %s (%d block(s), source=%s): "
            "quarantining replica copies", self.base_url, len(hashes), source,
        )
        self._quarantine(hashes)

    def put(
        self, h: int, k: np.ndarray, v: np.ndarray,
        timeout: Optional[float] = None,
    ) -> bool:
        try:
            payload = _serialize_page(k, v)
            r = self._session.put(
                f"{self.base_url}/blocks/{h}",
                data=payload,
                headers={"Content-Type": "application/octet-stream"},
                timeout=self._effective_timeout(timeout),
            )
            return r.status_code == 200
        except Exception as e:  # noqa: BLE001 — remote tier is best-effort
            logger.debug("remote KV put failed: %s", e)
            return False

    def get(
        self, h: int, timeout: Optional[float] = None,
        source: str = "restore",
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        page, _status = self.get_ex(h, timeout=timeout, source=source)
        return page

    def get_ex(
        self, h: int, timeout: Optional[float] = None,
        source: str = "restore",
    ) -> Tuple[Optional[Tuple[np.ndarray, np.ndarray]], str]:
        """``(page, status)`` — status ``ok`` | ``miss`` | ``corrupt`` |
        ``error``, so a replicated wrapper can tell a healthy miss (try
        the next owner, no breaker penalty) from a dead shard (breaker
        feed). The served digest (``X-PST-Digest``) is verified before
        the page is deserialized; a mismatch quarantines this replica's
        copy and reads as a miss to plain callers."""
        deadline = time.monotonic() + self._effective_timeout(timeout)
        status = "error"
        for _attempt in range(GET_RETRY_ATTEMPTS):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                r = self._session.get(
                    f"{self.base_url}/blocks/{h}", timeout=remaining
                )
            except Exception as e:  # noqa: BLE001
                logger.debug("remote KV get failed: %s", e)
                status = "error"
                if not self._retry_pause(deadline):
                    break
                continue
            if r.status_code == 404:
                return None, "miss"
            if r.status_code != 200:
                status = "error"
                if not self._retry_pause(deadline):
                    break
                continue
            digest_hex = r.headers.get("X-PST-Digest")
            if digest_hex:
                from ..kvserver.server import block_digest

                try:
                    expected = bytes.fromhex(digest_hex)
                except ValueError:
                    expected = b""
                if block_digest(r.content) != expected:
                    self._note_corrupt([h], source)
                    return None, "corrupt"
            return _deserialize_page(r.content), "ok"
        return None, status

    # -- batched endpoints (docs/disagg.md: one round trip for N pages) ---

    # Byte budget per batched POST /blocks: safely under the kvserver's
    # 256 MiB client_max_size even for large per-page serde (big models).
    BATCH_PUT_MAX_BYTES = 64 << 20

    def put_blocks(
        self,
        pages: Sequence[Tuple[int, np.ndarray, np.ndarray]],
        timeout: Optional[float] = None,
    ) -> bool:
        """Ship N pages in batched ``POST /blocks`` round trips (the
        streamed-handoff and finish-push transfer primitive — the
        per-block PUT loop it replaces paid one DCN round trip per page).
        Batches are bounded by BYTES, not page count: a count-only bound
        could exceed the server's request-size cap for large pages and
        silently drop the whole batch."""
        if not pages:
            return True
        try:
            batch: list = []
            batch_bytes = 0
            for h, k, v in pages:
                data = _serialize_page(k, v)
                if batch and batch_bytes + len(data) > self.BATCH_PUT_MAX_BYTES:
                    if not self._post_block_batch(batch, timeout):
                        return False
                    batch, batch_bytes = [], 0
                batch.append((h, data))
                batch_bytes += len(data)
            return self._post_block_batch(batch, timeout)
        except Exception as e:  # noqa: BLE001 — remote tier is best-effort
            logger.debug("remote KV batched put failed: %s", e)
            return False

    def _post_block_batch(self, batch, timeout: Optional[float]) -> bool:
        from ..kvserver.server import pack_blocks

        if not batch:
            return True
        r = self._session.post(
            f"{self.base_url}/blocks",
            data=pack_blocks(batch),
            headers={"Content-Type": "application/octet-stream"},
            timeout=self._effective_timeout(timeout),
        )
        return r.status_code == 200

    def get_blocks(
        self, hashes: Sequence[int], timeout: Optional[float] = None,
        source: str = "match_prefix",
    ) -> "dict[int, Tuple[np.ndarray, np.ndarray]]":
        """Fetch up to N pages in ONE ``GET /blocks?hashes=`` round trip;
        absent hashes are simply missing from the result."""
        pages, _status = self.get_blocks_ex(
            hashes, timeout=timeout, source=source
        )
        return pages

    def get_blocks_ex(
        self, hashes: Sequence[int], timeout: Optional[float] = None,
        source: str = "match_prefix",
    ) -> Tuple["dict[int, Tuple[np.ndarray, np.ndarray]]", str]:
        """``(pages, status)`` — status ``ok`` (the round trip completed;
        absent hashes are genuine misses) or ``error`` (the shard never
        answered). Every frame is digest-verified; corrupt blocks are
        dropped from the result, counted, and quarantined on the server —
        to the caller they look like misses (failover / recompute),
        never like pages."""
        if not hashes:
            return {}, "ok"
        from ..kvserver.server import unpack_blocks

        deadline = time.monotonic() + self._effective_timeout(timeout)
        for _attempt in range(GET_RETRY_ATTEMPTS):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                r = self._session.get(
                    f"{self.base_url}/blocks",
                    params={"hashes": ",".join(str(int(h)) for h in hashes)},
                    timeout=remaining,
                )
                if r.status_code != 200:
                    raise RuntimeError(f"status {r.status_code}")
                corrupt: List[int] = []
                pages = {
                    h: _deserialize_page(data)
                    for h, data in unpack_blocks(r.content, corrupt)
                }
                if corrupt:
                    self._note_corrupt(corrupt, source)
                return pages, "ok"
            except Exception as e:  # noqa: BLE001
                logger.debug("remote KV batched get failed: %s", e)
                if not self._retry_pause(deadline):
                    break
        return {}, "error"

    # -- disagg-transfer manifests (request-id-keyed; docs/disagg.md) -----

    def post_manifest(
        self,
        request_id: str,
        hashes: Sequence[int],
        complete: bool = False,
        total_blocks: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> bool:
        try:
            r = self._session.post(
                f"{self.base_url}/manifests/{request_id}",
                json={
                    "hashes": [int(h) for h in hashes],
                    "complete": bool(complete),
                    "total_blocks": total_blocks,
                },
                timeout=self._effective_timeout(timeout),
            )
            return r.status_code == 200
        except Exception as e:  # noqa: BLE001
            logger.debug("manifest post failed: %s", e)
            return False

    def get_manifest(
        self,
        request_id: str,
        wait_s: float = 0.0,
        have: int = -1,
        timeout: Optional[float] = None,
    ) -> Optional[dict]:
        """Manifest view (``None`` = unknown request id / server down).
        ``wait_s`` long-polls server-side for progress past ``have``."""
        try:
            eff = self._effective_timeout(timeout)
            r = self._session.get(
                f"{self.base_url}/manifests/{request_id}",
                params={"wait_s": wait_s, "have": have},
                # The long poll must be allowed to run its course: the
                # read timeout covers the poll window plus slack.
                timeout=max(eff, wait_s + 2.0),
            )
            if r.status_code != 200:
                return None
            return r.json()
        except Exception as e:  # noqa: BLE001
            logger.debug("manifest get failed: %s", e)
            return None


# v2: per-page host layout changed to [L, bs, KH, hd] (head-folded combined
# device pages); v1 pages ([L, KH, bs, hd]) are layout-incompatible and must
# not be faulted in across an upgrade.
_MAGIC = b"PSTKV2\x00\x00"


def _serialize_page(k: np.ndarray, v: np.ndarray) -> bytes:
    """Self-describing page serde (the LMCache 'serde' role): header carries
    dtype + shape; body is raw K then V bytes."""
    import json as _json

    header = _json.dumps(
        {"dtype": str(k.dtype), "shape": list(k.shape)}
    ).encode()
    return (
        _MAGIC
        + len(header).to_bytes(4, "little")
        + header
        + np.ascontiguousarray(k).tobytes()
        + np.ascontiguousarray(v).tobytes()
    )


def _deserialize_page(buf: bytes) -> Tuple[np.ndarray, np.ndarray]:
    import json as _json

    assert buf[:8] == _MAGIC, "bad KV page magic"
    hlen = int.from_bytes(buf[8:12], "little")
    header = _json.loads(buf[12 : 12 + hlen].decode())
    dtype = np.dtype(header["dtype"])
    shape = tuple(header["shape"])
    body = buf[12 + hlen :]
    n = dtype.itemsize * int(np.prod(shape))
    k = np.frombuffer(body[:n], dtype=dtype).reshape(shape)
    v = np.frombuffer(body[n : 2 * n], dtype=dtype).reshape(shape)
    return k, v


class TieredAllocator(BlockAllocator):
    """HBM allocator with spill-down / fault-up across host and remote tiers.

    ``page_io`` is the runner adapter exposing ``download_page(blk)`` and
    ``upload_page(blk, k, v)`` (device DMA endpoints).
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        page_io,
        host_blocks: int = 0,
        remote: Optional[RemoteKVClient] = None,
        enable_prefix_caching: bool = True,
    ):
        super().__init__(
            num_blocks,
            block_size,
            enable_prefix_caching=enable_prefix_caching,
            on_evict=self._spill,
        )
        self.page_io = page_io
        self.host_pool = HostKVPool(host_blocks) if host_blocks > 0 else None
        self.remote = remote
        # Tier KPIs (exported as lmcache-dashboard-style metrics).
        self.host_hit_blocks = 0
        self.remote_hit_blocks = 0
        self.spilled_blocks = 0
        self.remote_push_drops = 0
        # Remote pushes ride a bounded queue + worker thread: eviction sits
        # on the decode critical path and must never wait on DCN/HTTP.
        self._push_queue: "collections.deque[Tuple[int, np.ndarray, np.ndarray]]" = (
            collections.deque(maxlen=256)
        )
        self._push_event = threading.Event()
        self._push_stop = threading.Event()
        self._push_thread: Optional[threading.Thread] = None
        if remote is not None:
            self._push_thread = threading.Thread(
                target=self._push_worker, name="kv-remote-push", daemon=True
            )
            self._push_thread.start()

    # -- spill down -------------------------------------------------------

    def _spill(self, blk: int, h: int) -> None:
        if self.host_pool is None and self.remote is None:
            return
        k, v = self.page_io.download_page(blk)
        if self.host_pool is not None:
            self.host_pool.put(h, k, v)
        if self.remote is not None:
            if len(self._push_queue) == self._push_queue.maxlen:
                self.remote_push_drops += 1  # deque evicts the oldest entry
            self._push_queue.append((h, k, v))
            self._push_event.set()
        self.spilled_blocks += 1

    def _push_worker(self) -> None:
        while not self._push_stop.is_set():
            batch = []
            try:
                # Drain whatever spilled since the last pass into ONE
                # batched POST (bounded by the queue length) — spill bursts
                # used to pay one DCN round trip per page.
                while len(batch) < 64:
                    batch.append(self._push_queue.popleft())
            except IndexError:
                pass
            if not batch:
                self._push_event.wait(timeout=1.0)
                self._push_event.clear()
                continue
            self.remote.put_blocks(batch)  # best-effort; client logs failures

    def shutdown(self) -> None:
        """Stop the push worker (sleep level 2 rebuilds the allocator; without
        this, every sleep/wake cycle would leak one kv-remote-push thread)."""
        self._push_stop.set()
        self._push_event.set()
        if self._push_thread is not None:
            self._push_thread.join(timeout=2.0)
            self._push_thread = None

    # -- fault up ---------------------------------------------------------

    def _fetch_lower_tier(
        self, h: int, deadline: Optional[float] = None
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """``deadline`` is a monotonic expiry (Sequence.deadline): the host
        pool is always consulted (memcpy-fast), but a remote fetch is
        bounded by the remaining budget and skipped entirely once the
        budget is gone — recomputing the prefix beats blocking an expired
        request's shed on a DCN round trip."""
        if self.host_pool is not None:
            t0 = time.monotonic()
            page = self.host_pool.get(h)
            if page is not None:
                self.host_hit_blocks += 1
                observe_stage("engine", "kv_fetch_host", time.monotonic() - t0)
                return page
        if self.remote is not None:
            remaining: Optional[float] = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
            t0 = time.monotonic()
            page = self.remote.get(h, timeout=remaining)
            # Hit or miss, a DCN round trip happened: both belong in the
            # kv_fetch_remote latency decomposition.
            observe_stage("engine", "kv_fetch_remote", time.monotonic() - t0)
            if page is not None:
                self.remote_hit_blocks += 1
                if self.host_pool is not None:  # promote to the warmer tier
                    self.host_pool.put(h, *page)
                return page
        return None

    def acquire_resident(self, h: int) -> Optional[int]:
        """HBM hit, else fault the page up from host DRAM / remote store."""
        blk = self.acquire_cached(h)
        if blk is not None:
            return blk
        page = self._fetch_lower_tier(h)
        if page is None:
            return None
        try:
            blk = self.allocate()
        except NoFreeBlocksError:
            return None
        self.page_io.upload_page(blk, *page)
        return self.commit(blk, h)

    def _remote_batch_fetch(
        self, hashes: Sequence[int], deadline: Optional[float]
    ) -> "dict[int, Tuple[np.ndarray, np.ndarray]]":
        """One batched ``GET /blocks?hashes=`` for every hash not already
        resident in HBM or the host pool — the remote leg of match_prefix
        used to issue one sync HTTP call per page inside the walk."""
        if self.remote is None:
            return {}
        wanted = [
            h for h in hashes
            if self._block_of_hash.get(h) is None
            and (self.host_pool is None or not self.host_pool.contains(h))
        ]
        if not wanted:
            return {}
        remaining: Optional[float] = None
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {}
        t0 = time.monotonic()
        pages = self.remote.get_blocks(wanted, timeout=remaining)
        observe_stage("engine", "kv_fetch_remote", time.monotonic() - t0)
        self.remote_hit_blocks += len(pages)
        if self.host_pool is not None:  # promote to the warmer tier
            for h, (k, v) in pages.items():
                self.host_pool.put(h, k, v)
        return pages

    def match_prefix(
        self,
        token_ids: Sequence[int],
        salt: int = 0,
        deadline: Optional[float] = None,
    ) -> Tuple[List[int], List[int]]:
        self.query_tokens += len(token_ids)
        if not self.enable_prefix_caching:
            return [], []
        hashes = block_hashes(token_ids, self.block_size, parent=salt)
        fetched: "dict[int, Tuple[np.ndarray, np.ndarray]]" = {}
        fetch_attempted = False
        matched: List[int] = []
        matched_hashes: List[int] = []
        for i, h in enumerate(hashes):
            blk = self.acquire_cached(h)
            if blk is None:
                page = fetched.pop(h, None)
                if page is None and self.host_pool is not None:
                    t0 = time.monotonic()
                    page = self.host_pool.get(h)
                    if page is not None:
                        self.host_hit_blocks += 1
                        observe_stage(
                            "engine", "kv_fetch_host", time.monotonic() - t0
                        )
                if (
                    page is None
                    and self.remote is not None
                    and not fetch_attempted
                ):
                    # First miss below the host tier: batch-fetch the whole
                    # remaining suffix in ONE round trip, then keep walking
                    # — and never re-fetch: a hash absent from that reply
                    # is a genuine remote miss.
                    fetch_attempted = True
                    fetched = self._remote_batch_fetch(hashes[i:], deadline)
                    page = fetched.pop(h, None)
                if page is None:
                    break
                try:
                    blk = self.allocate()
                except NoFreeBlocksError:
                    break
                self.page_io.upload_page(blk, *page)
                blk = self.commit(blk, h)
            matched.append(blk)
            matched_hashes.append(h)
        self.hit_tokens += len(matched) * self.block_size
        return matched, matched_hashes
