"""Gateway API integration: Envoy ext-proc endpoint-picker shim."""
