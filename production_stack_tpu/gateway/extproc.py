"""Envoy ext-proc endpoint-picker service (`pst-extproc`).

The actual wire protocol a Gateway API inference-extension deployment
consults: Envoy's ext_proc filter opens a gRPC
``envoy.service.ext_proc.v3.ExternalProcessor/Process`` stream per HTTP
request, sends the request headers and (buffered) body, and applies the
header mutations we return before routing. The reference's pickers live
inside the Go endpoint-picker framework speaking exactly this protocol
(`/root/reference/src/gateway_inference_extension/prefix_aware_picker.go:27`);
here the protocol front-end is this Python service and the picking policies
stay in the native C++ ``pst-picker`` (`operator/src/picker_main.cc`), which
it consults over its ``POST /pick`` API.

Flow per request stream:
  1. ``request_headers`` → CONTINUE (ask Envoy for the body next).
  2. ``request_body`` (end_of_stream) → parse the OpenAI JSON, extract the
     prompt text exactly like the router's prefix policy
     (``router/routing/logic.py`` extract_prompt_text), call the picker,
     and return a header mutation setting ``x-gateway-destination-endpoint``
     (the inference-extension contract: the gateway's original-destination
     cluster routes on that header).

Wire stubs: ``extproc_pb2`` is protoc-generated from
``gateway/proto/extproc.proto`` — a hand-trimmed, field-number-compatible
subset of the public Envoy API (see that file's provenance note).
"""

from __future__ import annotations

import argparse
import json
import logging
import socket
import urllib.request
from concurrent import futures
from typing import Iterator, List, Optional

import grpc

from ..router.routing.logic import extract_prompt_text
from . import extproc_pb2 as pb2

logger = logging.getLogger("pst.extproc")

SERVICE = "envoy.service.ext_proc.v3.ExternalProcessor"
DEST_HEADER = "x-gateway-destination-endpoint"


class PickerClient:
    """Resolves the pod set and asks pst-picker's /pick for an endpoint."""

    def __init__(
        self,
        picker_url: str,
        policy: Optional[str] = None,
        pods: Optional[List[dict]] = None,
        pods_dns: Optional[str] = None,
        pods_port: int = 8000,
        timeout: float = 2.0,
    ):
        self.picker_url = picker_url.rstrip("/")
        self.policy = policy
        self.static_pods = pods or []
        self.pods_dns = pods_dns
        self.pods_port = pods_port
        self.timeout = timeout

    def resolve_pods(self) -> List[dict]:
        if self.static_pods:
            return self.static_pods
        if self.pods_dns:
            # Headless-service lookup: one A record per engine pod (the
            # K8s-native analogue of the EPP's InferencePool pod watch).
            try:
                infos = socket.getaddrinfo(
                    self.pods_dns, self.pods_port, proto=socket.IPPROTO_TCP
                )
                addrs = sorted({i[4][0] for i in infos})
                return [
                    {"name": a, "address": f"{a}:{self.pods_port}"}
                    for a in addrs
                ]
            except OSError as e:
                logger.warning("pod DNS resolve failed: %s", e)
        return []

    def pick(self, model: str, prompt: str) -> Optional[str]:
        pods = self.resolve_pods()
        if not pods:
            return None
        payload = {"model": model, "prompt": prompt, "pods": pods}
        if self.policy:
            payload["policy"] = self.policy
        req = urllib.request.Request(
            self.picker_url + "/pick",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — fall through to no-mutation
            logger.warning("picker /pick failed: %s", e)
            return None
        name = out.get("pod")
        for p in pods:
            if p.get("name") == name:
                return p.get("address") or name
        return name


def _continue_headers() -> pb2.ProcessingResponse:
    return pb2.ProcessingResponse(
        request_headers=pb2.HeadersResponse(
            response=pb2.CommonResponse(
                status=pb2.CommonResponse.CONTINUE
            )
        )
    )


def _body_response(endpoint: Optional[str]) -> pb2.ProcessingResponse:
    common = pb2.CommonResponse(status=pb2.CommonResponse.CONTINUE)
    if endpoint:
        common.header_mutation.set_headers.append(
            pb2.HeaderValueOption(
                header=pb2.HeaderValue(
                    key=DEST_HEADER, raw_value=endpoint.encode()
                )
            )
        )
    return pb2.ProcessingResponse(
        request_body=pb2.BodyResponse(response=common)
    )


class ExtProcHandler:
    """One instance serves all streams; per-stream state is local."""

    def __init__(self, picker: PickerClient):
        self.picker = picker

    def process(
        self, request_iterator: Iterator[pb2.ProcessingRequest], context
    ) -> Iterator[pb2.ProcessingResponse]:
        for msg in request_iterator:
            kind = msg.WhichOneof("request")
            if kind == "request_headers":
                if msg.request_headers.end_of_stream:
                    # Bodyless request (GET): nothing to hash — still pick
                    # so round-robin style policies work.
                    endpoint = self.picker.pick("", "")
                    resp = _continue_headers()
                    if endpoint:
                        resp.request_headers.response.header_mutation.set_headers.append(
                            pb2.HeaderValueOption(
                                header=pb2.HeaderValue(
                                    key=DEST_HEADER,
                                    raw_value=endpoint.encode(),
                                )
                            )
                        )
                    yield resp
                else:
                    yield _continue_headers()
            elif kind == "request_body":
                body = msg.request_body.body
                model, prompt = "", ""
                try:
                    req_json = json.loads(body) if body else {}
                    model = str(req_json.get("model", ""))
                    prompt = extract_prompt_text(req_json)
                except (ValueError, TypeError):
                    logger.warning("unparseable request body (%d bytes)", len(body))
                yield _body_response(self.picker.pick(model, prompt))
            elif kind in ("response_headers", "response_body"):
                # Pass-through: we only steer requests.
                if kind == "response_headers":
                    yield pb2.ProcessingResponse(
                        response_headers=pb2.HeadersResponse()
                    )
                else:
                    yield pb2.ProcessingResponse(
                        response_body=pb2.BodyResponse()
                    )
            else:
                # Unhandled message kind (e.g. request_trailers sent by a
                # processing mode the trimmed proto doesn't model —
                # WhichOneof returns None). Envoy matches response oneof to
                # request oneof, so answering with a headers response would
                # be a protocol error; we also can't build the right oneof
                # (the trimmed proto lacks it). Close the stream cleanly:
                # Envoy then continues the HTTP request without further
                # external processing instead of stalling on a reply.
                logger.warning(
                    "unhandled ext-proc message kind %r: closing stream "
                    "(request proceeds unprocessed)", kind,
                )
                return


def make_server(picker: PickerClient, port: int, max_workers: int = 16):
    """grpc.Server wired via generic handlers (no generated service stubs —
    grpc_tools is not in the image; the method path + message framing are
    what matter on the wire)."""
    handler = ExtProcHandler(picker)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    rpc = grpc.stream_stream_rpc_method_handler(
        handler.process,
        request_deserializer=pb2.ProcessingRequest.FromString,
        response_serializer=pb2.ProcessingResponse.SerializeToString,
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, {"Process": rpc}),)
    )
    bound = server.add_insecure_port(f"[::]:{port}")
    return server, bound


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=9002)
    p.add_argument("--picker-url", default="http://localhost:9001")
    p.add_argument(
        "--policy", default=None,
        help="override pst-picker's default policy per pick",
    )
    p.add_argument(
        "--pods", default=None,
        help="static pod list name=addr,name=addr (else --pods-dns)",
    )
    p.add_argument(
        "--pods-dns", default=None,
        help="headless service name resolving to engine pod IPs",
    )
    p.add_argument("--pods-port", type=int, default=8000)
    args = p.parse_args(argv)

    pods = None
    if args.pods:
        pods = []
        for ent in args.pods.split(","):
            name, _, addr = ent.partition("=")
            pods.append({"name": name, "address": addr or name})
    picker = PickerClient(
        args.picker_url, args.policy, pods, args.pods_dns, args.pods_port
    )
    logging.basicConfig(level=logging.INFO)
    server, bound = make_server(picker, args.port)
    server.start()
    logger.info("pst-extproc listening on :%d -> %s", bound, args.picker_url)
    server.wait_for_termination()


if __name__ == "__main__":
    main()
