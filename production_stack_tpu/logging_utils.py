"""Colored, leveled logging shared by router/engine/kvserver.

Behavioral parity with the reference router's logger
(``src/vllm_router/log.py:44-60``): per-level ANSI colors, INFO and below
to stdout, WARNING and above to stderr; idempotent handler install.
The implementation is our own.
"""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\033[36m",     # cyan
    logging.INFO: "\033[32m",      # green
    logging.WARNING: "\033[33m",   # yellow
    logging.ERROR: "\033[31m",     # red
    logging.CRITICAL: "\033[1;31m",  # bold red
}
_RESET = "\033[0m"

_FMT = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"
_DATEFMT = "%H:%M:%S"


class _ColorFormatter(logging.Formatter):
    def __init__(self, fmt: str, datefmt: str, stream) -> None:
        super().__init__(fmt, datefmt)
        self._stream = stream

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        color = _COLORS.get(record.levelno, "")
        if color and self._stream.isatty():
            return f"{color}{base}{_RESET}"
        return base


class _BelowWarning(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno < logging.WARNING


def init_logger(name: str, level: int = logging.INFO) -> logging.Logger:
    """Return a logger with colored stdout/stderr split handlers."""
    logger = logging.getLogger(name)
    if getattr(logger, "_pst_configured", False):
        logger.setLevel(level)
        return logger
    logger.setLevel(level)
    logger.propagate = False

    out = logging.StreamHandler(sys.stdout)
    out.addFilter(_BelowWarning())
    out.setFormatter(_ColorFormatter(_FMT, _DATEFMT, sys.stdout))
    err = logging.StreamHandler(sys.stderr)
    err.setLevel(logging.WARNING)
    err.setFormatter(_ColorFormatter(_FMT, _DATEFMT, sys.stderr))

    logger.addHandler(out)
    logger.addHandler(err)
    logger._pst_configured = True  # type: ignore[attr-defined]
    return logger
