"""Colored, leveled logging shared by router/engine/kvserver.

Behavioral parity with the reference router's logger
(``src/vllm_router/log.py:44-60``): per-level ANSI colors, INFO and below
to stdout, WARNING and above to stderr; idempotent handler install.
The implementation is our own.
"""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\033[36m",     # cyan
    logging.INFO: "\033[32m",      # green
    logging.WARNING: "\033[33m",   # yellow
    logging.ERROR: "\033[31m",     # red
    logging.CRITICAL: "\033[1;31m",  # bold red
}
_RESET = "\033[0m"

_FMT = "[%(asctime)s] %(levelname)s %(name)s: %(message)s"
_DATEFMT = "%H:%M:%S"


class _ColorFormatter(logging.Formatter):
    def __init__(self, fmt: str, datefmt: str, stream) -> None:
        super().__init__(fmt, datefmt)
        self._stream = stream

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        color = _COLORS.get(record.levelno, "")
        if color and self._stream.isatty():
            return f"{color}{base}{_RESET}"
        return base


class _BelowWarning(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        return record.levelno < logging.WARNING


# Structured-logging profile (obs/logging.py installs it): a factory
# producing the formatter for a given stream (None = the colored text
# default) plus an optional record filter (the hot-path sampler),
# applied to every init_logger logger — existing and future.
_FORMATTER_FACTORY = None
_RECORD_FILTER = None


def _make_formatter(stream) -> logging.Formatter:
    if _FORMATTER_FACTORY is not None:
        return _FORMATTER_FACTORY(stream)
    return _ColorFormatter(_FMT, _DATEFMT, stream)


def apply_log_profile(formatter_factory=None, record_filter=None) -> None:
    """Swap the formatter (and optional filter) on every logger this
    module configured, and remember both for loggers created later.
    Called by ``obs.logging.configure_logging``; with no arguments the
    colored text default is restored."""
    global _FORMATTER_FACTORY, _RECORD_FILTER
    old_filter = _RECORD_FILTER
    _FORMATTER_FACTORY = formatter_factory
    _RECORD_FILTER = record_filter
    for logger in logging.Logger.manager.loggerDict.values():
        if not getattr(logger, "_pst_configured", False):
            continue
        if old_filter is not None:
            logger.removeFilter(old_filter)
        if record_filter is not None:
            logger.addFilter(record_filter)
        for handler in logger.handlers:
            stream = getattr(handler, "stream", sys.stdout)
            handler.setFormatter(_make_formatter(stream))


def init_logger(name: str, level: int = logging.INFO) -> logging.Logger:
    """Return a logger with colored stdout/stderr split handlers."""
    logger = logging.getLogger(name)
    if getattr(logger, "_pst_configured", False):
        logger.setLevel(level)
        return logger
    logger.setLevel(level)
    logger.propagate = False

    out = logging.StreamHandler(sys.stdout)
    out.addFilter(_BelowWarning())
    out.setFormatter(_make_formatter(sys.stdout))
    err = logging.StreamHandler(sys.stderr)
    err.setLevel(logging.WARNING)
    err.setFormatter(_make_formatter(sys.stderr))

    logger.addHandler(out)
    logger.addHandler(err)
    if _RECORD_FILTER is not None:
        logger.addFilter(_RECORD_FILTER)
    logger._pst_configured = True  # type: ignore[attr-defined]
    return logger
