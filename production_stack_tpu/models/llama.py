"""Llama-architecture decoder in functional JAX with a paged KV cache.

This is the serving engine's compute core — the piece the reference stack
outsources to the vLLM container image (`helm/templates/
deployment-vllm-multi.yaml:101-118`). One architecture class covers the
Llama-3 / Llama-2 / Mistral / Qwen2 family: RMSNorm, rotary embeddings,
grouped-query attention, SwiGLU MLP, optional QKV biases (Qwen2), optional
tied embeddings.

Design notes (TPU-first):
- Params are a plain pytree with layers **stacked on a leading axis** and the
  forward pass is a single ``lax.scan`` over layers — one compiled layer body
  regardless of depth, fast XLA compiles even for 80-layer models.
- One unified forward for prefill and decode: tokens are ``[B, T]`` (decode is
  ``T=1``, prefill ``B=1`` chunks). KV is written into cache pages first, then
  attention reads through the block table, which makes prefix-cache hits and
  chunked prefill the same code path.
- Sharding is declarative: :func:`param_pspecs` / :func:`cache_pspec` return
  `PartitionSpec` trees (tp over heads/ffn, optional pp over the stacked layer
  axis); `jit` + `NamedSharding` lets XLA insert the ICI collectives. No
  NCCL analogue to manage.
- Matmuls accumulate in fp32 (``preferred_element_type``) with bf16 weights:
  MXU-native.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..logging_utils import init_logger
from ..ops.attention import paged_attention, window_eff
from ..parallel.mesh import AXIS_EXPERT, AXIS_PIPELINE, AXIS_TENSOR

logger = init_logger(__name__)

Params = Dict[str, Any]

# ----------------------------------------------------------------------------
# Weight-only int8 quantization (per-output-channel symmetric).
#
# The reference serves its 8B benchmark model on a 40 GiB A100
# (`tutorials/07-benchmark-multi-round-qa-single-gpu.md:5`); one v5e chip has
# 16 GiB, so bf16 8B weights (~16 GiB) cannot sit next to their KV. Weight-only
# int8 halves weight HBM (and decode's weight-read bandwidth, the decode-step
# floor) while keeping activations/accumulation in bf16/fp32 on the MXU:
# ``y = (x @ w_int8→bf16) * scale`` is exact for per-output-channel scales, and
# XLA fuses the int8→bf16 convert into the matmul's HBM read.
#
# The scale for quantized leaf ``w`` is stored as sibling leaf ``w_qs``.
# Matmul weights ([..., in, out] layout) quantize over their input dim
# (axis -2); embedding tables ([V, D]) over the hidden dim (axis -1) so one
# per-row scale serves both the lookup and the tied unembed.
# ----------------------------------------------------------------------------

QUANT_SUFFIX = "_qs"
QUANT4_SUFFIX = "_q4s"
QUANT4_GROUP = 128
QUANT_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
QUANT_TOP_KEYS = ("embed", "lm_head")


def quantize_leaf(w: jax.Array, axis: int = -2) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-channel int8: returns (int8 weights, fp32 scales).
    ``axis`` is the contraction (input) dim the scale reduces over."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / jnp.expand_dims(s, axis)), -127, 127)
    return q.astype(jnp.int8), s


def _q4_group(din: int) -> int:
    """Largest group size ≤ QUANT4_GROUP dividing the contraction dim (tiny
    debug models have dims < 128; real models hit 128 exactly)."""
    g = QUANT4_GROUP
    while din % g:
        g //= 2
        if g < 2:
            raise ValueError(f"int4 needs an even contraction dim, got {din}")
    return g


def quantize_leaf_int4(w: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric group-wise int4 over the contraction axis (-2), the
    AWQ/GPTQ-family layout (group size 128). Returns (packed int8
    [..., in/2, out] — even contraction rows in the low nibble, odd in the
    high — and fp32 scales [..., in/G, out]). Packed int8 (not jnp.int4):
    s4 arrays cannot cross jit boundaries on remote-attached backends."""
    wf = w.astype(jnp.float32)
    *lead, din, dout = wf.shape
    g = _q4_group(din)
    wg = wf.reshape(*lead, din // g, g, dout)
    amax = jnp.max(jnp.abs(wg), axis=-2)  # [..., G, out]
    s = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(wg / s[..., :, None, :]), -7, 7).astype(jnp.int8)
    q = q.reshape(*lead, din, dout)
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    packed = jnp.bitwise_or(
        jnp.bitwise_and(lo, jnp.int8(0x0F)), jnp.left_shift(hi, 4)
    )
    return packed, s


def dequant_int4(packed: jax.Array, scales: jax.Array, dtype) -> jax.Array:
    """Unpack + scale an int4 weight to the compute dtype. All ops here are
    elementwise/reshape on the packed array — XLA fuses them into the
    consuming dot's HBM read, so the stream stays 0.5 byte/weight."""
    lo = jnp.right_shift(jnp.left_shift(packed, 4), 4)  # sign-extended
    hi = jnp.right_shift(packed, 4)  # arithmetic shift
    w = jnp.stack([lo, hi], axis=-2)  # [..., in/2, 2, out]
    shape = w.shape[:-3] + (w.shape[-3] * 2, w.shape[-1])
    w = w.reshape(shape).astype(dtype)
    G = scales.shape[-2]
    g = shape[-2] // G
    w = w.reshape(shape[:-2] + (G, g, shape[-1])) * scales[
        ..., :, None, :
    ].astype(dtype)
    return w.reshape(shape)


def quantize_tree(params: Params, mode: str = "int8") -> Params:
    """Quantize all matmul weights of a loaded param tree in place.
    Used by the HF-checkpoint path (host-side); random-init presets use the
    streamed per-leaf path in the runner instead (never holds the bf16 tree).
    ``mode``: "int8" (per-channel) or "int4" (group-wise for the per-layer
    matmuls; embed/lm_head stay int8 — the gather and post-matmul-scale
    paths are exact there and the per-step byte win is negligible)."""
    layers = params["layers"]
    for k in QUANT_LAYER_KEYS:
        if k in layers:
            if mode == "int4":
                q, s = quantize_leaf_int4(layers[k])
                layers[k] = q
                layers[k + QUANT4_SUFFIX] = s
            else:
                q, s = quantize_leaf(layers[k], axis=-2)
                layers[k] = q
                layers[k + QUANT_SUFFIX] = s
    for k in QUANT_TOP_KEYS:
        if k in params:
            q, s = quantize_leaf(params[k], axis=-1)
            params[k] = q
            params[k + QUANT_SUFFIX] = s
    return params


def _wcast(w: jax.Array, dtype) -> jax.Array:
    """Weight operand for a matmul: int8 leaves convert on the fly (XLA
    fuses the convert into the dot's HBM read — the bandwidth saving is
    kept); everything else passes through."""
    return w.astype(dtype) if w.dtype == jnp.int8 else w


def _wmat(p: Params, name: str, dtype) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Matmul weight operand under any quantization mode.

    Returns (operand in compute dtype, post-matmul scale or None): int4
    leaves dequantize pre-matmul (group scales vary along the contraction
    dim, so no post-scale exists); int8 leaves convert on the fly — a bare
    convert XLA fuses into the dot's HBM read — and hand back their
    per-output-channel scale for the caller to apply post-matmul (exact).

    NOTE: the XLA int4 dequant does NOT fuse (the unpack's stack/reshape
    defeats operand fusion, materializing the bf16 weights per layer) —
    serving-shape int4 matmuls go through :func:`_qdot`'s Pallas kernel
    instead; this path remains for tiny/odd shapes and the MoE bank."""
    w = p[name]
    q4s = p.get(name + QUANT4_SUFFIX)
    if q4s is not None:
        return dequant_int4(w, q4s, dtype), None
    return _wcast(w, dtype), p.get(name + QUANT_SUFFIX)


def _qdot(
    x: jax.Array, p: Params, name: str
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """``x [..., din] @ weight`` under any quantization mode. Returns
    (fp32 output, post-matmul scale or None). int4 weights at serving
    shapes stream through the Pallas kernel (0.5 byte/weight from HBM);
    everything else is a plain einsum over :func:`_wmat`'s operand."""
    q4s = p.get(name + QUANT4_SUFFIX)
    if q4s is not None:
        from ..ops.int4_matmul import use_int4_kernel, int4_matmul

        if use_int4_kernel(p[name], q4s):
            lead = x.shape[:-1]
            y = int4_matmul(x.reshape(-1, x.shape[-1]), p[name], q4s)
            return y.reshape(*lead, y.shape[-1]), None
    w, s = _wmat(p, name, x.dtype)
    out = jnp.einsum("...d,do->...o", x, w, preferred_element_type=jnp.float32)
    return out, s


def init_leaf(name: str, shape, dtype, key: jax.Array) -> jax.Array:
    """One param leaf's random init, matching :meth:`Llama.init_params`
    distributions by name. Used by the runner's streamed materialization
    (leaf-by-leaf, jitted straight into its device sharding) so big-model
    init never holds the full bf16 tree anywhere."""
    if "norm" in name:
        return jnp.ones(shape, dtype)
    if name.startswith(("b", "lora_")):
        return jnp.zeros(shape, dtype)
    fan_in = shape[-1] if name in QUANT_TOP_KEYS else shape[-2]
    return (
        jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)
    ).astype(dtype)


def pp_compose(run_stage, x, replicated, scanned, pp_size: int, mesh):
    """Compose layer-stages across the ``pp`` mesh axis by rotating
    activations (TPU-native pipeline parallel; replaces the reference's
    Ray-cluster PP, ``helm/templates/ray-cluster.yaml:560-566``).

    Each pp rank holds ``L/pp`` layers (the ``scanned`` pytrees are sharded on
    their leading layer axis). The activation makes ``pp`` hops: at hop ``i``
    rank ``i`` holds the correctly-composed prefix, applies its local layers,
    and ``ppermute``s the result to rank ``i+1``; other ranks compute on
    rotated (discarded) lanes, so wall-clock equals the sequential depth while
    HBM per device drops by ``pp``. Rank 0 ends with the full composition,
    which a masked ``psum`` broadcasts. Collectives are point-to-point
    ``ppermute``s — DCN-friendly, exactly the inter-host traffic pattern PP
    wants (the tp all-reduces stay inside each stage on ICI, handled by GSPMD
    auto mode since only ``pp`` is manual here).

    ``run_stage(x, scanned_local, gate)`` applies the local layer stack;
    ``gate`` is a bool scalar — True only on the hop where this rank's input
    is the real composition, letting the stage suppress side effects (KV
    cache writes) on garbage lanes. Returns ``(x, scanned_local_out)``.

    ``replicated`` arrays (rope tables, block tables, …) are passed through
    explicitly — closed-over traced values would carry auto-mesh shardings
    that clash with the manual-``pp`` context.
    """
    perm = [(j, (j + 1) % pp_size) for j in range(pp_size)]

    def body(x, repl, *scanned_local):
        rank = jax.lax.axis_index(AXIS_PIPELINE)
        out_scanned = scanned_local
        for i in range(pp_size):
            x_out, out_scanned = run_stage(x, repl, out_scanned, rank == i)
            x = jax.lax.ppermute(x_out, AXIS_PIPELINE, perm)
        x = jax.lax.psum(
            jnp.where(rank == 0, x, jnp.zeros_like(x)), AXIS_PIPELINE
        )
        return (x, *out_scanned)

    pp_spec = P(AXIS_PIPELINE)
    out = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), P(), *([pp_spec] * len(scanned))),
        out_specs=(P(), *([pp_spec] * len(scanned))),
        axis_names={AXIS_PIPELINE},
        check_vma=False,
    )(x, replicated, *scanned)
    return out[0], out[1:]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    rope_theta: float = 10000.0
    # Llama-3.1-style rope scaling (HF config.json "rope_scaling" with
    # rope_type "llama3"). factor 0 = disabled. Without this, checkpoints
    # trained with scaled rope are silently wrong past their original
    # context (e.g. Llama-3.1 beyond 8k).
    rope_scaling_factor: float = 0.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_position: int = 8192
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 131072
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # Qwen2-style QKV biases
    # Mixture-of-experts (Mixtral-style sparse SwiGLU MLP; HF
    # ``num_local_experts`` / ``num_experts_per_tok``). 0 experts = dense.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Qwen3-style per-head RMSNorm on q/k (applied over head_dim, before
    # rope; params q_norm/k_norm [L, hd]).
    qk_norm: bool = False
    # Gemma-family architecture knobs (all default to the Llama conventions).
    hidden_act: str = "silu"  # silu | gelu_tanh (Gemma GeGLU)
    norm_unit_offset: bool = False  # RMSNorm weight is (1 + w) (Gemma)
    embed_scale: bool = False  # scale embeddings by sqrt(D) (Gemma)
    query_pre_attn_scalar: float = 0.0  # attn scale override (Gemma-2; 0=hd)
    attn_logit_softcap: float = 0.0  # tanh cap on attention logits (Gemma-2)
    final_logit_softcap: float = 0.0  # tanh cap on LM-head logits (Gemma-2)
    post_block_norms: bool = False  # Gemma-2 post-attn / post-mlp RMSNorms
    # Sliding-window (local) attention: each query sees at most the last
    # `sliding_window` positions (Mistral-v0.1, Gemma-2). With
    # `sliding_window_pattern` = N > 1, every Nth layer (li+1 ≡ 0 mod N) is
    # global and the rest are local (Gemma-2: N=2); 1 = all layers local.
    sliding_window: int = 0
    sliding_window_pattern: int = 1
    dtype: str = "bfloat16"
    # Serving identity / tokenizer hints (not part of the math).
    name: str = "llama"
    eos_token_ids: Tuple[int, ...] = (2,)
    bos_token_id: Optional[int] = 1

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @property
    def attn_scale(self) -> float:
        base = self.query_pre_attn_scalar or self.head_dim
        return 1.0 / math.sqrt(base)

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim


class Llama:
    """Stateless model functions bound to a config."""

    def __init__(self, cfg: LlamaConfig):
        self.cfg = cfg

    # ------------------------------------------------------------------
    # Parameters
    # ------------------------------------------------------------------

    def init_params(self, rng: jax.Array) -> Params:
        """Random (serving-scale-correct) initialization, for tests/bench."""
        cfg = self.cfg
        d = cfg.jdtype
        k = jax.random.split(rng, 9)
        D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

        def dense(key, shape, fan_in):
            return (
                jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)
            ).astype(d)

        if cfg.num_experts:
            E = cfg.num_experts
            mlp = {
                # Router kept [D, E] so routing is a plain x @ w (HF stores
                # the transpose). Experts are stacked on their own axis so
                # the whole bank feeds one grouped matmul (ragged_dot) or one
                # expert-batched einsum — and shards over the ep mesh axis.
                "w_router": dense(k[8], (L, D, E), D),
                "w_gate": dense(k[5], (L, E, D, F), D),
                "w_up": dense(k[6], (L, E, D, F), D),
                "w_down": dense(k[7], (L, E, F, D), F),
            }
        else:
            mlp = {
                "w_gate": dense(k[5], (L, D, F), D),
                "w_up": dense(k[6], (L, D, F), D),
                "w_down": dense(k[7], (L, F, D), F),
            }
        params: Params = {
            "embed": dense(k[0], (cfg.vocab_size, D), D),
            "layers": {
                "attn_norm": jnp.ones((L, D), d),
                "wq": dense(k[1], (L, D, cfg.q_size), D),
                "wk": dense(k[2], (L, D, cfg.kv_size), D),
                "wv": dense(k[3], (L, D, cfg.kv_size), D),
                "wo": dense(k[4], (L, cfg.q_size, D), cfg.q_size),
                "mlp_norm": jnp.ones((L, D), d),
                **mlp,
            },
            "final_norm": jnp.ones((D,), d),
        }
        if cfg.attention_bias:
            params["layers"]["bq"] = jnp.zeros((L, cfg.q_size), d)
            params["layers"]["bk"] = jnp.zeros((L, cfg.kv_size), d)
            params["layers"]["bv"] = jnp.zeros((L, cfg.kv_size), d)
        if cfg.qk_norm:
            params["layers"]["q_norm"] = jnp.ones((L, cfg.head_dim), d)
            params["layers"]["k_norm"] = jnp.ones((L, cfg.head_dim), d)
        if cfg.post_block_norms:
            params["layers"]["post_attn_norm"] = jnp.ones((L, D), d)
            params["layers"]["post_mlp_norm"] = jnp.ones((L, D), d)
        if not cfg.tie_word_embeddings:
            params["lm_head"] = dense(k[0], (cfg.vocab_size, D), D)
        return params

    def param_pspecs(
        self, pipeline: bool = False, quantize=False
    ) -> Params:
        """PartitionSpec tree matching :meth:`init_params`.

        tp shards attention heads and the FFN hidden dim (Megatron layout:
        column-parallel in-projections, row-parallel out-projections — XLA
        emits the single all-reduce per block that layout implies). With
        ``pipeline=True`` the stacked layer axis is additionally sharded over
        pp, giving layer-stage parallelism without restructuring the tree.
        ``quantize``: False, or a mode — "int8"/True adds the per-channel
        scale leaves (``*_qs``) sharded like their weight's output channels;
        "int4" adds group-wise scale leaves (``*_q4s``, same rank and mesh
        axes as their weight — only the contraction dim shrinks) for the
        per-layer matmuls plus int8 ``*_qs`` for embed/lm_head.
        """
        mode = "int8" if quantize is True else quantize
        pp = "pp" if pipeline else None
        if self.cfg.num_experts:
            # Expert bank: experts over ep, FFN hidden over tp (each expert
            # is itself Megatron-sharded). The combine einsum's reduction
            # over E becomes the one all-reduce over ep XLA inserts.
            mlp_specs = {
                "w_router": P(pp, None, None),
                "w_gate": P(pp, AXIS_EXPERT, None, AXIS_TENSOR),
                "w_up": P(pp, AXIS_EXPERT, None, AXIS_TENSOR),
                "w_down": P(pp, AXIS_EXPERT, AXIS_TENSOR, None),
            }
        else:
            mlp_specs = {
                "w_gate": P(pp, None, AXIS_TENSOR),
                "w_up": P(pp, None, AXIS_TENSOR),
                "w_down": P(pp, AXIS_TENSOR, None),
            }
        specs: Params = {
            "embed": P(None, AXIS_TENSOR),
            "layers": {
                "attn_norm": P(pp, None),
                "wq": P(pp, None, AXIS_TENSOR),
                "wk": P(pp, None, AXIS_TENSOR),
                "wv": P(pp, None, AXIS_TENSOR),
                "wo": P(pp, AXIS_TENSOR, None),
                "mlp_norm": P(pp, None),
                **mlp_specs,
            },
            "final_norm": P(None),
        }
        if self.cfg.attention_bias:
            specs["layers"]["bq"] = P(pp, AXIS_TENSOR)
            specs["layers"]["bk"] = P(pp, AXIS_TENSOR)
            specs["layers"]["bv"] = P(pp, AXIS_TENSOR)
        if self.cfg.qk_norm:
            specs["layers"]["q_norm"] = P(pp, None)
            specs["layers"]["k_norm"] = P(pp, None)
        if self.cfg.post_block_norms:
            specs["layers"]["post_attn_norm"] = P(pp, None)
            specs["layers"]["post_mlp_norm"] = P(pp, None)
        if not self.cfg.tie_word_embeddings:
            specs["lm_head"] = P(None, AXIS_TENSOR)
        if mode:
            # int8 scale spec = weight spec minus the reduced (input) axis:
            # the scale shards exactly like its weight's output channels.
            # int4 scale spec = weight spec verbatim (the group axis lives
            # where the contraction axis does and shards the same way).
            def drop_axis(spec: P, ndim: int, axis: int) -> P:
                ent = list(spec) + [None] * (ndim - len(spec))
                del ent[axis]
                return P(*ent)

            moe = bool(self.cfg.num_experts)
            for k in QUANT_LAYER_KEYS:
                if k in specs["layers"]:
                    if mode == "int4":
                        specs["layers"][k + QUANT4_SUFFIX] = specs["layers"][k]
                        continue
                    ndim = 4 if (moe and k in ("w_gate", "w_up", "w_down")) else 3
                    specs["layers"][k + QUANT_SUFFIX] = drop_axis(
                        specs["layers"][k], ndim, -2
                    )
            for k in QUANT_TOP_KEYS:
                if k in specs:
                    specs[k + QUANT_SUFFIX] = drop_axis(specs[k], 2, -1)
        return specs

    # ------------------------------------------------------------------
    # LoRA bank (stacked adapter slots — engine/lora.py owns the registry)
    # ------------------------------------------------------------------

    LORA_TARGETS = ("wq", "wk", "wv", "wo")

    def init_lora_bank(self, max_loras: int, max_rank: int) -> Params:
        """Zero-filled stacked adapter bank, merged into params["layers"]:
        ``lora_a_<t>`` [L, slots, in, r], ``lora_b_<t>`` [L, slots, r, out].
        Slot 0 stays zero forever = "no adapter" (exact no-op delta)."""
        cfg = self.cfg
        d = cfg.jdtype
        L, S, R = cfg.num_layers, max_loras + 1, max_rank
        dims = {
            "wq": (cfg.hidden_size, cfg.q_size),
            "wk": (cfg.hidden_size, cfg.kv_size),
            "wv": (cfg.hidden_size, cfg.kv_size),
            "wo": (cfg.q_size, cfg.hidden_size),
        }
        bank: Params = {}
        for t, (din, dout) in dims.items():
            bank[f"lora_a_{t}"] = jnp.zeros((L, S, din, R), d)
            bank[f"lora_b_{t}"] = jnp.zeros((L, S, R, dout), d)
        return bank

    def lora_pspecs(self, pipeline: bool = False) -> Params:
        """PartitionSpecs for the bank: B matrices follow their projection's
        output sharding (column-parallel q/k/v), A for wo follows its input
        sharding (row-parallel) — the deltas then compose with the base
        matmuls under the same collectives XLA already inserts."""
        pp = "pp" if pipeline else None
        return {
            "lora_a_wq": P(pp, None, None, None),
            "lora_b_wq": P(pp, None, None, AXIS_TENSOR),
            "lora_a_wk": P(pp, None, None, None),
            "lora_b_wk": P(pp, None, None, AXIS_TENSOR),
            "lora_a_wv": P(pp, None, None, None),
            "lora_b_wv": P(pp, None, None, AXIS_TENSOR),
            "lora_a_wo": P(pp, None, AXIS_TENSOR, None),
            "lora_b_wo": P(pp, None, None, None),
        }

    # ------------------------------------------------------------------
    # KV cache
    # ------------------------------------------------------------------

    def make_kv_cache(
        self, num_blocks: int, block_size: int, dtype: Optional[str] = None
    ) -> jax.Array:
        # One combined array [L, nb, 2, bs, KH*hd]: a page holds its K rows
        # (index 0 of dim 2) then V rows (index 1), each token row spanning
        # all kv heads in the lane dimension. One DMA moves a whole page in
        # the pallas kernel, the write path is a single scatter, and the
        # minor dims (bs, KH*hd) are sublane/lane tiling-exact — a
        # [..., KH, hd] tail would pad KH=8 up to the 16-sublane tile and
        # physically double the cache.
        cfg = self.cfg
        shape = (
            cfg.num_layers, num_blocks, 2, block_size,
            cfg.num_kv_heads * cfg.head_dim,
        )
        d = jnp.dtype(dtype) if dtype else cfg.jdtype
        return jnp.zeros(shape, d)

    @staticmethod
    def cache_pspec(pipeline: bool = False) -> P:
        # [L, nb, 2, bs, KH*hd] — the head-folded lane dim shards over tp
        # (shard boundaries align with head boundaries when tp | KH); layers
        # over pp when the engine runs pipeline-parallel (each stage holds
        # its layers' pages).
        pp = AXIS_PIPELINE if pipeline else None
        return P(pp, None, None, None, AXIS_TENSOR)

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------

    def forward(
        self,
        params: Params,
        tokens: jax.Array,  # [B, T] int32
        positions: jax.Array,  # [B, T] int32 absolute positions (pad: any)
        write_idx: jax.Array,  # [B, T] int32 flat slot idx (nb*bs => dropped)
        block_tables: jax.Array,  # [B, W] int32
        kv_lens: jax.Array,  # [B] int32 valid kv len AFTER this step's writes
        last_idx: jax.Array,  # [B] int32 index in T of each row's last token
        kv_cache: jax.Array,  # [L, nb, 2, bs, KH*hd] (donated by caller's jit)
        *,
        lora_idx: Optional[jax.Array] = None,  # [B] int32 bank slots (0=none)
        lora_scale: Optional[jax.Array] = None,  # [B] f32 alpha/r per row
        attn_impl: str = "auto",
        moe_impl: str = "auto",
        pp_size: int = 1,
        mesh=None,
        all_logits: bool = False,
    ) -> Tuple[jax.Array, jax.Array]:
        """One engine step. Returns (last-token logits [B, V], new cache) —
        or ([B, T, V] logits for every position when ``all_logits`` (the
        speculative-decoding verify step scores each draft position in one
        pass; ``last_idx`` is ignored).

        With ``pp_size > 1`` the stacked layer axis (params and cache) is
        sharded over the ``pp`` mesh axis and composed via
        :func:`pp_compose`; ``mesh`` must be the engine mesh.
        """
        cfg = self.cfg
        B, T = tokens.shape
        nb, bs = kv_cache.shape[1], kv_cache.shape[3]
        scale = cfg.attn_scale
        offset = cfg.norm_unit_offset

        x = _embed_lookup(params, tokens, cfg)  # [B, T, D]
        if cfg.embed_scale:
            # HF-Gemma convention: the sqrt(D) normalizer is rounded to the
            # model dtype before multiplying.
            x = x * jnp.asarray(math.sqrt(cfg.hidden_size), x.dtype)
        rope_cos, rope_sin = _rope_tables(positions, cfg)
        flat_write_real = write_idx.reshape(-1)  # [B*T]
        has_lora = "lora_a_wq" in params["layers"]
        if has_lora and lora_idx is None:
            lora_idx = jnp.zeros((B,), jnp.int32)
            lora_scale = jnp.zeros((B,), jnp.float32)

        def lora_delta(lp, t: str, inp: jax.Array) -> jax.Array:
            """scaling * (inp @ A[slot]) @ B[slot] per batch row (slot 0 is
            zeros, so no-adapter rows get an exact zero delta)."""
            a = lp[f"lora_a_{t}"][lora_idx]  # [B, in, r]
            b = lp[f"lora_b_{t}"][lora_idx]  # [B, r, out]
            d = jnp.einsum(
                "btd,bdr->btr", inp, a, preferred_element_type=jnp.float32
            )
            d = jnp.einsum(
                "btr,bro->bto", d.astype(b.dtype), b,
                preferred_element_type=jnp.float32,
            )
            return d * lora_scale[:, None, None]

        def layer_fn(ctx, x, kv_all, lp, li, li_global):
            # ctx: traced arrays shared by every layer. Threaded explicitly
            # (not closed over) so the pp shard_map can pass them through.
            # kv_all: the FULL stacked cache [L, nb, 2, bs, KH*hd]; li is
            # this layer's index into it. The cache is never sliced — the
            # attention kernel takes (cache, layer) and reads only the live
            # pages, and the write is a scatter at layer-offset rows, so the
            # carried buffer updates in place (a per-layer slice/update pair
            # would copy the whole layer cache twice per layer per step).
            flat_write, rope_cos, rope_sin, block_tables, kv_lens, positions = ctx
            h = _rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps, offset)
            q = _proj(h, lp, "wq", lp.get("bq"))
            k = _proj(h, lp, "wk", lp.get("bk"))
            v = _proj(h, lp, "wv", lp.get("bv"))
            if has_lora:
                q = q + lora_delta(lp, "wq", h).astype(q.dtype)
                k = k + lora_delta(lp, "wk", h).astype(k.dtype)
                v = v + lora_delta(lp, "wv", h).astype(v.dtype)
            q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
            k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
            v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
            if cfg.qk_norm:  # Qwen3: per-head RMSNorm over hd, pre-rope
                q = _rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
                k = _rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
            q = _apply_rope(q, rope_cos, rope_sin)
            k = _apply_rope(k, rope_cos, rope_sin)

            if _decode_write_fused(attn_impl) and T == 1:
                # Decode on the Pallas path: the KV write rides INSIDE the
                # attention kernel (one DMA per sequence before the read
                # loop) — the per-layer XLA scatter below is pure op
                # overhead on the 10 GiB carried buffer at decode shapes.
                from ..ops.paged_attention_pallas import (
                    pallas_paged_attention_decode_write,
                )

                attn, kv_all = pallas_paged_attention_decode_write(
                    q[:, 0], kv_all, block_tables, kv_lens, li,
                    k.reshape(B, cfg.kv_size), v.reshape(B, cfg.kv_size),
                    flat_write,  # [B*T] == [B] at T==1
                    scale=scale,
                    window=_layer_window(cfg, li_global),
                    softcap=cfg.attn_logit_softcap,
                )
                attn = attn[:, None]
            else:
                # One scatter over the flattened [L*nb*2*bs, KH*hd] row
                # view: slot (blk, pos) of layer li holds its K row at
                # (li*nb + blk)*2*bs + pos and its V row bs rows later. The
                # drop sentinel (flat_write == nb*bs) must map OUT of the
                # whole array, not merely past this layer's rows —
                # past-the-layer would land in layer li+1's first page.
                n_layers_total = kv_all.shape[0]
                blk = flat_write // bs
                pos = flat_write % bs
                oob = n_layers_total * nb * 2 * bs
                idx_k = jnp.where(
                    flat_write >= nb * bs,
                    oob,
                    (li * nb + blk) * (2 * bs) + pos,
                )
                kvd = jnp.concatenate(
                    [
                        k.reshape(B * T, cfg.kv_size),
                        v.reshape(B * T, cfg.kv_size),
                    ],
                    axis=0,
                ).astype(kv_all.dtype)  # [2*B*T, KH*hd]
                idx = jnp.concatenate([idx_k, idx_k + bs])
                kv_all = (
                    kv_all.reshape(n_layers_total * nb * 2 * bs, cfg.kv_size)
                    .at[idx]
                    .set(kvd, mode="drop")
                    .reshape(n_layers_total, nb, 2, bs, cfg.kv_size)
                )

                attn = paged_attention(
                    q, kv_all, block_tables, kv_lens, positions, li,
                    scale=scale, impl=attn_impl,
                    # Window pattern keys off the GLOBAL layer index (under
                    # pp, li is the stage-local cache index).
                    window=_layer_window(cfg, li_global),
                    softcap=cfg.attn_logit_softcap,
                )
            attn = attn.reshape(B, T, cfg.q_size).astype(x.dtype)
            o, wo_s = _qdot(attn, lp, "wo")
            if wo_s is not None:
                o = o * wo_s
            if has_lora:
                o = o + lora_delta(lp, "wo", attn)
            o = o.astype(x.dtype)
            if cfg.post_block_norms:  # Gemma-2 post-attention norm
                o = _rms_norm(o, lp["post_attn_norm"], cfg.rms_norm_eps, offset)
            x = x + o

            h = _rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps, offset)
            ff = _mlp(cfg, lp, h, moe_impl).astype(x.dtype)
            if cfg.post_block_norms:  # Gemma-2 post-feedforward norm
                ff = _rms_norm(ff, lp["post_mlp_norm"], cfg.rms_norm_eps, offset)
            x = x + ff
            return x, kv_all

        def scan_layers(ctx, x, kv_all, layers, n_layers, li_base=0):
            # The cache rides the scan CARRY — carried while-loop buffers
            # alias across iterations, so peak HBM holds ONE cache. (As scan
            # xs/ys the stacked outputs would be a second full-size
            # allocation: at the 32k-context bench config that is +11 GiB
            # and an instant OOM.) The body never slices the cache; see
            # layer_fn. ``li_base`` is the stage's global layer offset
            # (nonzero under pp, where the scan index is stage-local).
            def body(carry, sl):
                x, kv_all = carry
                lp, i = sl
                x, kv_all = layer_fn(ctx, x, kv_all, lp, i, li_base + i)
                return (x, kv_all), None

            (x, kv_all), _ = jax.lax.scan(
                body, (x, kv_all),
                (layers, jnp.arange(n_layers, dtype=jnp.int32)),
            )
            return x, kv_all

        ctx = (flat_write_real, rope_cos, rope_sin, block_tables, kv_lens,
               positions)
        if pp_size > 1:
            def run_stage(x, repl, scanned_local, gate):
                fw, *rest = repl
                # Suppress cache writes on garbage (rotated) lanes: only the
                # hop where this rank's input is the true composition may
                # write KV; others write to the dropped slot (nb*bs).
                fw = jnp.where(gate, fw, nb * bs)
                layers_local, kv_local = scanned_local
                n_local = cfg.num_layers // pp_size
                x, kv_local = scan_layers(
                    (fw, *rest), x, kv_local, layers_local, n_local,
                    li_base=jax.lax.axis_index(AXIS_PIPELINE) * n_local,
                )
                return x, (layers_local, kv_local)

            x, (_, kv_cache) = pp_compose(
                run_stage, x, ctx, (params["layers"], kv_cache),
                pp_size, mesh,
            )
        else:
            x, kv_cache = scan_layers(
                ctx, x, kv_cache, params["layers"], cfg.num_layers
            )

        x = _rms_norm(x, params["final_norm"], cfg.rms_norm_eps, offset)
        head = "lm_head" if "lm_head" in params else "embed"
        unembed = _wcast(params[head], x.dtype)  # [V, D]
        uqs = params.get(head + QUANT_SUFFIX)
        if all_logits:
            logits = jnp.einsum(
                "btd,vd->btv", x, unembed, preferred_element_type=jnp.float32
            )
        else:
            last = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
            logits = jnp.einsum(
                "bd,vd->bv", last, unembed, preferred_element_type=jnp.float32
            )
        if uqs is not None:
            logits = logits * uqs  # per-vocab-row scale, broadcast over batch
        logits = _softcap(logits, cfg.final_logit_softcap)
        return logits, kv_cache

    def encode(
        self,
        params: Params,
        tokens: jax.Array,
        lengths: jax.Array,
        *,
        pp_size: int = 1,
        sp_size: int = 1,
        moe_impl: str = "auto",
        mesh=None,
    ) -> jax.Array:
        """Embedding path (/v1/embeddings): full causal attention, no cache;
        returns L2-normalized mean-pooled final hidden states [B, D].

        With ``sp_size > 1`` (and ``pp_size == 1``) the per-layer attention
        runs as RING attention over the ``sp`` mesh axis
        (:mod:`production_stack_tpu.ops.ring_attention`): the per-hop KV
        shards across devices and no [B, T, S] score matrix ever
        materializes, so contexts larger than one device's attention memory
        encode across the sp group.
        """
        cfg = self.cfg
        B, T = tokens.shape
        use_ring = sp_size > 1 and mesh is not None
        if use_ring and pp_size > 1:
            raise ValueError("ring (sp) encode does not compose with pp yet")
        if use_ring and (cfg.sliding_window or cfg.attn_logit_softcap):
            raise ValueError(
                "ring (sp) encode does not support sliding-window/"
                "softcap models yet"
            )
        offset = cfg.norm_unit_offset
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
        x = _embed_lookup(params, tokens, cfg)
        if cfg.embed_scale:
            x = x * jnp.asarray(math.sqrt(cfg.hidden_size), x.dtype)
        rope_cos, rope_sin = _rope_tables(positions, cfg)
        valid = positions < lengths[:, None]  # [B, T]
        if use_ring:
            causal = jnp.zeros((0,), jnp.bool_)  # ring derives its own masks
        else:
            causal = (
                positions[:, None, :] <= positions[:, :, None]
            ) & valid[:, None, :]  # [B, T, S]
        G = cfg.num_heads // cfg.num_kv_heads

        def layer(ctx, x, lp, li):
            rope_cos, rope_sin, causal = ctx
            h = _rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps, offset)
            q = _proj(h, lp, "wq", lp.get("bq")).reshape(
                B, T, cfg.num_kv_heads, G, cfg.head_dim
            )
            k = _proj(h, lp, "wk", lp.get("bk")).reshape(
                B, T, cfg.num_kv_heads, cfg.head_dim
            )
            v = _proj(h, lp, "wv", lp.get("bv")).reshape(
                B, T, cfg.num_kv_heads, cfg.head_dim
            )
            q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
            if cfg.qk_norm:  # Qwen3: per-head RMSNorm over hd, pre-rope
                q = _rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
                k = _rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
            q = _apply_rope(q, rope_cos, rope_sin)
            k = _apply_rope(k, rope_cos, rope_sin)
            if use_ring:
                from ..ops.ring_attention import ring_self_attention

                attn = ring_self_attention(
                    q, k, v, lengths, mesh,
                    scale=cfg.attn_scale,
                ).reshape(B, T, cfg.q_size).astype(x.dtype)
            else:
                qg = q.reshape(B, T, cfg.num_kv_heads, G, cfg.head_dim)
                scores = jnp.einsum(
                    "btkgd,bskd->bkgts", qg, k,
                    preferred_element_type=jnp.float32,
                ) * cfg.attn_scale
                scores = _softcap(scores, cfg.attn_logit_softcap)
                mask = causal
                if cfg.sliding_window:
                    mask = mask & (
                        positions[:, None, :]
                        > positions[:, :, None]
                        - window_eff(_layer_window(cfg, li))
                    )
                scores = jnp.where(mask[:, None, None], scores, -1e30)
                probs = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum(
                    "bkgts,bskd->btkgd", probs.astype(v.dtype), v,
                    preferred_element_type=jnp.float32,
                ).reshape(B, T, cfg.q_size).astype(x.dtype)
            o, wo_s = _qdot(attn, lp, "wo")
            if wo_s is not None:
                o = o * wo_s
            o = o.astype(x.dtype)
            if cfg.post_block_norms:
                o = _rms_norm(o, lp["post_attn_norm"], cfg.rms_norm_eps, offset)
            x = x + o
            h = _rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps, offset)
            ff = _mlp(cfg, lp, h, moe_impl).astype(x.dtype)
            if cfg.post_block_norms:
                ff = _rms_norm(ff, lp["post_mlp_norm"], cfg.rms_norm_eps, offset)
            x = x + ff
            return x, None

        ctx = (rope_cos, rope_sin, causal)
        if pp_size > 1:
            n_local = cfg.num_layers // pp_size

            def run_stage(x, repl, scanned_local, gate):
                (layers_local,) = scanned_local
                base = jax.lax.axis_index(AXIS_PIPELINE) * n_local
                x, _ = jax.lax.scan(
                    lambda c, s: layer(repl, c, s[0], base + s[1]),
                    x,
                    (layers_local, jnp.arange(n_local, dtype=jnp.int32)),
                )
                return x, (layers_local,)

            x, _ = pp_compose(
                run_stage, x, ctx, (params["layers"],), pp_size, mesh
            )
        else:
            x, _ = jax.lax.scan(
                lambda c, s: layer(ctx, c, s[0], s[1]),
                x,
                (
                    params["layers"],
                    jnp.arange(cfg.num_layers, dtype=jnp.int32),
                ),
            )
        x = _rms_norm(x, params["final_norm"], cfg.rms_norm_eps, offset)
        mask = valid[..., None].astype(jnp.float32)
        pooled = (x.astype(jnp.float32) * mask).sum(1) / jnp.maximum(mask.sum(1), 1.0)
        norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
        return pooled / jnp.maximum(norm, 1e-12)


# ----------------------------------------------------------------------------
# Layer primitives
# ----------------------------------------------------------------------------


def _decode_write_fused(attn_impl: str) -> bool:
    """Whether single-token decode should fold the KV write into the
    Pallas attention kernel (skips the per-layer XLA scatter).

    OFF by default: measured on v5e at the 8B bench shape, the fold's
    page round-trip (sub-row DMA into a tiled fp8 page is not
    expressible, so the kernel pulls/splices/pushes the whole page) costs
    MORE than the XLA scatter it removes (36.2 vs 32.5 ms/step at batch
    8 x 20k). Kept behind PST_FUSED_KV_WRITE=1 with its exact-parity test
    for revisiting on hardware where row-granular HBM writes are legal."""
    if os.environ.get("PST_FUSED_KV_WRITE") != "1":
        return False
    if attn_impl == "pallas":
        return True
    if attn_impl == "gather":
        return False
    from ..ops.attention import _use_pallas

    return _use_pallas()


def _rms_norm(
    x: jax.Array, w: jax.Array, eps: float, unit_offset: bool = False
) -> jax.Array:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    if unit_offset:  # Gemma stores w with effective weight (1 + w), fp32 math
        return (normed * (1.0 + w.astype(jnp.float32))).astype(x.dtype)
    return normed.astype(x.dtype) * w


def _act(cfg: "LlamaConfig"):
    if cfg.hidden_act == "gelu_tanh":  # Gemma GeGLU
        return lambda v: jax.nn.gelu(v, approximate=True)
    if cfg.hidden_act != "silu":
        raise ValueError(f"unsupported hidden_act {cfg.hidden_act!r}")
    return jax.nn.silu


def _layer_window(cfg: "LlamaConfig", li) -> jax.Array:
    """Sliding window for (traced) layer index ``li``: 0 = global."""
    if not cfg.sliding_window:
        return jnp.int32(0)
    pat = cfg.sliding_window_pattern
    if pat <= 1:
        return jnp.int32(cfg.sliding_window)
    return jnp.where(
        (jnp.asarray(li, jnp.int32) + 1) % pat == 0,
        jnp.int32(0),
        jnp.int32(cfg.sliding_window),
    )


def _softcap(logits: jax.Array, cap: float) -> jax.Array:
    return jnp.tanh(logits / cap) * cap if cap else logits


def _embed_lookup(params: Params, tokens: jax.Array, cfg: "LlamaConfig") -> jax.Array:
    """Token embedding gather; int8 tables dequantize with their per-row
    scale (the same rows the tied unembed scales by)."""
    x = params["embed"][tokens]
    s = params.get("embed" + QUANT_SUFFIX)
    if s is not None:
        x = (x.astype(jnp.float32) * s[tokens][..., None]).astype(cfg.jdtype)
    return x


def _mlp(cfg: "LlamaConfig", lp: Params, h: jax.Array, moe_impl: str = "auto") -> jax.Array:
    """SwiGLU MLP block output [B, T, D] in fp32 — dense, or Mixtral-style
    sparse mixture-of-experts when ``cfg.num_experts``."""
    act = _act(cfg)
    if not cfg.num_experts:
        gate = _proj(h, lp, "w_gate")
        up = _proj(h, lp, "w_up")
        ff = (
            act(gate.astype(jnp.float32)) * up.astype(jnp.float32)
        ).astype(h.dtype)
        out, wd_s = _qdot(ff, lp, "w_down")
        if wd_s is not None:
            out = out * wd_s
        return out
    B, T, D = h.shape
    return _moe_mlp(cfg, lp, h.reshape(B * T, D), moe_impl).reshape(B, T, D)


def _moe_mlp(cfg: "LlamaConfig", lp: Params, x: jax.Array, impl: str) -> jax.Array:
    """Sparse MoE SwiGLU over flattened tokens ``x`` [N, D] → fp32 [N, D].

    Router math in fp32 (HF Mixtral convention), top-k weights renormalized.
    Two TPU execution strategies:

    - ``ragged`` — dropless grouped matmul via ``lax.ragged_dot``: token-
      expert pairs are sorted by expert and each expert multiplies exactly
      the tokens routed to it. FLOPs stay proportional to N*k (no capacity
      padding, no token dropping). The idiomatic single-shard / tp-only path.
    - ``dense`` — expert-batched einsums over ALL tokens with a one-hot
      combine. E/k× the FLOPs, but every contraction is a plain einsum that
      GSPMD shards cleanly over the ``ep``/``tp`` mesh axes (experts stay
      resident on their shard; the combine reduction becomes the ep
      all-reduce). Used whenever the expert bank is mesh-sharded.

    ``auto`` resolves to ``ragged`` (the engine passes ``dense`` explicitly
    on ep/tp/pp-sharded meshes — see runner).
    """
    N, D = x.shape
    E, K = cfg.num_experts, cfg.num_experts_per_tok
    logits = jnp.einsum(
        "nd,de->ne", x.astype(jnp.float32), lp["w_router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(logits, axis=-1)  # [N, E] fp32
    weights, ids = jax.lax.top_k(probs, K)  # [N, K]
    weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    if impl not in ("ragged", "dense", "auto"):
        raise ValueError(f"unknown moe_impl {impl!r} (ragged|dense|auto)")

    def deq(key: str) -> jax.Array:
        # ragged_dot has no mixed-dtype story: int8/int4 expert banks
        # dequantize to one transient [E, ., .] bf16 bank (per layer inside
        # the scan — storage stays quantized, only this layer's working copy
        # is bf16). _wmat already dequantizes int4 pre-matmul; int8 hands
        # back its per-channel scale to fold in here.
        w, s = _wmat(lp, key, x.dtype)
        return w if s is None else w * s[:, None, :].astype(x.dtype)

    if impl in ("ragged", "auto"):
        flat_ids = ids.reshape(-1)  # [N*K]
        order = jnp.argsort(flat_ids)  # sorted-by-expert slot order
        tok = order // K  # originating token of each sorted slot
        xs = x[tok]  # [N*K, D]
        group_sizes = jnp.bincount(flat_ids, length=E).astype(jnp.int32)
        g = jax.lax.ragged_dot(
            xs, deq("w_gate"), group_sizes,
            preferred_element_type=jnp.float32,
        )
        u = jax.lax.ragged_dot(
            xs, deq("w_up"), group_sizes, preferred_element_type=jnp.float32
        )
        hh = (_act(cfg)(g) * u).astype(x.dtype)
        y = jax.lax.ragged_dot(
            hh, deq("w_down"), group_sizes, preferred_element_type=jnp.float32
        )  # [N*K, D]
        wsort = weights.reshape(-1)[order]  # [N*K]
        return (
            jnp.zeros((N, D), jnp.float32).at[tok].add(y * wsort[:, None])
        )
    # dense: combine[n, e] = summed top-k weight of expert e for token n.
    combine = jnp.sum(
        jax.nn.one_hot(ids, E, dtype=jnp.float32) * weights[..., None], axis=1
    )  # [N, E]
    wg, wg_s = _wmat(lp, "w_gate", x.dtype)
    wu, wu_s = _wmat(lp, "w_up", x.dtype)
    g = jnp.einsum(
        "nd,edf->enf", x, wg, preferred_element_type=jnp.float32
    )
    u = jnp.einsum(
        "nd,edf->enf", x, wu, preferred_element_type=jnp.float32
    )
    if wg_s is not None:
        g = g * wg_s[:, None, :]
    if wu_s is not None:
        u = u * wu_s[:, None, :]
    hh = (_act(cfg)(g) * u).astype(x.dtype)
    wd, wd_s = _wmat(lp, "w_down", x.dtype)
    y = jnp.einsum(
        "enf,efd->end", hh, wd, preferred_element_type=jnp.float32
    )
    if wd_s is not None:
        y = y * wd_s[:, None, :]
    return jnp.einsum("end,ne->nd", y, combine)


def _proj(
    x: jax.Array,
    p: Params,
    name: str,
    b: Optional[jax.Array] = None,
) -> jax.Array:
    out, s = _qdot(x, p, name)
    if s is not None:  # int8 per-output-channel scale
        out = out * s
    if b is not None:
        out = out + b.astype(out.dtype)
    return out.astype(x.dtype)


def _rope_tables(
    positions: jax.Array, cfg: "LlamaConfig"
) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables [B, T, hd/2] for the given absolute positions.

    Applies Llama-3.1 "llama3" rope scaling when configured: long-wavelength
    frequencies are divided by ``factor``, short ones kept, with a smooth
    ramp between ``low_freq_factor`` and ``high_freq_factor`` thresholds of
    the original context length (HF ``modeling_rope_utils`` semantics)."""
    half = cfg.head_dim // 2
    freqs = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )  # [half]
    if cfg.rope_scaling_factor:
        wavelen = 2.0 * math.pi / freqs
        low_w = cfg.rope_original_max_position / cfg.rope_low_freq_factor
        high_w = cfg.rope_original_max_position / cfg.rope_high_freq_factor
        smooth = (
            cfg.rope_original_max_position / wavelen - cfg.rope_low_freq_factor
        ) / (cfg.rope_high_freq_factor - cfg.rope_low_freq_factor)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = (
            (1.0 - smooth) * freqs / cfg.rope_scaling_factor + smooth * freqs
        )
        freqs = jnp.where(
            wavelen > low_w,
            freqs / cfg.rope_scaling_factor,  # long wavelengths: full scale
            jnp.where(wavelen < high_w, freqs, scaled),  # short: keep; mid: ramp
        )
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [B, T, half]
    return jnp.cos(angles), jnp.sin(angles)


def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """HF-Llama rotate-half convention; x: [B, T, H, hd]."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1
    ).astype(x.dtype)


# ----------------------------------------------------------------------------
# HF checkpoint loading (local safetensors; zero-egress environment)
# ----------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "self_attn.q_proj": "wq",
    "self_attn.k_proj": "wk",
    "self_attn.v_proj": "wv",
    "self_attn.o_proj": "wo",
    "mlp.gate_proj": "w_gate",
    "mlp.up_proj": "w_up",
    "mlp.down_proj": "w_down",
    "input_layernorm": "attn_norm",
    "post_attention_layernorm": "mlp_norm",
}
_HF_BIAS_MAP = {
    "self_attn.q_proj": "bq",
    "self_attn.k_proj": "bk",
    "self_attn.v_proj": "bv",
}


def _np_quantize(w: np.ndarray, axis: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side (numpy) int8 quantization for checkpoint loading: the bf16
    tree of a big model must never land on the device, and the CPU JAX
    backend may be absent when JAX_PLATFORMS pins the TPU platform."""
    if w.dtype == np.uint16:  # raw bf16 bit pattern from safetensors
        import ml_dtypes

        w = w.view(ml_dtypes.bfloat16)
    wf = w.astype(np.float32)
    amax = np.max(np.abs(wf), axis=axis)
    s = np.maximum(amax, 1e-8) / 127.0
    q = np.clip(np.round(wf / np.expand_dims(s, axis)), -127, 127).astype(np.int8)
    return q, s.astype(np.float32)


def _np_quantize_int4(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side group-wise int4 (contraction axis -2), numpy mirror of
    :func:`quantize_leaf_int4` — bit-identical packing."""
    if w.dtype == np.uint16:
        import ml_dtypes

        w = w.view(ml_dtypes.bfloat16)
    wf = w.astype(np.float32)
    *lead, din, dout = wf.shape
    g = _q4_group(din)
    wg = wf.reshape(*lead, din // g, g, dout)
    amax = np.max(np.abs(wg), axis=-2)
    s = np.maximum(amax, 1e-8) / 7.0
    q = np.clip(np.round(wg / s[..., :, None, :]), -7, 7).astype(np.int8)
    q = q.reshape(*lead, din, dout)
    lo = q[..., 0::2, :]
    hi = q[..., 1::2, :]
    packed = ((lo & 0x0F) | (hi << 4)).astype(np.int8)
    return packed, s.astype(np.float32)


def load_hf_params(
    cfg: LlamaConfig, model_dir: str, quantize=False
) -> Params:
    """Load HF-format safetensors from a local directory into the pytree.

    HF linear weights are stored ``[out, in]``; ours are ``[in, out]`` so the
    forward is a plain ``x @ w`` (no transposes at serve time). Layers are
    stacked on axis 0 to match the scan layout. ``quantize``: False, or
    "int8"/True (per-channel) or "int4" (group-wise per-layer matmuls,
    embed/lm_head int8) — computed in numpy on the host so the big leaves
    stay host-resident until the runner's sharded device_put.
    """
    qmode = "int8" if quantize is True else quantize
    from safetensors import safe_open

    files = sorted(
        os.path.join(model_dir, f)
        for f in os.listdir(model_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")

    d = cfg.jdtype
    L = cfg.num_layers
    layer_acc: Dict[str, list] = {}
    params: Params = {"layers": {}}

    def to_np(t) -> np.ndarray:
        arr = np.asarray(t)
        if arr.dtype == np.dtype("V2"):  # raw bf16 view
            arr = arr.view(np.uint16)
        return arr

    raw: Dict[str, np.ndarray] = {}
    for path in files:
        with safe_open(path, framework="numpy") as f:
            for key in f.keys():
                raw[key] = to_np(f.get_tensor(key))

    def cast(arr: np.ndarray) -> jax.Array:
        if arr.dtype == np.uint16:  # bf16 bit pattern
            return jax.lax.bitcast_convert_type(
                jnp.asarray(arr), jnp.bfloat16
            ).astype(d)
        return jnp.asarray(arr).astype(d)

    def put_top(name: str, arr: np.ndarray) -> None:
        if qmode and name in QUANT_TOP_KEYS:
            q, s = _np_quantize(arr, axis=-1)
            params[name], params[name + QUANT_SUFFIX] = q, s
        else:
            params[name] = cast(arr)

    put_top("embed", raw.pop("model.embed_tokens.weight"))
    params["final_norm"] = cast(raw.pop("model.norm.weight"))
    if "lm_head.weight" in raw:
        put_top("lm_head", raw.pop("lm_head.weight"))

    layer_map = dict(_HF_LAYER_MAP)
    if cfg.qk_norm:
        layer_map["self_attn.q_norm"] = "q_norm"
        layer_map["self_attn.k_norm"] = "k_norm"
    if cfg.post_block_norms:
        # Gemma-2 norm layout: post_attention_layernorm is the POST-attn
        # norm (not the MLP pre-norm as in Llama), and the MLP has its own
        # pre/post pair.
        layer_map["post_attention_layernorm"] = "post_attn_norm"
        layer_map["pre_feedforward_layernorm"] = "mlp_norm"
        layer_map["post_feedforward_layernorm"] = "post_mlp_norm"
    if cfg.num_experts:
        # Mixtral: per-expert w1/w3/w2 (gate/up/down) + the router. Experts
        # are stacked on axis 0 of each layer to form the bank the grouped
        # matmuls consume.
        for hf_name in ("mlp.gate_proj", "mlp.up_proj", "mlp.down_proj"):
            del layer_map[hf_name]
        hf_expert = {"w_gate": "w1", "w_up": "w3", "w_down": "w2"}
        for ours, wname in hf_expert.items():
            layer_acc[ours] = [
                np.stack(
                    [
                        raw[
                            f"model.layers.{i}.block_sparse_moe.experts."
                            f"{e}.{wname}.weight"
                        ].T
                        for e in range(cfg.num_experts)
                    ],
                    axis=0,
                )
                for i in range(L)
            ]
        layer_acc["w_router"] = [
            raw[f"model.layers.{i}.block_sparse_moe.gate.weight"].T
            for i in range(L)
        ]

    for hf_name, ours in layer_map.items():
        stack = []
        for i in range(L):
            w = raw[f"model.layers.{i}.{hf_name}.weight"]
            if w.ndim == 2:
                w = w.T  # [out,in] -> [in,out]
            stack.append(w)
        layer_acc[ours] = stack
    if cfg.attention_bias:
        for hf_name, ours in _HF_BIAS_MAP.items():
            layer_acc[ours] = [
                raw[f"model.layers.{i}.{hf_name}.bias"] for i in range(L)
            ]

    for name, stack in layer_acc.items():
        stacked = np.stack(stack, axis=0)
        if qmode and name in QUANT_LAYER_KEYS:
            if qmode == "int4":
                q, s = _np_quantize_int4(stacked)
                params["layers"][name] = q
                params["layers"][name + QUANT4_SUFFIX] = s
            else:
                q, s = _np_quantize(stacked, axis=-2)
                params["layers"][name] = q
                params["layers"][name + QUANT_SUFFIX] = s
        else:
            params["layers"][name] = cast(stacked)
    logger.info("loaded %d HF tensors from %s", len(raw) + 3, model_dir)
    return params


def config_from_hf_json(config_path: str, name: str = "") -> LlamaConfig:
    """Build a :class:`LlamaConfig` from an HF ``config.json``."""
    with open(config_path) as f:
        hf = json.load(f)
    mt = hf.get("model_type", "llama")
    if mt not in (
        "llama", "mistral", "qwen2", "qwen3", "mixtral", "gemma", "gemma2",
    ):
        raise ValueError(
            f"unsupported model_type {mt!r} "
            "(llama/mistral/qwen2/qwen3/mixtral/gemma/gemma2)"
        )
    eos = hf.get("eos_token_id", 2)
    eos_ids = tuple(eos) if isinstance(eos, list) else (eos,)
    heads = hf["num_attention_heads"]
    gemma = mt in ("gemma", "gemma2")
    act = hf.get("hidden_activation") or hf.get("hidden_act") or "silu"
    act = "gelu_tanh" if act.startswith("gelu") else act
    # Sliding window: Mistral v0.1 (all layers), Gemma-2 (alternating).
    sliding = int(hf.get("sliding_window") or 0)
    if mt not in ("mistral", "gemma2"):
        sliding = 0
    # Llama-3.1-style rope scaling. "linear"/"dynamic" variants are not
    # implemented — refuse loudly rather than serve wrong long-context math.
    rs = hf.get("rope_scaling") or {}
    rs_kind = rs.get("rope_type") or rs.get("type") or ""
    if rs and rs_kind not in ("llama3", "default", ""):
        raise ValueError(
            f"unsupported rope_scaling type {rs_kind!r} (llama3 only)"
        )
    scaling = dict(
        rope_scaling_factor=float(rs.get("factor", 0.0)) if rs_kind == "llama3" else 0.0,
        rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
        rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
        rope_original_max_position=int(
            rs.get("original_max_position_embeddings", 8192)
        ),
    )
    return LlamaConfig(
        **scaling,
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=heads,
        num_kv_heads=hf.get("num_key_value_heads", heads),
        head_dim=hf.get("head_dim", hf["hidden_size"] // heads),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_position_embeddings=hf.get("max_position_embeddings", 4096),
        tie_word_embeddings=hf.get("tie_word_embeddings", gemma),
        attention_bias=mt == "qwen2" or hf.get("attention_bias", False),
        qk_norm=mt == "qwen3",
        num_experts=hf.get("num_local_experts", 0) if mt == "mixtral" else 0,
        num_experts_per_tok=hf.get("num_experts_per_tok", 2),
        hidden_act=act,
        norm_unit_offset=gemma,
        embed_scale=gemma,
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar", 0.0))
        if mt == "gemma2" else 0.0,
        attn_logit_softcap=float(hf.get("attn_logit_softcapping") or 0.0)
        if mt == "gemma2" else 0.0,
        final_logit_softcap=float(hf.get("final_logit_softcapping") or 0.0)
        if mt == "gemma2" else 0.0,
        post_block_norms=mt == "gemma2",
        sliding_window=sliding,
        sliding_window_pattern=2 if mt == "gemma2" else 1,
        name=name or hf.get("_name_or_path", mt),
        eos_token_ids=eos_ids,
        bos_token_id=hf.get("bos_token_id"),
    )
