from .llama import LlamaConfig, Llama  # noqa: F401
from .registry import get_model_config, PRESETS  # noqa: F401
