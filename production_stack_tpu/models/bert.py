"""BERT/RoBERTa/XLM-R-family encoder with a sequence-classification head.

The TRUE cross-encoder scoring path for `/rerank` and `/score`: the
reference stack serves these endpoints from engines running dedicated
scoring checkpoints (bge-reranker-* — XLM-RoBERTa encoders with a 1-label
classification head) via vLLM's `--task score`. The decoder-family engine
approximated relevance with embedding cosine similarity; this module scores
(query, document) PAIRS jointly, which is what a reranker actually is.

TPU-first notes: bidirectional attention over short (≤512-token) pairs is a
single dense [B, T, T] softmax — no paging, no masking subtleties beyond
padding — and the whole encoder is one `lax.scan` over stacked layers, so
one compiled layer body serves any depth. Weights are small (≈0.3-0.6B);
the forward runs replicated (no sharding) by design.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..logging_utils import init_logger

logger = init_logger(__name__)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 250002
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position_embeddings: int = 514
    layer_norm_eps: float = 1e-5
    num_labels: int = 1
    # BERT proper distinguishes segment A (query) from segment B (document)
    # via learned type embeddings; RoBERTa/XLM-R collapse to one type.
    type_vocab_size: int = 1
    # RoBERTa-family position ids start at pad_token_id + 1 (= 2): the
    # checkpoint's position table rows 0/1 are never used for real tokens.
    position_offset: int = 2
    pad_token_id: int = 1
    name: str = "bert"
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


BERT_PRESETS: Dict[str, BertConfig] = {
    # Tiny debug encoder for tests (random weights).
    "tiny-bert-debug": BertConfig(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        max_position_embeddings=130,
        type_vocab_size=2,
        name="tiny-bert-debug",
    ),
    # bge-reranker-base shapes (XLM-RoBERTa base, 1-label head).
    "bge-reranker-base": BertConfig(name="bge-reranker-base"),
    # bge-reranker-large shapes (XLM-RoBERTa large).
    "bge-reranker-large": BertConfig(
        hidden_size=1024,
        intermediate_size=4096,
        num_layers=24,
        num_heads=16,
        name="bge-reranker-large",
    ),
}


class BertClassifier:
    """Stateless encoder + classification-head functions bound to a config."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg

    def init_params(self, rng: jax.Array) -> Params:
        cfg = self.cfg
        d = cfg.jdtype
        D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
        k = jax.random.split(rng, 10)

        def dense(key, shape, fan_in):
            return (
                jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)
            ).astype(d)

        def ln():
            return {"w": jnp.ones((L, D), d), "b": jnp.zeros((L, D), d)}

        return {
            "word_emb": dense(k[0], (cfg.vocab_size, D), D),
            "pos_emb": dense(k[1], (cfg.max_position_embeddings, D), D),
            "type_emb": jnp.zeros((cfg.type_vocab_size, D), d),
            "emb_ln_w": jnp.ones((D,), d),
            "emb_ln_b": jnp.zeros((D,), d),
            "layers": {
                "wq": dense(k[2], (L, D, D), D),
                "bq": jnp.zeros((L, D), d),
                "wk": dense(k[3], (L, D, D), D),
                "bk": jnp.zeros((L, D), d),
                "wv": dense(k[4], (L, D, D), D),
                "bv": jnp.zeros((L, D), d),
                "wo": dense(k[5], (L, D, D), D),
                "bo": jnp.zeros((L, D), d),
                "attn_ln": ln(),
                "w1": dense(k[6], (L, D, F), D),
                "b1": jnp.zeros((L, F), d),
                "w2": dense(k[7], (L, F, D), F),
                "b2": jnp.zeros((L, D), d),
                "mlp_ln": ln(),
            },
            "cls_dense_w": dense(k[8], (D, D), D),
            "cls_dense_b": jnp.zeros((D,), d),
            "cls_out_w": dense(k[9], (D, cfg.num_labels), D),
            "cls_out_b": jnp.zeros((cfg.num_labels,), d),
        }

    def forward(
        self,
        params: Params,
        tokens: jax.Array,  # [B, T] int32 (pad with cfg.pad_token_id)
        lengths: jax.Array,  # [B] int32 valid lengths
        type_ids: Optional[jax.Array] = None,  # [B, T] segment ids (BERT)
    ) -> jax.Array:
        """Relevance logits [B] (label 0 of the classification head)."""
        cfg = self.cfg
        B, T = tokens.shape
        H, hd = cfg.num_heads, cfg.head_dim
        positions = jnp.arange(T, dtype=jnp.int32)[None, :] + cfg.position_offset
        valid = jnp.arange(T, dtype=jnp.int32)[None, :] < lengths[:, None]

        if type_ids is None:
            type_ids = jnp.zeros((B, T), jnp.int32)
        type_ids = jnp.minimum(type_ids, cfg.type_vocab_size - 1)
        x = (
            params["word_emb"][tokens]
            + params["pos_emb"][jnp.minimum(
                positions, cfg.max_position_embeddings - 1
            )]
            + params["type_emb"][type_ids]
        )
        x = _layer_norm(x, params["emb_ln_w"], params["emb_ln_b"],
                        cfg.layer_norm_eps)

        mask = valid[:, None, None, :]  # [B, 1, 1, T] — padding only (bidir)

        def layer(x, lp):
            q = (x @ lp["wq"] + lp["bq"]).reshape(B, T, H, hd)
            kk = (x @ lp["wk"] + lp["bk"]).reshape(B, T, H, hd)
            v = (x @ lp["wv"] + lp["bv"]).reshape(B, T, H, hd)
            scores = jnp.einsum(
                "bthd,bshd->bhts", q, kk, preferred_element_type=jnp.float32
            ) / math.sqrt(hd)
            scores = jnp.where(mask, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum(
                "bhts,bshd->bthd", probs.astype(v.dtype), v,
                preferred_element_type=jnp.float32,
            ).reshape(B, T, cfg.hidden_size).astype(x.dtype)
            a = attn @ lp["wo"] + lp["bo"]
            x = _layer_norm(x + a, lp["attn_ln"]["w"], lp["attn_ln"]["b"],
                            cfg.layer_norm_eps)
            f = jax.nn.gelu(
                (x @ lp["w1"] + lp["b1"]).astype(jnp.float32),
                approximate=False,
            ).astype(x.dtype)
            f = f @ lp["w2"] + lp["b2"]
            x = _layer_norm(x + f, lp["mlp_ln"]["w"], lp["mlp_ln"]["b"],
                            cfg.layer_norm_eps)
            return x, None

        x, _ = jax.lax.scan(layer, x, params["layers"])
        # RoBERTa classification head: dense+tanh on the <s> (first) token.
        cls = x[:, 0]
        h = jnp.tanh(cls @ params["cls_dense_w"] + params["cls_dense_b"])
        logits = h @ params["cls_out_w"] + params["cls_out_b"]
        # Relevance score: 1-label heads (bge-reranker style) score column
        # 0; 2-label sequence-classification heads conventionally put the
        # positive class at label 1 (ADVICE r3: column 0 would score the
        # negative class). >2 labels are rejected at config parse.
        col = 1 if cfg.num_labels == 2 else 0
        return logits[:, col].astype(jnp.float32)


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return (((xf - mu) * jax.lax.rsqrt(var + eps)) * w + b).astype(x.dtype)


def bert_config_from_hf(config_path: str, name: str = "") -> BertConfig:
    with open(config_path) as f:
        hf = json.load(f)
    mt = hf.get("model_type", "")
    if mt not in ("bert", "roberta", "xlm-roberta"):
        raise ValueError(
            f"unsupported scoring model_type {mt!r} (bert/roberta/xlm-roberta)"
        )
    roberta = mt != "bert"
    n_labels = len(hf.get("id2label", {0: ""})) or 1
    if n_labels > 2:
        # A >2-class head has no single "relevance" column; refuse loudly
        # rather than silently scoring an arbitrary class.
        raise ValueError(
            f"scoring model has {n_labels} labels; cross-encoder scoring "
            "supports 1-label (regression) or 2-label (positive=1) heads"
        )
    return BertConfig(
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        max_position_embeddings=hf["max_position_embeddings"],
        layer_norm_eps=hf.get("layer_norm_eps", 1e-5),
        num_labels=n_labels,
        position_offset=(hf.get("pad_token_id", 1) or 0) + 1 if roberta else 0,
        pad_token_id=hf.get("pad_token_id", 1 if roberta else 0),
        type_vocab_size=hf.get("type_vocab_size", 1),
        name=name or mt,
    )


def load_hf_bert_params(cfg: BertConfig, model_dir: str) -> Params:
    """Load an HF ...ForSequenceClassification checkpoint (safetensors).

    Handles the `roberta.`/`bert.`/bare prefixes and both head layouts:
    RoBERTa (`classifier.dense` + `classifier.out_proj`) and BERT
    (`bert.pooler.dense` + bare `classifier`).
    """
    from safetensors import safe_open

    files = sorted(
        os.path.join(model_dir, f)
        for f in os.listdir(model_dir)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {model_dir}")
    raw: Dict[str, np.ndarray] = {}
    for path in files:
        with safe_open(path, framework="numpy") as f:
            for key in f.keys():
                raw[key] = np.asarray(f.get_tensor(key))

    prefix = ""
    for p in ("roberta.", "bert.", ""):
        if f"{p}embeddings.word_embeddings.weight" in raw:
            prefix = p
            break

    d = cfg.jdtype
    cast = lambda a: jnp.asarray(a, d)  # noqa: E731
    g = lambda k: raw[prefix + k]  # noqa: E731

    L, D = cfg.num_layers, cfg.hidden_size
    lay = {
        "wq": [], "bq": [], "wk": [], "bk": [], "wv": [], "bv": [],
        "wo": [], "bo": [],
        "attn_ln": {"w": [], "b": []},
        "w1": [], "b1": [], "w2": [], "b2": [],
        "mlp_ln": {"w": [], "b": []},
    }
    for i in range(L):
        e = f"encoder.layer.{i}."
        lay["wq"].append(g(e + "attention.self.query.weight").T)
        lay["bq"].append(g(e + "attention.self.query.bias"))
        lay["wk"].append(g(e + "attention.self.key.weight").T)
        lay["bk"].append(g(e + "attention.self.key.bias"))
        lay["wv"].append(g(e + "attention.self.value.weight").T)
        lay["bv"].append(g(e + "attention.self.value.bias"))
        lay["wo"].append(g(e + "attention.output.dense.weight").T)
        lay["bo"].append(g(e + "attention.output.dense.bias"))
        lay["attn_ln"]["w"].append(g(e + "attention.output.LayerNorm.weight"))
        lay["attn_ln"]["b"].append(g(e + "attention.output.LayerNorm.bias"))
        lay["w1"].append(g(e + "intermediate.dense.weight").T)
        lay["b1"].append(g(e + "intermediate.dense.bias"))
        lay["w2"].append(g(e + "output.dense.weight").T)
        lay["b2"].append(g(e + "output.dense.bias"))
        lay["mlp_ln"]["w"].append(g(e + "output.LayerNorm.weight"))
        lay["mlp_ln"]["b"].append(g(e + "output.LayerNorm.bias"))

    def stack(v):
        if isinstance(v, dict):
            return {kk: stack(vv) for kk, vv in v.items()}
        return cast(np.stack(v, axis=0))

    if "classifier.dense.weight" in raw:  # RoBERTa head
        head = {
            "cls_dense_w": cast(raw["classifier.dense.weight"].T),
            "cls_dense_b": cast(raw["classifier.dense.bias"]),
            "cls_out_w": cast(raw["classifier.out_proj.weight"].T),
            "cls_out_b": cast(raw["classifier.out_proj.bias"]),
        }
    else:  # BERT head: pooler dense+tanh then classifier
        head = {
            "cls_dense_w": cast(g("pooler.dense.weight").T),
            "cls_dense_b": cast(g("pooler.dense.bias")),
            "cls_out_w": cast(raw["classifier.weight"].T),
            "cls_out_b": cast(raw["classifier.bias"]),
        }

    params: Params = {
        "word_emb": cast(g("embeddings.word_embeddings.weight")),
        "pos_emb": cast(g("embeddings.position_embeddings.weight")),
        "type_emb": cast(g("embeddings.token_type_embeddings.weight")),
        "emb_ln_w": cast(g("embeddings.LayerNorm.weight")),
        "emb_ln_b": cast(g("embeddings.LayerNorm.bias")),
        "layers": stack(lay),
        **head,
    }
    logger.info("loaded %d cross-encoder tensors from %s", len(raw), model_dir)
    return params


def get_bert_config(model: str) -> BertConfig:
    if model in BERT_PRESETS:
        return BERT_PRESETS[model]
    cfg_path = os.path.join(model, "config.json")
    if os.path.isfile(cfg_path):
        return bert_config_from_hf(cfg_path, name=model)
    raise ValueError(
        f"unknown scoring model {model!r}: not a preset "
        f"({', '.join(sorted(BERT_PRESETS))}) and no local HF dir found"
    )
