"""Model registry: presets for known architectures + HF-dir resolution.

The reference selects models by HF id passed to ``vllm serve``
(`deployment-vllm-multi.yaml:101-118`). Here a model is either a local HF
directory (config.json + safetensors, loaded zero-egress) or a named preset
(random-init — used by tests, benchmarks, and the fake fleet).
"""

from __future__ import annotations

import os
from typing import Dict

from .llama import LlamaConfig, config_from_hf_json

# Architecture presets. Shapes match the public configs of each family so
# perf numbers are honest; weights are random-init unless an HF dir is given.
PRESETS: Dict[str, LlamaConfig] = {
    # Tiny debug model for unit tests / CPU-mesh e2e (heads divisible by 8
    # so every tp degree the test mesh uses divides cleanly).
    "tiny-llama-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=8,
        num_kv_heads=8,
        head_dim=16,
        max_position_embeddings=2048,
        name="tiny-llama-debug",
        eos_token_ids=(0,),
        bos_token_id=None,
        dtype="float32",
    ),
    # ~1B-class model: single-chip bench workhorse.
    "llama-1b": LlamaConfig(
        vocab_size=32768,
        hidden_size=2048,
        intermediate_size=5632,
        num_layers=16,
        num_heads=16,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        name="llama-1b",
        eos_token_ids=(2,),
    ),
    # Llama-3-8B shapes (the BASELINE.md flagship target).
    "llama-3-8b": LlamaConfig(
        vocab_size=128256,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=131072,
        name="llama-3-8b",
        eos_token_ids=(128001, 128009),
        bos_token_id=128000,
    ),
    # Llama-3-70B shapes (pipeline-parallel multi-host config ladder rung 5).
    "llama-3-70b": LlamaConfig(
        vocab_size=128256,
        hidden_size=8192,
        intermediate_size=28672,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=500000.0,
        max_position_embeddings=131072,
        name="llama-3-70b",
        eos_token_ids=(128001, 128009),
        bos_token_id=128000,
    ),
    "mistral-7b": LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        name="mistral-7b",
        eos_token_ids=(2,),
    ),
    # Tiny MoE debug model (Mixtral-style sparse MLP; 4 experts, top-2).
    "tiny-mixtral-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=8,
        num_kv_heads=8,
        head_dim=16,
        max_position_embeddings=2048,
        num_experts=4,
        num_experts_per_tok=2,
        name="tiny-mixtral-debug",
        eos_token_ids=(0,),
        bos_token_id=None,
        dtype="float32",
    ),
    # Mixtral-8x7B shapes (sparse MoE flagship; 47B params, 13B active).
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000,
        hidden_size=4096,
        intermediate_size=14336,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        max_position_embeddings=32768,
        num_experts=8,
        num_experts_per_tok=2,
        name="mixtral-8x7b",
        eos_token_ids=(2,),
    ),
    # Tiny Gemma-1-style debug model (GeGLU, (1+w) norms, scaled embeddings,
    # tied head).
    "tiny-gemma-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=8,
        num_kv_heads=4,
        head_dim=16,
        max_position_embeddings=2048,
        hidden_act="gelu_tanh",
        norm_unit_offset=True,
        embed_scale=True,
        tie_word_embeddings=True,
        name="tiny-gemma-debug",
        eos_token_ids=(0,),
        bos_token_id=None,
        dtype="float32",
    ),
    # Tiny Gemma-2-style debug model (adds logit softcaps, post-block norms,
    # alternating sliding-window layers).
    "tiny-gemma2-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=4,
        num_heads=8,
        num_kv_heads=4,
        head_dim=16,
        max_position_embeddings=2048,
        hidden_act="gelu_tanh",
        norm_unit_offset=True,
        embed_scale=True,
        tie_word_embeddings=True,
        query_pre_attn_scalar=32.0,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        post_block_norms=True,
        sliding_window=16,
        sliding_window_pattern=2,
        name="tiny-gemma2-debug",
        eos_token_ids=(0,),
        bos_token_id=None,
        dtype="float32",
    ),
    "gemma-7b": LlamaConfig(
        vocab_size=256000,
        hidden_size=3072,
        intermediate_size=24576,
        num_layers=28,
        num_heads=16,
        num_kv_heads=16,
        head_dim=256,
        rope_theta=10000.0,
        max_position_embeddings=8192,
        hidden_act="gelu_tanh",
        norm_unit_offset=True,
        embed_scale=True,
        tie_word_embeddings=True,
        name="gemma-7b",
        eos_token_ids=(1,),
        bos_token_id=2,
    ),
    "gemma2-9b": LlamaConfig(
        vocab_size=256000,
        hidden_size=3584,
        intermediate_size=14336,
        num_layers=42,
        num_heads=16,
        num_kv_heads=8,
        head_dim=256,
        rope_theta=10000.0,
        max_position_embeddings=8192,
        hidden_act="gelu_tanh",
        norm_unit_offset=True,
        embed_scale=True,
        tie_word_embeddings=True,
        query_pre_attn_scalar=256.0,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        post_block_norms=True,
        sliding_window=4096,
        sliding_window_pattern=2,
        name="gemma2-9b",
        eos_token_ids=(1,),
        bos_token_id=2,
    ),
    # Tiny Qwen3-style debug model (per-head q/k RMSNorm, no QKV bias).
    "tiny-qwen3-debug": LlamaConfig(
        vocab_size=512,
        hidden_size=128,
        intermediate_size=256,
        num_layers=2,
        num_heads=8,
        num_kv_heads=4,
        head_dim=16,
        max_position_embeddings=2048,
        qk_norm=True,
        name="tiny-qwen3-debug",
        eos_token_ids=(0,),
        bos_token_id=None,
        dtype="float32",
    ),
    "qwen3-8b": LlamaConfig(
        vocab_size=151936,
        hidden_size=4096,
        intermediate_size=12288,
        num_layers=36,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        rope_theta=1000000.0,
        max_position_embeddings=40960,
        qk_norm=True,
        name="qwen3-8b",
        eos_token_ids=(151645, 151643),
        bos_token_id=None,
    ),
    "qwen2-7b": LlamaConfig(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        rope_theta=1000000.0,
        attention_bias=True,
        name="qwen2-7b",
        eos_token_ids=(151645, 151643),
        bos_token_id=None,
    ),
}


def get_model_config(model: str) -> LlamaConfig:
    """Resolve ``model`` to a config: preset name or local HF directory."""
    if model in PRESETS:
        return PRESETS[model]
    cfg_path = os.path.join(model, "config.json")
    if os.path.isfile(cfg_path):
        return config_from_hf_json(cfg_path, name=model)
    raise ValueError(
        f"unknown model {model!r}: not a preset "
        f"({', '.join(sorted(PRESETS))}) and no local HF dir found"
    )
