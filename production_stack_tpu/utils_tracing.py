"""Optional error reporting + distributed tracing plumbing.

Reference parity (SURVEY.md §5): the router initializes Sentry when a DSN is
configured (`src/vllm_router/app.py:123-130`, flags `parser.py:338-355`) and
tracing reaches the engines through standard OpenTelemetry environment
variables applied by deployment config (`tutorials/12-distributed-tracing.md`).

Both integrations are OPTIONAL dependencies: this module degrades to loud
no-ops when `sentry_sdk` / `opentelemetry` are not installed (they are not
part of the base image), so enabling the flags never breaks serving.

OTel env contract (the chart's `observability.otelExporterEndpoint` value
sets these on router AND engine pods; consumed here when the SDK is present):
  OTEL_SERVICE_NAME, OTEL_EXPORTER_OTLP_ENDPOINT, OTEL_RESOURCE_ATTRIBUTES
"""

from __future__ import annotations

import os
from typing import Optional

from .logging_utils import init_logger

logger = init_logger(__name__)

# Set by init_otel: None = never attempted, False = attempted and degraded
# (endpoint unset / SDK missing), True = a real TracerProvider is installed.
_otel_state: Optional[bool] = None


def otel_active() -> bool:
    """Whether init_otel installed a real SDK TracerProvider this process.

    The in-process span recorder (``obs/tracing.py``) consults this before
    mirroring spans, so the OTel SDK is never touched unless it was
    successfully initialized."""
    return bool(_otel_state)


def reset_otel_state_for_tests() -> None:
    global _otel_state
    _otel_state = None


def init_sentry(dsn: Optional[str], traces_sample_rate: float = 0.0,
                profile_session_sample_rate: float = 0.0) -> bool:
    """Initialize Sentry when a DSN is given and sentry_sdk is installed."""
    if not dsn:
        return False
    try:
        import sentry_sdk
    except ImportError:
        logger.warning(
            "--sentry-dsn set but sentry_sdk is not installed; "
            "error reporting disabled (pip install sentry-sdk)"
        )
        return False
    sentry_sdk.init(
        dsn=dsn,
        traces_sample_rate=traces_sample_rate,
        profile_session_sample_rate=profile_session_sample_rate,
    )
    logger.info("sentry initialized (traces_sample_rate=%s)", traces_sample_rate)
    return True


def init_otel(service_name_default: str) -> bool:
    """Initialize OpenTelemetry tracing from the standard env contract.

    Activates only when OTEL_EXPORTER_OTLP_ENDPOINT is set AND the OTel SDK
    is importable; spans export over OTLP to the configured collector (the
    reference wires the same envs into its engines,
    `tutorials/12-distributed-tracing.md:1-70`).

    Idempotent: a second call (router and engine bootstrap paths can both
    reach here in one process, e.g. in tests) returns the first outcome
    without installing a second TracerProvider — the SDK would reject it
    and the duplicate BatchSpanProcessor would double-export every span."""
    global _otel_state
    if _otel_state is not None:
        return _otel_state
    endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT")
    if not endpoint:
        # Not cached: the endpoint may be configured later in-process
        # (tests, dynamic bootstrap) and a retry should then succeed.
        return False
    try:
        from opentelemetry import trace
        from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (
            OTLPSpanExporter,
        )
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import BatchSpanProcessor
    except ImportError:
        logger.warning(
            "OTEL_EXPORTER_OTLP_ENDPOINT set but the OpenTelemetry SDK is "
            "not installed; tracing disabled (pip install opentelemetry-sdk "
            "opentelemetry-exporter-otlp)"
        )
        _otel_state = False
        return False
    service = os.environ.get("OTEL_SERVICE_NAME", service_name_default)
    resource = Resource.create({"service.name": service})
    try:
        # Span-recorder mirroring (obs/tracing.py) replays spans with the
        # recorder's own trace/span ids so exported parent links resolve;
        # older SDKs without the id_generator kwarg fall back to random
        # ids (spans still export, parent links degrade).
        from .obs.tracing import MirroredIdGenerator

        provider = TracerProvider(
            resource=resource, id_generator=MirroredIdGenerator()
        )
    except TypeError:
        provider = TracerProvider(resource=resource)
    provider.add_span_processor(BatchSpanProcessor(OTLPSpanExporter()))
    trace.set_tracer_provider(provider)
    logger.info("otel tracing initialized: %s -> %s", service, endpoint)
    _otel_state = True
    return True
