"""KV-cache block hashing, tiering, and transfer shared by engine/router/kvserver."""
