"""Content hashing of token chunks — the contract that makes prefix reuse,
KV-aware routing, and remote KV lookup agree with each other.

The reference delegates this to LMCache (engines report chunk hashes to the
LMCache controller; the router tokenizes and asks the controller for the
longest match — ``routing_logic.py:287-299``). Here the scheme is explicit
and shared: a rolling xxhash over fixed-size token chunks, where each chunk
hash commits to the full prefix before it (so equal hash ⇒ equal prefix,
modulo 64-bit collisions).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import xxhash

# One hash per CHUNK_TOKENS tokens. Must divide/align with the engine KV
# block size (engine blocks per chunk = CHUNK_TOKENS // block_size).
CHUNK_TOKENS = 256


def chunk_hashes(token_ids: Sequence[int], chunk_tokens: int = CHUNK_TOKENS) -> List[int]:
    """Prefix-committing hashes of each full chunk of ``token_ids``.

    Only complete chunks are hashed: a 700-token prompt with 256-token
    chunks yields 2 hashes. Returns unsigned 63-bit ints (JSON-safe).
    """
    out: List[int] = []
    prev = 0
    n_full = len(token_ids) // chunk_tokens
    arr = np.asarray(token_ids[: n_full * chunk_tokens], dtype=np.int64)
    for i in range(n_full):
        h = xxhash.xxh64(arr[i * chunk_tokens : (i + 1) * chunk_tokens].tobytes())
        h.update(prev.to_bytes(8, "little"))
        # Chain on the *returned* (masked) value so incremental callers can
        # resume from any emitted hash and land on the identical chain.
        prev = h.intdigest() & 0x7FFF_FFFF_FFFF_FFFF
        out.append(prev)
    return out


def block_hashes(
    token_ids: Sequence[int], block_size: int, parent: int = 0
) -> List[int]:
    """Per-KV-block prefix-committing hashes (engine-side prefix caching).

    Same construction as :func:`chunk_hashes` but at engine block
    granularity, with an optional parent hash to chain from (used when
    extending an existing sequence).
    """
    out: List[int] = []
    prev = parent
    n_full = len(token_ids) // block_size
    arr = np.asarray(token_ids[: n_full * block_size], dtype=np.int64)
    for i in range(n_full):
        h = xxhash.xxh64(arr[i * block_size : (i + 1) * block_size].tobytes())
        h.update(prev.to_bytes(8, "little", signed=False))
        prev = h.intdigest() & 0x7FFF_FFFF_FFFF_FFFF  # chain == emitted value
        out.append(prev)
    return out
