"""Owned background tasks: reference-held, exception-observed, cancellable.

``asyncio`` keeps only *weak* references to tasks: a fire-and-forget
``asyncio.create_task(...)`` whose result nobody stores can be garbage
collected mid-await (the PR 10 review caught exactly this on the trie
eviction walks), and a crashed loop task whose exception nobody reads
dies silently — the scrape/canary/gossip loop is simply gone until an
operator notices the metrics went flat.

:func:`spawn_owned` is the sanctioned spawn point for background work:

- the task is strongly referenced by a process-wide registry until it
  finishes (no mid-walk GC),
- a done-callback *observes* the task's outcome and logs any non-
  cancellation exception with the task's name (a dead loop is loud),
- the returned task is still the caller's to cancel — ``close()`` paths
  keep working unchanged, and :func:`cancel_owned` sweeps whatever is
  left at shutdown.

The ``task-lifecycle`` pstlint check (docs/static-analysis.md) enforces
the contract tree-wide: every ``create_task``/``ensure_future`` site must
either go through this helper, store the task on an annotated owner
(``# pstlint: task-owner=<attr>``) with a cancellation path, or be a
local task whose result is actually awaited.
"""

from __future__ import annotations

import asyncio
from typing import Any, Coroutine, Optional, Set

from ..logging_utils import init_logger

logger = init_logger(__name__)

# Strong references for every spawn_owned task, process-wide. Tasks are
# not app state (they die with the loop, not the app), so one registry
# serves every router app in the process.
# pstlint: owned-by=task:spawn_owned,_observe,cancel_owned
_OWNED_TASKS: Set["asyncio.Task[Any]"] = set()


def _observe(task: "asyncio.Task[Any]") -> None:
    """Done-callback: release the strong reference and surface crashes."""
    _OWNED_TASKS.discard(task)
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        logger.error(
            "background task %r died: %r", task.get_name(), exc
        )


def spawn_owned(
    coro: Coroutine[Any, Any, Any], *, name: Optional[str] = None
) -> "asyncio.Task[Any]":
    """``create_task`` with a strong reference and exception observation.

    Requires a running event loop (same contract as
    ``asyncio.create_task``). The caller may keep the returned task for
    its own cancellation path; the registry reference is dropped by the
    done-callback either way.
    """
    loop = asyncio.get_running_loop()
    # pstlint: task-owner=_OWNED_TASKS
    task = loop.create_task(coro, name=name)
    _OWNED_TASKS.add(task)
    task.add_done_callback(_observe)
    return task


def owned_task_count() -> int:
    """Live spawn_owned tasks (tests / diagnostics)."""
    return sum(1 for t in _OWNED_TASKS if not t.done())


def cancel_owned() -> int:
    """Cancel every still-running owned task (process shutdown sweep).

    Returns the number of tasks cancelled. Individual owners' ``close()``
    paths normally cancel their own tasks first; this is the backstop so
    nothing outlives the loop.
    """
    cancelled = 0
    for task in list(_OWNED_TASKS):
        if not task.done():
            task.cancel()
            cancelled += 1
    return cancelled
