"""Tail-outlier forensics: turn a bad measured point into evidence.

The missing layer ROADMAP item 5 names: BENCH_r05 carries a 120 s p99 at
qps 0.5 and *nothing that explains it* — the flight recorder retained
the snapshot naming the stalled step's bucket and queue state, but no
path connected the measured outlier back to it. This module closes the
loop: whenever a measured bench point (or an e2e leg) crosses its tail
bar — ``p99 > factor × p50`` (the sweep's ``tail_outlier`` flag) or an
absolute SLO bar — the collector harvests an **evidence bundle**:

- the engine's flight-recorder dump with retained + persisted snapshots
  (``GET /debug/flight?snapshots=1``) and its ``/debug/state``;
- the ``/debug/requests`` timelines for the worst trace ids (by
  duration) on engine and router;
- the router's gossip-merged ``GET /debug/fleet`` snapshot;
- before/after ``/metrics`` deltas (``mark()`` before measuring, delta
  at collection);
- any snapshots a dead engine persisted to ``--flight-snapshot-dir``
  (the post-mortem path — collectable after SIGKILL).

Bundles are written as JSON beside the bench output
(``<out>.evidence/point_<phase>_<point>.json``), and counted by
``pst_forensics_bundles_total{trigger}``.

Deliberately stdlib-only on the collection path (urllib, no aiohttp):
``bench.py`` imports this before any server dependency is guaranteed,
and every fetch is best-effort — a half-dead stack yields a bundle with
``error`` entries, never an exception that kills the bench run.
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.request
from typing import Dict, Iterable, List, Optional

from .flight import load_snapshot_dir

BUNDLE_SCHEMA = "pst-evidence-bundle/v1"
DEFAULT_TAIL_FACTOR = 3.0


def crosses_tail_bar(
    p50_ms: Optional[float],
    p99_ms: Optional[float],
    factor: float = DEFAULT_TAIL_FACTOR,
    abs_bar_ms: Optional[float] = None,
) -> Optional[str]:
    """The trigger name when (p50, p99) crosses a tail bar, else None.

    ``tail_outlier`` is the sweep's own flag (p99 worse than ``factor`` ×
    p50 — an unexplained tail); ``slo_bar`` is an absolute p99 bar for
    legs with an SLO target instead of a self-relative shape."""
    if p99_ms is None:
        return None
    if abs_bar_ms is not None and p99_ms > abs_bar_ms:
        return "slo_bar"
    if p50_ms is not None and p50_ms > 0 and p99_ms > factor * p50_ms:
        return "tail_outlier"
    return None


def _fetch_json(url: str, timeout: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def fetch_metrics(url: str, timeout: float = 5.0) -> Dict[str, float]:
    """One ``/metrics`` scrape parsed to ``{series_key: value}``.

    The key is the full sample line head (name + label set), so deltas
    are per-series — a counter moving on one engine is attributable."""
    with urllib.request.urlopen(f"{url}/metrics", timeout=timeout) as r:
        text = r.read().decode()
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(" ", 1)
            out[key.strip()] = float(val)
        except ValueError:
            continue
    return out


def metrics_delta(
    before: Dict[str, float], after: Dict[str, float]
) -> Dict[str, float]:
    """Per-series after−before for series that moved (or appeared).

    A series absent from ``before`` (first observation after the mark)
    delta-counts its full value — new label children born during the
    measured window are part of what happened in it."""
    out: Dict[str, float] = {}
    for key, val in after.items():
        d = val - before.get(key, 0.0)
        if d != 0.0:
            out[key] = round(d, 6)
    return out


def worst_traces(requests_payload: dict, n: int = 3) -> List[dict]:
    """The ``n`` slowest request timelines from a ``/debug/requests``
    body (most evidence per byte: the traces that ARE the tail)."""
    reqs = requests_payload.get("requests") or []
    reqs = [r for r in reqs if isinstance(r, dict)]
    reqs.sort(key=lambda r: r.get("duration_ms") or 0.0, reverse=True)
    return reqs[:n]


def _point_slug(phase: str, point) -> str:
    raw = f"{phase}_{point}"
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", raw)


class ForensicsCollector:
    """Harvests evidence bundles into ``<out>.evidence/``.

    Lifecycle per measured leg: ``mark(urls)`` before the load starts
    (captures the /metrics baseline), measure, then ``maybe_collect``
    with the leg's p50/p99 — a crossed bar harvests and writes the
    bundle; a healthy leg costs one dict comparison."""

    def __init__(
        self,
        evidence_dir: str,
        tail_factor: float = DEFAULT_TAIL_FACTOR,
        timeout_s: float = 5.0,
    ):
        self.evidence_dir = evidence_dir
        self.tail_factor = float(tail_factor)
        self.timeout_s = float(timeout_s)
        self.bundles: List[str] = []

    # -- metrics baseline -------------------------------------------------

    def mark(self, urls: Iterable[str]) -> Dict[str, Dict[str, float]]:
        """Best-effort /metrics baseline for each URL (missing scrapes
        record an empty dict: the delta then shows absolute values)."""
        baseline: Dict[str, Dict[str, float]] = {}
        for url in urls:
            try:
                baseline[url] = fetch_metrics(url, self.timeout_s)
            except Exception:  # noqa: BLE001 — evidence is best-effort
                baseline[url] = {}
        return baseline

    # -- collection -------------------------------------------------------

    def maybe_collect(
        self,
        phase: str,
        point,
        p50_ms: Optional[float],
        p99_ms: Optional[float],
        *,
        abs_bar_ms: Optional[float] = None,
        engines: Iterable[str] = (),
        router: Optional[str] = None,
        snapshot_dirs: Iterable[str] = (),
        baseline: Optional[Dict[str, Dict[str, float]]] = None,
        detail: Optional[dict] = None,
    ) -> Optional[str]:
        trigger = crosses_tail_bar(
            p50_ms, p99_ms, self.tail_factor, abs_bar_ms
        )
        if trigger is None:
            return None
        full_detail = {
            "p50_ms": p50_ms,
            "p99_ms": p99_ms,
            "tail_factor": self.tail_factor,
            "abs_bar_ms": abs_bar_ms,
            **(detail or {}),
        }
        return self.collect(
            trigger, phase, point,
            engines=engines, router=router, snapshot_dirs=snapshot_dirs,
            baseline=baseline, detail=full_detail,
        )

    def collect(
        self,
        trigger: str,
        phase: str,
        point,
        *,
        engines: Iterable[str] = (),
        router: Optional[str] = None,
        snapshot_dirs: Iterable[str] = (),
        baseline: Optional[Dict[str, Dict[str, float]]] = None,
        detail: Optional[dict] = None,
        worst_n: int = 3,
    ) -> str:
        """Harvest one bundle NOW and write it; returns the file path.

        Every fetch is individually guarded: a dead engine contributes
        ``{"error": ...}`` plus whatever its snapshot dir retained."""
        t = self.timeout_s
        bundle: dict = {
            "schema": BUNDLE_SCHEMA,
            "trigger": trigger,
            "phase": phase,
            "point": point,
            "ts": time.time(),
            "detail": detail or {},
            "engines": {},
            "router": None,
            "metrics_delta": {},
            "postmortem_snapshots": [],
        }
        for url in engines:
            entry: dict = {}
            try:
                entry["flight"] = _fetch_json(
                    f"{url}/debug/flight?snapshots=1", t
                )
            except Exception as e:  # noqa: BLE001
                entry["flight"] = {"error": str(e)}
            try:
                entry["state"] = _fetch_json(f"{url}/debug/state", t)
            except Exception as e:  # noqa: BLE001
                entry["state"] = {"error": str(e)}
            try:
                entry["worst_traces"] = worst_traces(
                    _fetch_json(f"{url}/debug/requests?limit=100", t),
                    worst_n,
                )
            except Exception as e:  # noqa: BLE001
                entry["worst_traces"] = [{"error": str(e)}]
            bundle["engines"][url] = entry
        if router:
            rentry: dict = {"url": router}
            try:
                rentry["fleet"] = _fetch_json(f"{router}/debug/fleet", t)
            except Exception as e:  # noqa: BLE001
                rentry["fleet"] = {"error": str(e)}
            try:
                rentry["worst_traces"] = worst_traces(
                    _fetch_json(f"{router}/debug/requests?limit=100", t),
                    worst_n,
                )
            except Exception as e:  # noqa: BLE001
                rentry["worst_traces"] = [{"error": str(e)}]
            bundle["router"] = rentry
        for url in (baseline or {}):
            try:
                bundle["metrics_delta"][url] = metrics_delta(
                    baseline[url], fetch_metrics(url, t)
                )
            except Exception as e:  # noqa: BLE001
                bundle["metrics_delta"][url] = {"error": str(e)}
        for d in snapshot_dirs:
            bundle["postmortem_snapshots"].extend(load_snapshot_dir(d))
        path = self._write(bundle, phase, point)
        try:
            from .metrics import note_forensics_bundle

            note_forensics_bundle(trigger)
        except Exception:  # noqa: BLE001 — metrics must not kill harvest
            pass
        return path

    def collect_postmortem(
        self,
        phase: str,
        point,
        snapshot_dirs: Iterable[str],
        detail: Optional[dict] = None,
    ) -> Optional[str]:
        """The after-death path: no live endpoints, only what the engine
        persisted to ``--flight-snapshot-dir`` before it was killed.
        Returns None (no bundle) when the dirs hold nothing — an empty
        post-mortem is noise, not evidence."""
        snaps: List[dict] = []
        for d in snapshot_dirs:
            snaps.extend(load_snapshot_dir(d))
        if not snaps:
            return None
        bundle = {
            "schema": BUNDLE_SCHEMA,
            "trigger": "postmortem",
            "phase": phase,
            "point": point,
            "ts": time.time(),
            "detail": detail or {},
            "engines": {},
            "router": None,
            "metrics_delta": {},
            "postmortem_snapshots": snaps,
        }
        path = self._write(bundle, phase, point)
        try:
            from .metrics import note_forensics_bundle

            note_forensics_bundle("postmortem")
        except Exception:  # noqa: BLE001
            pass
        return path

    def _write(self, bundle: dict, phase: str, point) -> str:
        os.makedirs(self.evidence_dir, exist_ok=True)
        path = os.path.join(
            self.evidence_dir, f"point_{_point_slug(phase, point)}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f)
        os.replace(tmp, path)
        self.bundles.append(path)
        return path


def evidence_dir_for(out_path: Optional[str]) -> str:
    """``<out>.evidence`` beside the bench output ($PST_BENCH_OUT when
    set, a /tmp default otherwise — the bundles must land somewhere even
    when the driver never asked for a file mirror)."""
    base = out_path or "/tmp/pst_bench"
    return base + ".evidence"
