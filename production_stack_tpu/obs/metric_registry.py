"""The single source of truth for every ``pst`` metric name.

Dashboards (observability/gen_dashboards.py), alert rules
(prometheus-rules.yaml), docs/observability.md and operators' PromQL all
key on these names; before this module they were re-listed in each
consumer and drift was caught (at best) by a regex scan. Now: code that
constructs a ``pst``-prefixed Counter/Gauge/Histogram must have a
matching :class:`MetricSpec` here — the ``metric-registry`` pstlint
check enforces both directions (undeclared constructor -> finding; stale
declaration -> finding) plus docs coverage, and
``scripts/check_metric_docs.py`` is a thin CI shim over the same logic.

Kept importable with zero third-party dependencies (no prometheus_client
import) so the analyzer and scripts can consume it on a bare checkout.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    """One declared metric family.

    ``name`` is the constructor name (what ``Counter(...)`` receives —
    prometheus_client appends ``_total`` to counters at exposition);
    ``module`` is the declaring module, for doc pointers.
    """

    name: str
    kind: str
    module: str

    @property
    def exposition_name(self) -> str:
        if self.kind == COUNTER and not self.name.endswith("_total"):
            return self.name + "_total"
        return self.name


# Declaration order groups by owning module (matches the metric rows in
# docs/observability.md).
REGISTRY: Tuple[MetricSpec, ...] = (
    # --- obs/metrics.py: shared stage-latency decomposition -------------
    MetricSpec("pst_stage_duration_seconds", HISTOGRAM, "obs/metrics.py"),
    # Replicated remote-KV tier integrity (docs/kvserver.md): corrupt
    # replica copies detected on read (by source path) and blocks
    # re-pushed to owners that missed them (read-repair).
    MetricSpec("pst_kv_integrity_failures", COUNTER, "obs/metrics.py"),
    MetricSpec("pst_kv_read_repairs", COUNTER, "obs/metrics.py"),
    # Evidence plane (docs/observability.md "Forensics bundles" /
    # "Flight recorder"): bundles harvested when a measured point crosses
    # its tail bar, and flight snapshots persisted to disk so they
    # survive process death.
    MetricSpec("pst_forensics_bundles", COUNTER, "obs/metrics.py"),
    MetricSpec("pst_engine_flight_snapshots_persisted", COUNTER, "obs/metrics.py"),
    # --- obs/logging.py: structured-logging hot-path sampler ------------
    MetricSpec("pst_log_dropped", COUNTER, "obs/logging.py"),
    # --- obs/engine_telemetry.py: TPU engine device layer ---------------
    MetricSpec("pst_engine_compile", COUNTER, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_compile_seconds", HISTOGRAM, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_step_duration_seconds", HISTOGRAM, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_host_gap_seconds", HISTOGRAM, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_batch_fill_ratio", HISTOGRAM, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_tokens_per_second", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_mfu", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_kv_page_occupancy", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_kv_page_high_watermark", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_preemptions", COUNTER, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_swap_out", COUNTER, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_swap_in", COUNTER, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_start_time_seconds", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_startup_seconds", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_warmup_coverage", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_warmup_buckets", GAUGE, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_compile_cache_hits", COUNTER, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_compile_cache_misses", COUNTER, "obs/engine_telemetry.py"),
    # Per-request cost attribution (docs/observability.md "Cost
    # attribution"): device-seconds per finished request + the per-tenant
    # chip-time billing meter and its audit denominator.
    MetricSpec("pst_request_device_seconds", HISTOGRAM, "obs/engine_telemetry.py"),
    MetricSpec("pst_tenant_device_seconds", COUNTER, "obs/engine_telemetry.py"),
    MetricSpec("pst_engine_device_busy_seconds", COUNTER, "obs/engine_telemetry.py"),
    # --- resilience/metrics.py: breakers, deadlines, hedges, resume -----
    MetricSpec("pst_resilience_breaker_state", GAUGE, "resilience/metrics.py"),
    MetricSpec("pst_resilience_breaker_transitions_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_retries_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_failovers_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_upstream_failures_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_admitted_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_sheds_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_queue_depth", GAUGE, "resilience/metrics.py"),
    MetricSpec("pst_resilience_client_disconnects_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_resilience_draining_engines", GAUGE, "resilience/metrics.py"),
    MetricSpec("pst_resilience_warming_engines", GAUGE, "resilience/metrics.py"),
    MetricSpec("pst_deadline_budget_ms", HISTOGRAM, "resilience/metrics.py"),
    MetricSpec("pst_deadline_sheds_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_hedge_fired_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_hedge_won_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_hedge_cancelled_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_hedge_suppressed_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_stream_resume_attempts_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_stream_resume_success_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_stream_resume_failures_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_stream_truncated_total", COUNTER, "resilience/metrics.py"),
    # Multi-tenant QoS (docs/multi-tenancy.md): per-tenant admission,
    # queue depth and usage metering.
    MetricSpec("pst_tenant_admitted_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_tenant_sheds_total", COUNTER, "resilience/metrics.py"),
    MetricSpec("pst_tenant_queue_depth", GAUGE, "resilience/metrics.py"),
    MetricSpec("pst_tenant_usage_tokens_total", COUNTER, "resilience/metrics.py"),
    # --- router/routing/metrics.py: fleet routing ------------------------
    # --- router/services/disagg.py: disaggregated P/D pools -------------
    MetricSpec("pst_disagg_transfer_seconds", HISTOGRAM, "router/services/disagg.py"),
    MetricSpec("pst_disagg_overlap_seconds", HISTOGRAM, "router/services/disagg.py"),
    MetricSpec("pst_disagg_fallback", COUNTER, "router/services/disagg.py"),
    MetricSpec("pst_route_score", HISTOGRAM, "router/routing/metrics.py"),
    MetricSpec("pst_route_spill", COUNTER, "router/routing/metrics.py"),
    MetricSpec("pst_route_session_remap", COUNTER, "router/routing/metrics.py"),
    MetricSpec("pst_route_lookup_skipped", COUNTER, "router/routing/metrics.py"),
    # --- router/state/metrics.py: router HA / replication ----------------
    MetricSpec("pst_router_replica_peers", GAUGE, "router/state/metrics.py"),
    MetricSpec("pst_router_replica_sync", COUNTER, "router/state/metrics.py"),
    MetricSpec("pst_router_replica_sync_seconds", HISTOGRAM, "router/state/metrics.py"),
    MetricSpec("pst_router_replica_admission_share", GAUGE, "router/state/metrics.py"),
    MetricSpec("pst_router_replica_journals", GAUGE, "router/state/metrics.py"),
    MetricSpec("pst_router_replica_takeovers", COUNTER, "router/state/metrics.py"),
    # --- router/services/metrics_service.py: router process + SLO -------
    MetricSpec("pst_router:cpu_percent", GAUGE, "router/services/metrics_service.py"),
    MetricSpec("pst_router:memory_mb", GAUGE, "router/services/metrics_service.py"),
    MetricSpec("pst_router:disk_percent", GAUGE, "router/services/metrics_service.py"),
    MetricSpec("pst_slo_requests", COUNTER, "router/services/metrics_service.py"),
    MetricSpec("pst_slo_ttft_within_target", COUNTER, "router/services/metrics_service.py"),
    MetricSpec("pst_tenant_slo_requests", COUNTER, "router/services/metrics_service.py"),
    MetricSpec("pst_tenant_slo_ttft_within_target", COUNTER, "router/services/metrics_service.py"),
    MetricSpec("pst_canary_ttft_seconds", GAUGE, "router/services/metrics_service.py"),
    MetricSpec("pst_canary_failures", COUNTER, "router/services/metrics_service.py"),
    # --- router/services/fleet.py: fleet introspection plane ------------
    MetricSpec("pst_fleet_engines", GAUGE, "router/services/fleet.py"),
    # --- router/services/capacity.py: autoscaler capacity signals -------
    MetricSpec("pst_capacity_saturation", GAUGE, "router/services/capacity.py"),
    MetricSpec("pst_capacity_burn_rate", GAUGE, "router/services/capacity.py"),
    MetricSpec("pst_capacity_replica_hint", GAUGE, "router/services/capacity.py"),
    MetricSpec("pst_capacity_queue_depth_slope", GAUGE, "router/services/capacity.py"),
    MetricSpec("pst_capacity_kv_headroom", GAUGE, "router/services/capacity.py"),
)

BY_NAME: Dict[str, MetricSpec] = {s.name: s for s in REGISTRY}


def declared_names() -> Tuple[str, ...]:
    return tuple(s.name for s in REGISTRY)


def exposition_names() -> Tuple[str, ...]:
    return tuple(s.exposition_name for s in REGISTRY)
