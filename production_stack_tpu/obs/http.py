"""Shared HTTP surface for the trace recorder.

Router and engine both serve ``GET /debug/requests``; one implementation
keeps the contract (404 semantics, ``limit``/``request_id`` params,
response shape) from drifting between components.
"""

from __future__ import annotations

from aiohttp import web

from .tracing import SpanRecorder, error_headers


def debug_requests_response(
    recorder: SpanRecorder, request: web.Request
) -> web.Response:
    """The ring buffer of completed request timelines, most recent first.

    404s when tracing is off (``--no-tracing``) or the ring is sized 0
    (``--debug-requests-buffer 0``) — tracing itself (histograms, header
    propagation) still runs in the latter case.
    """
    if not recorder.debug_endpoint_enabled:
        return web.json_response(
            {"error": {"message": "request tracing timelines are disabled "
                                  "(--no-tracing or --debug-requests-buffer 0)",
                       "type": "not_found_error", "code": 404}},
            status=404,
            headers=error_headers(request),
        )
    try:
        limit = int(request.query.get("limit", "50"))
    except ValueError:
        limit = 50
    return web.json_response({
        "component": recorder.component,
        "buffer_size": recorder.buffer_size,
        "requests": recorder.timelines(
            limit=limit, request_id=request.query.get("request_id")
        ),
    })
