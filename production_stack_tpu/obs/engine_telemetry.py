"""TPU-engine telemetry: compiles, step durations, MFU, KV pressure.

The metrics PR 3 could not give the engine: everything here is fed from
the *device-dispatch* layer (``engine/runner.py``) and the scheduler, so a
mid-run XLA recompile, a padding-wasteful batch, or a slow startup phase
becomes a Prometheus series instead of a mystery p99 outlier (BENCH_r05's
120 s TTFT was exactly such a recompile, invisible to every existing
metric).

Compile detection is the first-call-per-bucket heuristic the static-shape
design makes sound: the runner pads every step into a small set of bucket
shapes and ``jax.jit`` caches one executable per bucket, so the FIRST
dispatch of a (kind, bucket, static-flags) signature is the one that pays
tracing + XLA compilation — its wall time is recorded as the compile cost
and the event is queued so the engine can attach it to the victim
request's trace (a recompile shows up *inside* the request timeline that
absorbed it).

Like :data:`..obs.metrics.OBS_REGISTRY`, everything lives in a dedicated
registry appended to the engine's ``/metrics`` — the router never double
registers it, and the fake engine can serve the same names as plain text.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

ENGINE_TELEMETRY_REGISTRY = CollectorRegistry()

# Compile times span "re-trace only" (~100 ms) to multi-minute 8B builds.
_COMPILE_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                    120.0, 300.0)
# Step times span sub-ms CPU toys to 100 s cold 20k prefills.
_STEP_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0, 120.0)
_FILL_BUCKETS = (0.1, 0.25, 0.5, 0.625, 0.75, 0.875, 1.0)

compile_total = Counter(
    "pst_engine_compile",
    "XLA compilations observed at jitted dispatch (first call per shape "
    "bucket), by step kind and padded shape bucket",
    ["kind", "shape_bucket"],
    registry=ENGINE_TELEMETRY_REGISTRY,
)
compile_seconds = Histogram(
    "pst_engine_compile_seconds",
    "Wall time of compile-bearing dispatches (trace + XLA build + first "
    "execution), by step kind",
    ["kind"],
    registry=ENGINE_TELEMETRY_REGISTRY,
    buckets=_COMPILE_BUCKETS,
)
step_duration = Histogram(
    "pst_engine_step_duration_seconds",
    "Device step wall time (dispatch to fetch), by step kind and padded "
    "batch bucket; compile-bearing first calls excluded",
    ["kind", "batch_bucket"],
    registry=ENGINE_TELEMETRY_REGISTRY,
    buckets=_STEP_BUCKETS,
)
# Host gaps span "pipelined, zero by construction" to ~100 ms of serial
# bookkeeping between bursts on a busy host.
_HOST_GAP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25)
host_gap_seconds = Histogram(
    "pst_engine_host_gap_seconds",
    "Serial host wall between a decode step's device completion and the "
    "next decode dispatch (batch build, detokenization, stop scans, "
    "scheduler accounting on the critical path), by padded batch bucket; "
    "pipelined continuations record 0 — the device never idled",
    ["batch_bucket"],
    registry=ENGINE_TELEMETRY_REGISTRY,
    buckets=_HOST_GAP_BUCKETS,
)
batch_fill_ratio = Histogram(
    "pst_engine_batch_fill_ratio",
    "Useful fraction of each padded device step (real rows*tokens over "
    "padded rows*tokens) — 1.0 means zero padding waste",
    ["kind"],
    registry=ENGINE_TELEMETRY_REGISTRY,
    buckets=_FILL_BUCKETS,
)
tokens_per_second = Gauge(
    "pst_engine_tokens_per_second",
    "Engine token throughput over a short sliding window, by step kind",
    ["kind"],
    registry=ENGINE_TELEMETRY_REGISTRY,
)
mfu_gauge = Gauge(
    "pst_engine_mfu",
    "Model-FLOPs utilization estimate: 2 * params * tokens/s over the "
    "accelerator's peak FLOPs",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
kv_page_occupancy = Gauge(
    "pst_engine_kv_page_occupancy",
    "Fraction of HBM KV pages in use",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
kv_page_high_watermark = Gauge(
    "pst_engine_kv_page_high_watermark",
    "Highest KV page occupancy fraction observed since engine start",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
preemptions_total = Counter(
    "pst_engine_preemptions",
    "Scheduler recompute preemptions (out of KV pages)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
swap_out_total = Counter(
    "pst_engine_swap_out",
    "Sequences swapped out by the scheduler (KV parked host-side)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
swap_in_total = Counter(
    "pst_engine_swap_in",
    "Sequences swapped back in by the scheduler (KV resumed)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
start_time_seconds = Gauge(
    "pst_engine_start_time_seconds",
    "Wall-clock time the engine's runner initialized (the alert rules "
    "gate recompile alerts on uptime so cold-start compiles never page)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
startup_seconds = Gauge(
    "pst_engine_startup_seconds",
    "Engine startup decomposition: load (param materialization), shard "
    "(device placement + KV alloc + jit wiring), warmup (tokenizer, "
    "allocator, scheduler), precompile (ahead-of-time shape-bucket "
    "lattice compilation)",
    ["phase"],
    registry=ENGINE_TELEMETRY_REGISTRY,
)
warmup_coverage = Gauge(
    "pst_engine_warmup_coverage",
    "Warmup precompile coverage: shape buckets compiled over buckets in "
    "the enumerated lattice (1.0 = every padded shape live traffic can "
    "produce is already compiled)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
warmup_buckets = Gauge(
    "pst_engine_warmup_buckets",
    "Warmup lattice size, by state: total (enumerated) vs compiled "
    "(dispatched at warmup)",
    ["state"],
    registry=ENGINE_TELEMETRY_REGISTRY,
)
compile_cache_hits = Counter(
    "pst_engine_compile_cache_hits",
    "Persistent JAX compilation-cache hits (executable deserialized "
    "instead of rebuilt by XLA)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
compile_cache_misses = Counter(
    "pst_engine_compile_cache_misses",
    "Persistent JAX compilation-cache misses (fresh XLA build, entry "
    "written for the next restart)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)
# Per-request cost attribution (docs/observability.md "Cost attribution"):
# each finished request's accumulated device-seconds, split by phase, and
# the per-tenant chip-time meter that extends PR 12's token metering into
# billing-grade chip-seconds.
_REQUEST_DEVICE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                           0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0)
request_device_seconds = Histogram(
    "pst_request_device_seconds",
    "Device-seconds attributed to one finished request, by phase: prefill "
    "(token-weighted share of its prefill steps) or decode (active-row "
    "share of its decode bursts/spec verifies)",
    ["phase"],
    registry=ENGINE_TELEMETRY_REGISTRY,
    buckets=_REQUEST_DEVICE_BUCKETS,
)
tenant_device_seconds = Counter(
    "pst_tenant_device_seconds",
    "Device-seconds attributed to finished requests, per tenant — the "
    "chip-time billing meter beside pst_tenant_usage_tokens",
    ["tenant"],
    registry=ENGINE_TELEMETRY_REGISTRY,
)
device_busy_seconds = Counter(
    "pst_engine_device_busy_seconds",
    "Cumulative wall the device spent executing live-traffic dispatches "
    "(warmup precompilation excluded) — the denominator per-request cost "
    "attribution is audited against (sum of request device-seconds must "
    "cover >= 90% of this)",
    registry=ENGINE_TELEMETRY_REGISTRY,
)

# Peak FLOPs per chip for the MFU denominator (public specs, bf16 MXU).
_PEAK_FLOPS_BY_DEVICE_KIND = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}
_DEFAULT_PEAK_FLOPS = 197e12

# Fresh runners must re-count compiles even when an earlier runner in the
# same process already compiled identical bucket shapes (jit caches are
# per-runner): each ModelRunner takes a distinct scope id into its keys.
_runner_scope = itertools.count()


def next_runner_scope() -> int:
    return next(_runner_scope)


class EngineTelemetry:
    """Process-wide sink the runner/scheduler/server feed.

    Thread-safe: dispatches run on the engine step thread and executor
    threads while ``/metrics`` refreshes from the asyncio loop.
    """

    _TOKEN_WINDOW_S = 10.0

    def __init__(self):
        self._lock = threading.Lock()
        self._seen_shapes: set = set()
        self._pending_compile_events: List[dict] = []
        self._compiles = 0
        # (monotonic, kind, tokens) samples for the throughput window.
        self._tok_samples: "deque[Tuple[float, str, int]]" = deque()
        # Kinds that ever reported tokens: their gauges must drop to 0
        # when the window empties instead of freezing at the last burst.
        self._tok_kinds: set = set()
        self._counter_last: Dict[str, float] = {}
        self._kv_hwm = 0.0
        # Persistent compilation-cache accounting (fed by the jax
        # monitoring listener precompile.configure_compile_cache installs).
        self._cache_hits = 0
        self._cache_misses = 0
        # Bounded raw host-gap samples per batch bucket: Prometheus
        # histograms cannot answer "p50 at batch 8" locally, but the bench
        # and scripts/tpu_decode_profile.py --host-gap must.
        self._host_gap: Dict[str, "deque[float]"] = {}
        # Flight-recorder sink (obs/flight.py): every live dispatch
        # forwards one ring record; the null recorder makes this free.
        from .flight import NULL_FLIGHT_RECORDER

        self._flight = NULL_FLIGHT_RECORDER
        # Live-traffic device-busy accumulator — the denominator the cost
        # attribution audit (bench `cost` phase) sums request costs against.
        self._device_busy_s = 0.0
        self.param_count = 0
        self.peak_flops = _DEFAULT_PEAK_FLOPS
        # --no-startup-phases: the gauges stay at 0 (helm
        # servingEngineSpec.observability.startupPhases).
        self.startup_enabled = True

    # -- model / startup ------------------------------------------------

    def set_model_info(
        self, param_count: int, device_kind: Optional[str] = None,
        peak_flops: Optional[float] = None,
    ) -> None:
        self.param_count = int(param_count)
        self.peak_flops = peak_flops or _PEAK_FLOPS_BY_DEVICE_KIND.get(
            device_kind or "", _DEFAULT_PEAK_FLOPS
        )
        start_time_seconds.set(time.time())

    def record_startup_phase(self, phase: str, seconds: float) -> None:
        if not self.startup_enabled:
            return
        startup_seconds.labels(phase=phase).set(max(seconds, 0.0))

    # -- warmup / persistent compile cache -------------------------------

    def set_warmup_coverage(self, compiled: int, total: int) -> None:
        """Buckets-compiled over buckets-in-lattice (the /ready story in
        one gauge; updated as the precompiler walks the lattice)."""
        warmup_buckets.labels(state="total").set(max(total, 0))
        warmup_buckets.labels(state="compiled").set(max(compiled, 0))
        warmup_coverage.set(compiled / total if total > 0 else 0.0)

    def record_cache_event(self, hit: bool) -> None:
        """One persistent-compilation-cache lookup outcome (from the jax
        monitoring listener)."""
        with self._lock:
            if hit:
                self._cache_hits += 1
            else:
                self._cache_misses += 1
        (compile_cache_hits if hit else compile_cache_misses).inc()

    def cache_stats(self) -> "Tuple[int, int]":
        """(hits, misses) observed since process start — bench and the
        warm-restart e2e assert zero fresh misses on a warm restart."""
        with self._lock:
            return self._cache_hits, self._cache_misses

    # -- flight recorder / cost attribution ------------------------------

    def attach_flight(self, recorder) -> None:
        """Install the engine's flight recorder as the dispatch sink
        (obs/flight.py). One recorder per engine; re-attachment replaces
        (fresh engines in one process must not write a dead ring)."""
        from .flight import NULL_FLIGHT_RECORDER

        self._flight = recorder if recorder is not None else NULL_FLIGHT_RECORDER

    @property
    def flight(self):
        return self._flight

    def device_busy_seconds(self) -> float:
        """Cumulative live-traffic dispatch wall since process start (or
        the last reset) — warmup precompilation excluded."""
        with self._lock:
            return self._device_busy_s

    def record_request_cost(
        self, tenant: str, prefill_s: float, decode_s: float
    ) -> None:
        """One finished request's attributed device time → the per-phase
        histograms and the per-tenant chip-time meter."""
        prefill_s = max(prefill_s, 0.0)
        decode_s = max(decode_s, 0.0)
        if prefill_s > 0:
            request_device_seconds.labels(phase="prefill").observe(prefill_s)
        if decode_s > 0:
            request_device_seconds.labels(phase="decode").observe(decode_s)
        total = prefill_s + decode_s
        if total > 0:
            tenant_device_seconds.labels(
                tenant=str(tenant or "default")[:64]
            ).inc(total)

    # -- dispatch-level telemetry ---------------------------------------

    def record_dispatch(
        self,
        kind: str,
        shape_key: tuple,
        seconds: float,
        *,
        batch_bucket: str,
        tokens: int = 0,
        fill_ratio: Optional[float] = None,
        count_busy: bool = True,
    ) -> bool:
        """Record one device dispatch; returns True when this was the
        first call for its shape bucket (i.e. it paid a compile).

        ``count_busy=False`` marks warmup-precompile dispatches: they
        compile real executables but serve no request, so they stay out
        of the device-busy denominator and the flight ring."""
        seconds = max(seconds, 0.0)
        with self._lock:
            compiled = shape_key not in self._seen_shapes
            if compiled:
                self._seen_shapes.add(shape_key)
                self._compiles += 1
                self._pending_compile_events.append({
                    "kind": kind,
                    "shape_bucket": batch_bucket,
                    "seconds": round(seconds, 3),
                })
            if tokens > 0:
                now = time.monotonic()
                self._tok_samples.append((now, kind, tokens))
                self._refresh_throughput_locked(now)
            if count_busy:
                self._device_busy_s += seconds
        if count_busy:
            device_busy_seconds.inc(seconds)
            # Flight ring (obs/flight.py): one bounded record per live
            # dispatch, with the scheduler/KV state the engine's probe
            # supplies — the post-mortem trail for any step that stalls.
            self._flight.record_step(
                kind, batch_bucket, seconds, compiled=compiled, tokens=tokens
            )
        if compiled:
            compile_total.labels(kind=kind, shape_bucket=batch_bucket).inc()
            compile_seconds.labels(kind=kind).observe(seconds)
        else:
            # Compile-bearing calls are excluded from the step histogram so
            # its percentiles describe steady-state steps, not XLA builds.
            step_duration.labels(
                kind=kind, batch_bucket=batch_bucket
            ).observe(seconds)
        if fill_ratio is not None:
            batch_fill_ratio.labels(kind=kind).observe(
                min(max(fill_ratio, 0.0), 1.0)
            )
        return compiled

    _HOST_GAP_SAMPLE_CAP = 1024  # per bucket; enough for a stable p50

    def record_host_gap(
        self, batch_bucket: str, seconds: float,
        request_id: "Optional[str]" = None,
    ) -> None:
        """One decode-loop host gap (engine/runner.py host-gap accounting):
        the serial host wall between a decode step's completion and the
        next decode dispatch. Pipelined continuations record 0.0 — the
        continuation was dispatched before the previous burst's tokens
        were read, so the device ran the two back-to-back.

        ``request_id`` (one sequence of the gap-closing burst) attaches
        as an OpenMetrics exemplar: a slow host-gap bucket links to the
        ``/debug/requests?request_id=`` timeline that absorbed it."""
        seconds = max(seconds, 0.0)
        with self._lock:
            dq = self._host_gap.get(batch_bucket)
            if dq is None:
                dq = self._host_gap[batch_bucket] = deque(
                    maxlen=self._HOST_GAP_SAMPLE_CAP
                )
            dq.append(seconds)
        # The gap closes AT the next decode dispatch: hand it to the
        # flight ring so that dispatch's record carries it.
        self._flight.note_host_gap(seconds)
        child = host_gap_seconds.labels(batch_bucket=batch_bucket)
        if request_id:
            child.observe(seconds, exemplar={"request_id": str(request_id)[:48]})
        else:
            child.observe(seconds)

    def reset_host_gap(self) -> None:
        """Drop retained host-gap samples (NOT the Prometheus histogram —
        that stays cumulative). The bench calls this per phase so one
        model's summary never mixes a previous engine's samples that
        landed in the same batch bucket."""
        with self._lock:
            self._host_gap.clear()

    def host_gap_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-bucket {count, p50, mean} over the retained sample window —
        what the bench's roofline block and the --host-gap profiling mode
        report (the acceptance bar: p50 < 10% of the decode-step p50)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            buckets = {k: list(v) for k, v in self._host_gap.items()}
        for bucket, samples in sorted(buckets.items()):
            if not samples:
                continue
            ordered = sorted(samples)
            out[bucket] = {
                "count": float(len(ordered)),
                "p50": float(ordered[len(ordered) // 2]),
                "mean": float(sum(ordered) / len(ordered)),
            }
        return out

    def _refresh_throughput_locked(self, now: float) -> None:
        cutoff = now - self._TOKEN_WINDOW_S
        while self._tok_samples and self._tok_samples[0][0] < cutoff:
            self._tok_samples.popleft()
        per_kind: Dict[str, int] = {}
        total = 0
        for _, kind, toks in self._tok_samples:
            self._tok_kinds.add(kind)
            per_kind[kind] = per_kind.get(kind, 0) + toks
            total += toks
        span = (
            max(now - self._tok_samples[0][0], 0.5)
            if self._tok_samples else 1.0
        )
        # Kinds with no samples left in the window read 0, not their last
        # burst's value — an idle engine must look idle.
        for kind in self._tok_kinds:
            tokens_per_second.labels(kind=kind).set(
                per_kind.get(kind, 0) / span
            )
        if self.param_count and self.peak_flops:
            mfu_gauge.set(
                2.0 * self.param_count * (total / span) / self.peak_flops
            )

    # -- compile events → request traces --------------------------------

    def drain_compile_events(self) -> List[dict]:
        """Compile events recorded since the last drain (the engine
        attaches them to the step's in-flight request traces)."""
        with self._lock:
            events, self._pending_compile_events = (
                self._pending_compile_events, []
            )
        return events

    def compile_count(self) -> int:
        """Total compiles observed since process start (bench.py snapshots
        this around each qps point to flag recompile-polluted sweeps)."""
        with self._lock:
            return self._compiles

    # -- scheduler / KV refresh (from LLMEngine.stats()) ----------------

    def _counter_to(self, counter, key: str, total: float) -> None:
        last = self._counter_last.get(key, 0.0)
        if total > last:
            counter.inc(total - last)
            self._counter_last[key] = total
        elif total < last:  # in-process reset: re-baseline
            if total > 0:
                counter.inc(total)
            self._counter_last[key] = total

    def refresh_from_stats(self, stats: dict) -> None:
        occ = float(stats.get("kv_cache_usage_perc", 0.0))
        kv_page_occupancy.set(occ)
        with self._lock:
            # /metrics scrapes keep the throughput window honest even when
            # no dispatch has run since the last burst.
            self._refresh_throughput_locked(time.monotonic())
            self._kv_hwm = max(self._kv_hwm, occ)
            hwm = self._kv_hwm
        kv_page_high_watermark.set(hwm)
        self._counter_to(
            preemptions_total, "preempt",
            float(stats.get("num_preemptions_total", 0.0)),
        )
        self._counter_to(
            swap_out_total, "swap_out",
            float(stats.get("kv_swap_out_total", 0.0)),
        )
        self._counter_to(
            swap_in_total, "swap_in",
            float(stats.get("kv_swap_in_total", 0.0)),
        )

    # -- tests ----------------------------------------------------------

    def reset_for_tests(self) -> None:
        with self._lock:
            self._seen_shapes.clear()
            self._pending_compile_events.clear()
            self._compiles = 0
            self._tok_samples.clear()
            self._tok_kinds.clear()
            self._counter_last.clear()
            self._kv_hwm = 0.0
            self._cache_hits = 0
            self._cache_misses = 0
            self._host_gap.clear()
            self._device_busy_s = 0.0
            self.startup_enabled = True
        from .flight import NULL_FLIGHT_RECORDER

        self._flight = NULL_FLIGHT_RECORDER


ENGINE_TELEMETRY = EngineTelemetry()


def render_engine_telemetry() -> bytes:
    """Prometheus exposition of the engine telemetry registry — appended
    to the engine's ``/metrics`` next to ``render_obs_metrics()``."""
    return generate_latest(ENGINE_TELEMETRY_REGISTRY)
