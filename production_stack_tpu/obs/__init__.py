"""Observability layer: end-to-end request tracing + latency decomposition.

- :mod:`tracing` — in-process span recorder (always available, no SDK
  required), W3C ``traceparent`` propagation helpers, bounded ring buffer
  of completed request timelines (``GET /debug/requests``), optional
  mirroring into the real OpenTelemetry SDK.
- :mod:`metrics` — the ``pst_stage_duration_seconds{component,stage}``
  histogram every completed span feeds.

The router holds one process-wide recorder (initialize/get/teardown like
the other router singletons); each engine server owns its own recorder
(created in ``create_engine_app``).
"""

from __future__ import annotations

from typing import Optional

from .engine_telemetry import (
    ENGINE_TELEMETRY,
    ENGINE_TELEMETRY_REGISTRY,
    EngineTelemetry,
    next_runner_scope,
    render_engine_telemetry,
)
from .http import debug_requests_response
from .logging import (
    bind_log_context,
    configure_logging,
    current_log_context,
    set_log_identity,
    unbind_log_context,
    update_log_context,
)
from .metrics import (
    OBS_REGISTRY,
    OPENMETRICS_CONTENT_TYPE,
    observe_stage,
    render_obs_metrics,
    render_registries,
    wants_openmetrics,
)
from .tracing import (
    NOOP_SPAN,
    NOOP_TRACE,
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    RequestTrace,
    Span,
    SpanRecorder,
    error_headers,
    format_traceparent,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)

_router_recorder: Optional[SpanRecorder] = None


def initialize_request_tracing(
    enabled: bool = True, buffer: int = 256
) -> SpanRecorder:
    """Create the router's process-wide span recorder."""
    global _router_recorder
    _router_recorder = SpanRecorder("router", buffer=buffer, enabled=enabled)
    return _router_recorder


def get_request_tracer() -> Optional[SpanRecorder]:
    return _router_recorder


def teardown_request_tracing() -> None:
    global _router_recorder
    _router_recorder = None


__all__ = [
    "ENGINE_TELEMETRY",
    "ENGINE_TELEMETRY_REGISTRY",
    "EngineTelemetry",
    "NOOP_SPAN",
    "NOOP_TRACE",
    "OBS_REGISTRY",
    "OPENMETRICS_CONTENT_TYPE",
    "REQUEST_ID_HEADER",
    "TRACEPARENT_HEADER",
    "RequestTrace",
    "Span",
    "SpanRecorder",
    "bind_log_context",
    "configure_logging",
    "current_log_context",
    "debug_requests_response",
    "error_headers",
    "format_traceparent",
    "get_request_tracer",
    "initialize_request_tracing",
    "new_span_id",
    "new_trace_id",
    "next_runner_scope",
    "observe_stage",
    "parse_traceparent",
    "render_engine_telemetry",
    "render_obs_metrics",
    "render_registries",
    "set_log_identity",
    "teardown_request_tracing",
    "unbind_log_context",
    "update_log_context",
    "wants_openmetrics",
]
