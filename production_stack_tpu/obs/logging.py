"""Structured correlated logging (docs/observability.md "Structured logging").

One JSON-lines formatter, installed at app/engine bootstrap
(``--log-format json``), that enriches EVERY stdlib ``logging`` record the
~50 ``init_logger`` modules emit — zero call-site churn — with the
request identity the tracing layer already carries:

- ``trace_id`` / ``request_id`` from the per-request log context the
  tracing middlewares bind (router and engine), so one grep joins a
  router log line, an engine log line, a ``pst_stage_duration_seconds``
  exemplar, and the ``/debug/requests`` timeline on the same id;
- ``tenant`` from the admission middleware (docs/multi-tenancy.md);
- ``component`` plus ``replica_id`` (router) / ``engine_id`` (engine)
  from the process identity set once at bootstrap.

Field contract (stable — dashboards and log pipelines key on it):
``ts`` (epoch seconds), ``level``, ``logger``, ``msg``, ``component``,
``replica_id`` | ``engine_id``, and — when a request context is bound —
``trace_id``, ``request_id``, ``tenant``. ``exc`` carries a formatted
traceback when the record has one. Unknown context fields pass through
verbatim, so callers may bind extra correlation keys.

Hot-path protection: INFO-and-below records are sampled through a
per-logger token bucket (``pst_log_dropped_total`` counts the drops, in
the shared observability registry so both components export it).
WARNING and above are never dropped — errors must always be joinable.

The text format stays byte-identical to the historical colored output;
this module only takes over when ``configure_logging("json", ...)`` runs.
"""

from __future__ import annotations

import contextvars
import json
import logging
import threading
import time
from typing import Dict, Optional

from prometheus_client import Counter

from .. import logging_utils
from .metrics import OBS_REGISTRY

JSON = "json"
TEXT = "text"
LOG_FORMATS = (JSON, TEXT)

# Default hot-path sampling: generous enough that steady-state serving
# never drops a line, tight enough that a per-request DEBUG/INFO storm
# (one line per token, say) cannot melt stdout. WARNING+ is exempt.
DEFAULT_SAMPLE_RATE = 200.0   # records/sec per logger
DEFAULT_SAMPLE_BURST = 400

log_dropped_total = Counter(
    "pst_log_dropped",
    "Log records dropped by the structured-logging hot-path sampler "
    "(INFO and below only; WARNING+ is never sampled)",
    ["component", "logger"],
    registry=OBS_REGISTRY,
)

# Per-request correlation fields (trace_id, request_id, tenant, ...),
# bound by the tracing/admission middlewares and inherited by every task
# the request handler spawns (contextvars propagate through create_task).
_LOG_CONTEXT: "contextvars.ContextVar[Optional[Dict[str, str]]]" = (
    contextvars.ContextVar("pst_log_context", default=None)
)

# Process identity (component, replica_id / engine_id): set once at
# bootstrap, merged into every JSON record.
_IDENTITY: Dict[str, str] = {}


def bind_log_context(**fields) -> contextvars.Token:
    """Bind per-request correlation fields for the current context; the
    returned token restores the previous binding (``finally`` in the
    middleware). Falsy values are skipped so callers can pass optionals."""
    merged = dict(_LOG_CONTEXT.get() or {})
    merged.update({k: str(v) for k, v in fields.items() if v})
    return _LOG_CONTEXT.set(merged)


def update_log_context(**fields) -> None:
    """Merge more fields into the current binding (the admission
    middleware learns the tenant AFTER the tracing middleware bound the
    trace) without a token to manage — the context dies with the request
    context either way."""
    merged = dict(_LOG_CONTEXT.get() or {})
    merged.update({k: str(v) for k, v in fields.items() if v})
    _LOG_CONTEXT.set(merged)


def unbind_log_context(token: contextvars.Token) -> None:
    _LOG_CONTEXT.reset(token)


def current_log_context() -> Dict[str, str]:
    return dict(_LOG_CONTEXT.get() or {})


def structured_logging_active() -> bool:
    """Whether the JSON profile (with its hot-path sampler) is installed.
    Call sites that want a per-request correlation line gate its level on
    this: INFO when the sampler bounds the volume, DEBUG otherwise — a
    text-mode deployment must not grow an unbounded access log."""
    return logging_utils._FORMATTER_FACTORY is not None


def set_log_identity(**fields) -> None:
    """Set (or extend) the process identity merged into every record:
    ``component="router"``, ``replica_id=...`` / ``engine_id=...``.
    Call again as identity becomes known (the router learns its replica
    id when the state backend constructs)."""
    _IDENTITY.update({k: str(v) for k, v in fields.items() if v})


class JsonLineFormatter(logging.Formatter):
    """One JSON object per line; stable field contract (module docstring)."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, object] = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        out.update(_IDENTITY)
        ctx = _LOG_CONTEXT.get()
        if ctx:
            out.update(ctx)
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class _SamplingFilter(logging.Filter):
    """Per-logger token bucket over INFO-and-below records.

    WARNING+ always passes: correlation exists so failures can be
    joined, and a sampler that could eat an error would defeat that.
    Drops are counted (never silent) in ``pst_log_dropped_total``.
    """

    def __init__(self, rate: float, burst: int) -> None:
        super().__init__()
        self.rate = max(float(rate), 0.001)
        self.burst = max(int(burst), 1)
        self._lock = threading.Lock()
        # logger name -> (tokens, last_refill_monotonic)
        self._buckets: Dict[str, list] = {}

    def filter(self, record: logging.LogRecord) -> bool:
        if record.levelno >= logging.WARNING:
            return True
        now = time.monotonic()
        with self._lock:
            b = self._buckets.get(record.name)
            if b is None:
                b = self._buckets[record.name] = [float(self.burst), now]
            tokens, last = b
            tokens = min(tokens + (now - last) * self.rate, float(self.burst))
            if tokens >= 1.0:
                b[0], b[1] = tokens - 1.0, now
                return True
            b[0], b[1] = tokens, now
        log_dropped_total.labels(
            component=_IDENTITY.get("component", "unknown"),
            logger=record.name,
        ).inc()
        return False


def configure_logging(
    fmt: str = TEXT,
    component: Optional[str] = None,
    sample_rate: float = DEFAULT_SAMPLE_RATE,
    sample_burst: int = DEFAULT_SAMPLE_BURST,
    **identity,
) -> None:
    """Install the structured-logging profile process-wide.

    ``fmt="json"`` swaps every ``init_logger`` handler (existing and
    future) to :class:`JsonLineFormatter` and arms the hot-path sampler;
    ``fmt="text"`` restores the colored text profile (and disarms the
    sampler). ``component`` + ``identity`` kwargs become the static
    fields on every record (``replica_id=...``, ``engine_id=...``).
    """
    if fmt not in LOG_FORMATS:
        raise ValueError(f"unknown log format {fmt!r} (expected json|text)")
    if component:
        set_log_identity(component=component)
    set_log_identity(**identity)
    if fmt == JSON:
        logging_utils.apply_log_profile(
            formatter_factory=lambda stream: JsonLineFormatter(),
            record_filter=_SamplingFilter(sample_rate, sample_burst),
        )
    else:
        logging_utils.apply_log_profile()
