"""Engine flight recorder: an always-on ring of per-device-step records.

The *write side* of deep performance introspection (docs/observability.md
"Flight recorder"). PR 3's ``/debug/requests`` answers "what happened to
THIS request"; the flight recorder answers the question the BENCH_r05
120 s tail left open — "what exactly was the engine doing when that p99
outlier happened?" Every jitted dispatch appends one fixed-size record:
step kind, padded batch bucket, device step wall, the host gap that
preceded it, queue depths, KV occupancy, preemption count, tenant tier
mix, and whether the dispatch absorbed an XLA compile.

Design constraints, in order:

- **Always on.** The ring is a preallocated list of ``capacity`` slots
  written round-robin under a tiny lock — no allocation grows with
  uptime, and the per-step cost is one tuple build + one list store, so
  the PR 8 host-gap and roofline numbers are unaffected (asserted by
  the bench acceptance bar).
- **Post-mortem by construction.** Whenever a step exceeds the
  ``tail_outlier`` bar (the PR 8 flag: worse than ``outlier_factor`` ×
  the rolling per-bucket median), the recorder snapshots the ring — so
  any p99>3×p50 event leaves a trace naming the stalled step's bucket
  and queue state even if nobody was scraping. SIGTERM/fatal paths
  snapshot too (``engine/server.py`` and ``engine/async_engine.py``).
- **Feed-forward, not call-site churn.** :class:`EngineTelemetry`
  already sees every dispatch (PR 5); the recorder registers as its
  flight sink and the engine supplies a state probe closure
  (scheduler depths + KV occupancy) — no new calls ride the hot loop.

Served by ``GET /debug/flight`` (last-N or time-window) on the engine.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

# Record tuple layout (kept positional — a dict per step would allocate
# a hash table on the hot path; rows render to dicts only at read time).
_F_WALL = 0        # time.time() stamp (for ?window_s= and human output)
_F_KIND = 1        # prefill | decode | spec_verify | encode
_F_BUCKET = 2      # padded batch bucket label (b8xn4, b1xt512, ...)
_F_DEVICE_S = 3    # device step wall (dispatch -> fetch)
_F_HOST_GAP_S = 4  # serial host wall that preceded this dispatch
_F_COMPILED = 5    # this dispatch absorbed an XLA compile
_F_WAITING = 6     # scheduler waiting depth at dispatch
_F_RUNNING = 7     # scheduler running depth at dispatch
_F_SWAPPED = 8     # sequences parked host-side
_F_KV_OCC = 9      # KV page occupancy fraction
_F_PREEMPT = 10    # cumulative preemptions
_F_BATCH_ROWS = 11 # batch-tier rows in the running set (tier mix)
_F_TOKENS = 12     # real tokens the step moved

_FIELDS = (
    "ts", "kind", "bucket", "device_s", "host_gap_s", "compiled",
    "waiting", "running", "swapped", "kv_occupancy", "preemptions",
    "batch_tier_rows", "tokens",
)


def _row_dict(row: tuple) -> dict:
    return dict(zip(_FIELDS, row))


def load_snapshot_dir(path: str, limit: Optional[int] = None) -> List[dict]:
    """Read persisted snapshots back from a ``--flight-snapshot-dir``,
    oldest first. Filenames encode a monotone (time_ns, seq) pair so a
    lexical sort is chronological. Unparseable files are skipped — a
    snapshot half-written at SIGKILL must not poison the post-mortem.

    Shared by the recorder's restart load-back and the forensics
    collector's post-mortem path (obs/forensics.py)."""
    snaps: List[dict] = []
    try:
        names = sorted(
            f for f in os.listdir(path)
            if f.startswith("flight_") and f.endswith(".json")
        )
    except OSError:
        return snaps
    if limit is not None and limit > 0:
        names = names[-limit:]
    for name in names:
        try:
            with open(os.path.join(path, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(snap, dict):
            snap.setdefault("persisted_as", name)
            snaps.append(snap)
    return snaps


class FlightRecorder:
    """Bounded, thread-safe per-step ring + outlier auto-snapshots.

    Written from the engine step thread (and executor threads for
    encode); read from the asyncio loop by ``GET /debug/flight``. The
    lock guards only the slot store / ring copy — never a device wait.
    """

    # Rolling per-bucket median window for the outlier bar. Small on
    # purpose: the bar should track the CURRENT steady state (post-warmup
    # step times), not the whole process history.
    _MEDIAN_WINDOW = 64
    # Steps below this are never outliers regardless of the median —
    # 3x a 2 ms CPU decode step is noise, not a stall.
    _MIN_OUTLIER_S = 0.05
    # Buckets need this many samples before the bar arms (a fresh bucket's
    # first few steps straddle cache effects).
    _MIN_SAMPLES = 8

    def __init__(
        self,
        capacity: int = 512,
        outlier_factor: float = 3.0,
        snapshot_keep: int = 8,
        snapshot_tail: int = 64,
        snapshot_dir: Optional[str] = None,
        snapshot_disk_keep: int = 32,
    ):
        self.capacity = max(int(capacity), 0)
        self.outlier_factor = float(outlier_factor)
        self._ring: List[Optional[tuple]] = [None] * self.capacity
        self._idx = 0
        self._total = 0
        self._lock = threading.Lock()
        self._snapshots: "deque[dict]" = deque(maxlen=max(snapshot_keep, 1))
        self._snapshot_tail = max(int(snapshot_tail), 1)
        # Snapshot persistence (--flight-snapshot-dir): every retained
        # snapshot is also written as one JSON file, bounded to
        # ``snapshot_disk_keep`` with oldest-first eviction, and loaded
        # back after a restart — the post-mortem survives the process.
        self.snapshot_dir = snapshot_dir or None
        self._snapshot_disk_keep = max(int(snapshot_disk_keep), 1)
        self._persist_seq = 0
        self._restored: List[dict] = []
        if self.snapshot_dir:
            try:
                os.makedirs(self.snapshot_dir, exist_ok=True)
            except OSError:
                self.snapshot_dir = None
            else:
                self._restored = load_snapshot_dir(
                    self.snapshot_dir, limit=self._snapshot_disk_keep
                )
        # (bucket -> recent device_s samples) for the rolling median.
        self._samples: Dict[Tuple[str, str], "deque[float]"] = {}
        # Engine-supplied closure: () -> dict(waiting, running, swapped,
        # batch_tier_rows, kv_occupancy, preemptions). Must be cheap and
        # safe on the step thread.
        self._probe: Optional[Callable[[], dict]] = None
        # Host gap noted between steps: consumed by the next record.
        self._pending_gap = 0.0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def set_probe(self, probe: Optional[Callable[[], dict]]) -> None:
        self._probe = probe

    # -- write side (engine step thread) --------------------------------

    def note_host_gap(self, seconds: float) -> None:
        """The host gap closing at the NEXT decode dispatch; attached to
        that dispatch's record (EngineTelemetry.record_host_gap feeds
        this)."""
        self._pending_gap = max(float(seconds), 0.0)

    def record_step(
        self,
        kind: str,
        bucket: str,
        device_s: float,
        *,
        compiled: bool = False,
        tokens: int = 0,
    ) -> None:
        if not self.enabled:
            return
        probe = self._probe
        state: dict = {}
        if probe is not None:
            try:
                state = probe() or {}
            except Exception:  # noqa: BLE001 — telemetry must not kill steps
                state = {}
        gap, self._pending_gap = self._pending_gap, 0.0
        row = (
            time.time(),
            kind,
            bucket,
            round(max(device_s, 0.0), 6),
            round(gap, 6),
            bool(compiled),
            int(state.get("waiting", 0)),
            int(state.get("running", 0)),
            int(state.get("swapped", 0)),
            round(float(state.get("kv_occupancy", 0.0)), 4),
            int(state.get("preemptions", 0)),
            int(state.get("batch_tier_rows", 0)),
            int(tokens),
        )
        outlier_bar = None
        with self._lock:
            self._ring[self._idx] = row
            self._idx = (self._idx + 1) % self.capacity
            self._total += 1
            key = (kind, bucket)
            dq = self._samples.get(key)
            if dq is None:
                dq = self._samples[key] = deque(maxlen=self._MEDIAN_WINDOW)
            # Compile-bearing steps are architecture, not steady state:
            # they set no baseline (and ARE flagged via `compiled`).
            if not compiled:
                if len(dq) >= self._MIN_SAMPLES:
                    ordered = sorted(dq)
                    p50 = ordered[len(ordered) // 2]
                    outlier_bar = max(
                        p50 * self.outlier_factor, self._MIN_OUTLIER_S
                    )
                dq.append(device_s)
        if (
            outlier_bar is not None and device_s > outlier_bar
        ) or (compiled and device_s > self._MIN_OUTLIER_S):
            self.snapshot(
                "compile" if compiled else "tail_outlier",
                detail={
                    "kind": kind,
                    "bucket": bucket,
                    "device_s": round(device_s, 6),
                    "bar_s": round(outlier_bar, 6) if outlier_bar else None,
                    "waiting": row[_F_WAITING],
                    "running": row[_F_RUNNING],
                    "swapped": row[_F_SWAPPED],
                    "kv_occupancy": row[_F_KV_OCC],
                },
            )

    # -- read side -------------------------------------------------------

    def _rows_locked(self) -> List[tuple]:
        """Chronological copy of the live ring (oldest first)."""
        if self._total < self.capacity:
            rows = self._ring[: self._idx]
        else:
            rows = self._ring[self._idx:] + self._ring[: self._idx]
        return [r for r in rows if r is not None]

    def records(
        self, n: Optional[int] = None, window_s: Optional[float] = None
    ) -> List[dict]:
        with self._lock:
            rows = self._rows_locked()
        if window_s is not None and window_s > 0:
            cutoff = time.time() - window_s
            rows = [r for r in rows if r[_F_WALL] >= cutoff]
        if n is not None and n > 0:
            rows = rows[-n:]
        return [_row_dict(r) for r in rows]

    def snapshot(self, reason: str, detail: Optional[dict] = None) -> dict:
        """Freeze the ring tail as a post-mortem and retain it (bounded).

        Returns the snapshot so shutdown paths can also log it. The tail
        (not the whole ring) keeps SIGTERM dumps one log line, not a MB.
        """
        with self._lock:
            rows = self._rows_locked()[-self._snapshot_tail:]
            snap = {
                "reason": reason,
                "ts": time.time(),
                "detail": detail or {},
                "total_steps": self._total,
                "records": [_row_dict(r) for r in rows],
            }
            self._snapshots.append(snap)
        self._persist(snap)
        return snap

    def _persist(self, snap: dict) -> None:
        """Write one snapshot file (atomic rename) and evict beyond the
        disk bound, oldest first. Disk I/O stays off the ring lock; any
        failure downgrades to in-memory-only retention."""
        d = self.snapshot_dir
        if not d:
            return
        with self._lock:
            self._persist_seq += 1
            seq = self._persist_seq
        name = f"flight_{time.time_ns():020d}_{seq:06d}_{snap['reason']}.json"
        try:
            tmp = os.path.join(d, name + ".tmp")
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, os.path.join(d, name))
            stale = sorted(
                f for f in os.listdir(d)
                if f.startswith("flight_") and f.endswith(".json")
            )[: -self._snapshot_disk_keep]
            for old in stale:
                try:
                    os.remove(os.path.join(d, old))
                except OSError:
                    pass
        except OSError:
            return
        try:
            from .metrics import note_flight_snapshot_persisted

            note_flight_snapshot_persisted()
        except Exception:  # noqa: BLE001 — metrics must not kill snapshots
            pass

    def restored_snapshots(self) -> List[dict]:
        """Snapshots a previous process persisted to the snapshot dir,
        loaded at construction (``GET /debug/flight?snapshots=1``)."""
        return list(self._restored)

    def snapshots(self) -> List[dict]:
        with self._lock:
            return list(self._snapshots)

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "total_steps": self._total,
                "resident": min(self._total, self.capacity),
                "snapshots": len(self._snapshots),
            }

    def to_payload(
        self,
        n: Optional[int] = None,
        window_s: Optional[float] = None,
        include_restored: bool = False,
    ) -> dict:
        """The ``GET /debug/flight`` response body. ``include_restored``
        (the ``?snapshots=1`` query) adds snapshots persisted by a
        previous process to this snapshot dir — the post-mortem a
        forensics collector reads after a restart."""
        payload = {
            **self.stats(),
            "fields": list(_FIELDS),
            "records": self.records(n=n, window_s=window_s),
            "snapshot_log": self.snapshots(),
        }
        if include_restored:
            payload["restored_snapshots"] = self.restored_snapshots()
            payload["snapshot_dir"] = self.snapshot_dir
        return payload

    def reset_for_tests(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._idx = 0
            self._total = 0
            self._snapshots.clear()
            self._samples.clear()
            self._pending_gap = 0.0


class _NullFlightRecorder(FlightRecorder):
    """``--flight-buffer 0``: every write is a no-op, reads are empty."""

    def __init__(self):
        super().__init__(capacity=0)


NULL_FLIGHT_RECORDER = _NullFlightRecorder()
