"""``pst_stage_duration_seconds`` — the per-stage latency decomposition.

One histogram, labeled by ``component`` (router | engine) and ``stage``
(the span taxonomy in docs/observability.md), fed by every span the
in-process recorder completes. Unlike the whole-request moving averages in
``router/stats/request_stats.py``, these are true distributions: a TTFT
regression decomposes into admission vs routing vs proxy vs engine queue
vs prefill in one PromQL query.

The histogram lives in its own :data:`OBS_REGISTRY` (not the process
default registry) because router and engine expose *different* registries
on ``/metrics`` — both handlers append :func:`render_obs_metrics` so the
stage surface shows up on either component without double registration.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Histogram,
    generate_latest,
)
from prometheus_client.openmetrics import exposition as _openmetrics

OBS_REGISTRY = CollectorRegistry()

# The OpenMetrics content type /metrics answers when the scraper
# negotiates it (Accept: application/openmetrics-text) — the format that
# carries exemplars. Plain Prometheus scrapes keep getting text/plain,
# byte-identical to the pre-exemplar exposition.
OPENMETRICS_CONTENT_TYPE = _openmetrics.CONTENT_TYPE_LATEST
_OM_EOF = b"# EOF\n"

# Buckets span sub-ms (routing decisions) to minutes (long decodes).
_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

stage_duration = Histogram(
    "pst_stage_duration_seconds",
    "Per-stage request latency decomposition (span durations by stage)",
    ["component", "stage"],
    registry=OBS_REGISTRY,
    buckets=_BUCKETS,
)


kv_integrity_failures = Counter(
    "pst_kv_integrity_failures",
    "KV pages whose BLAKE2 digest failed verification on a read path, by "
    "source (prefetch = disagg consumer manifest-following, match_prefix "
    "= the remote leg of prefix matching, restore = single-page fault-up)."
    " Each count is a quarantined replica copy and a failover/recompute — "
    "a corrupt page is never decoded (docs/kvserver.md)",
    ["source"],
    registry=OBS_REGISTRY,
)

kv_read_repairs = Counter(
    "pst_kv_read_repairs",
    "KV pages found on fewer than R ring owners during a read and "
    "re-pushed to the owners that missed (client-side read-repair, "
    "docs/kvserver.md)",
    registry=OBS_REGISTRY,
)

forensics_bundles = Counter(
    "pst_forensics_bundles",
    "Evidence bundles harvested by the tail-outlier forensics collector "
    "(obs/forensics.py), by trigger (tail_outlier = p99 > 3x p50, "
    "slo_bar = an absolute latency bar, postmortem = collected from a "
    "dead engine's persisted snapshot dir). Each bundle is one JSON file "
    "beside the bench output naming the stalled step's bucket and queue "
    "state (docs/observability.md \"Forensics bundles\")",
    ["trigger"],
    registry=OBS_REGISTRY,
)

flight_snapshots_persisted = Counter(
    "pst_engine_flight_snapshots_persisted",
    "Flight-recorder snapshots written to --flight-snapshot-dir (bounded,"
    " oldest-first eviction) so tail-outlier post-mortems survive process"
    " death and restart (docs/observability.md \"Flight recorder\")",
    registry=OBS_REGISTRY,
)


def note_forensics_bundle(trigger: str, n: int = 1) -> None:
    """Count ``n`` harvested evidence bundles for ``trigger``."""
    if n > 0:
        forensics_bundles.labels(trigger=trigger).inc(n)


def note_flight_snapshot_persisted(n: int = 1) -> None:
    if n > 0:
        flight_snapshots_persisted.inc(n)


def note_integrity_failure(source: str, n: int = 1) -> None:
    """Count ``n`` digest-verification failures on read path ``source``."""
    if n > 0:
        kv_integrity_failures.labels(source=source).inc(n)


def note_read_repair(n: int = 1) -> None:
    if n > 0:
        kv_read_repairs.inc(n)


def observe_stage(
    component: str, stage: str, seconds: float,
    trace_id: Optional[str] = None,
) -> None:
    """Record one stage duration (negative durations clamp to 0 so a
    misbehaving clock can never corrupt the histogram).

    ``trace_id`` attaches as an OpenMetrics exemplar on the bucket this
    observation lands in, so a Grafana p99 bucket links straight to the
    matching ``/debug/requests`` timeline. Exemplars surface only on
    negotiated OpenMetrics scrapes; plain exposition is unchanged.
    """
    child = stage_duration.labels(component=component, stage=stage)
    if trace_id:
        child.observe(max(seconds, 0.0), exemplar={"trace_id": trace_id})
    else:
        child.observe(max(seconds, 0.0))


def wants_openmetrics(accept: Optional[str]) -> bool:
    """Whether an Accept header negotiates the OpenMetrics exposition."""
    return "application/openmetrics-text" in (accept or "")


def render_registries(
    registries: Iterable[CollectorRegistry], accept: Optional[str] = None
) -> Tuple[bytes, str]:
    """Render several registries as one exposition body.

    Plain Prometheus (the default): the byte-for-byte concatenation the
    pre-exemplar handlers produced. With OpenMetrics negotiated, each
    registry renders through the OpenMetrics encoder (exemplars appear)
    and the per-registry ``# EOF`` terminators collapse to one.
    """
    regs = list(registries)
    if wants_openmetrics(accept):
        parts = [_openmetrics.generate_latest(r) for r in regs]
        body = b"".join(
            p[: -len(_OM_EOF)] if p.endswith(_OM_EOF) else p for p in parts
        ) + _OM_EOF
        return body, OPENMETRICS_CONTENT_TYPE
    return b"".join(generate_latest(r) for r in regs), "text/plain"


def render_obs_metrics() -> bytes:
    """Prometheus exposition of the shared observability registry —
    appended to both the router's and the engine's ``/metrics`` body."""
    return generate_latest(OBS_REGISTRY)
