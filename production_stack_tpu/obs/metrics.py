"""``pst_stage_duration_seconds`` — the per-stage latency decomposition.

One histogram, labeled by ``component`` (router | engine) and ``stage``
(the span taxonomy in docs/observability.md), fed by every span the
in-process recorder completes. Unlike the whole-request moving averages in
``router/stats/request_stats.py``, these are true distributions: a TTFT
regression decomposes into admission vs routing vs proxy vs engine queue
vs prefill in one PromQL query.

The histogram lives in its own :data:`OBS_REGISTRY` (not the process
default registry) because router and engine expose *different* registries
on ``/metrics`` — both handlers append :func:`render_obs_metrics` so the
stage surface shows up on either component without double registration.
"""

from __future__ import annotations

from prometheus_client import CollectorRegistry, Histogram, generate_latest

OBS_REGISTRY = CollectorRegistry()

# Buckets span sub-ms (routing decisions) to minutes (long decodes).
_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

stage_duration = Histogram(
    "pst_stage_duration_seconds",
    "Per-stage request latency decomposition (span durations by stage)",
    ["component", "stage"],
    registry=OBS_REGISTRY,
    buckets=_BUCKETS,
)


def observe_stage(component: str, stage: str, seconds: float) -> None:
    """Record one stage duration (negative durations clamp to 0 so a
    misbehaving clock can never corrupt the histogram)."""
    stage_duration.labels(component=component, stage=stage).observe(
        max(seconds, 0.0)
    )


def render_obs_metrics() -> bytes:
    """Prometheus exposition of the shared observability registry —
    appended to both the router's and the engine's ``/metrics`` body."""
    return generate_latest(OBS_REGISTRY)
