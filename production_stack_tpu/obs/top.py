"""``pst-top`` — live terminal fleet view over ``GET /debug/fleet``.

Stdlib-only by design (urllib + ANSI): it must run from any pod or
laptop with nothing installed but Python. Polls one router replica —
any replica serves the same gossip-merged snapshot
(docs/observability.md "Fleet debugging") — and renders the deployment
as engines × {phase, breaker, in-flight, KV occupancy, prefix hit rate,
canary TTFT, compiles, host-gap p50} plus the replica membership,
routing and tenant panes.

    python -m production_stack_tpu.obs.top --router http://router:8001
    python -m production_stack_tpu.obs.top --once --json   # scripts/tests

``--once`` renders a single frame and exits (``--json`` prints the raw
snapshot instead — the mode e2e tests and shell pipelines consume).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Optional

_CLEAR = "\x1b[2J\x1b[H"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RED = "\x1b[31m"
_YELLOW = "\x1b[33m"
_GREEN = "\x1b[32m"
_RESET = "\x1b[0m"


def fetch_snapshot(
    router: str, timeout: float = 5.0, api_key: Optional[str] = None
) -> dict:
    req = urllib.request.Request(router.rstrip("/") + "/debug/fleet")
    if api_key:
        req.add_header("Authorization", f"Bearer {api_key}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def fetch_signal(
    router: str, timeout: float = 5.0, api_key: Optional[str] = None
) -> Optional[dict]:
    """Best-effort GET /autoscale/signal for the cost/burn pane; None
    when the router predates capacity signals or runs with
    --no-capacity-signal (the fleet view still renders)."""
    req = urllib.request.Request(router.rstrip("/") + "/autoscale/signal")
    if api_key:
        req.add_header("Authorization", f"Bearer {api_key}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, ValueError):
        return None


def _fmt(value, spec: str = "", dash: str = "-") -> str:
    if value is None:
        return dash
    try:
        return format(value, spec) if spec else str(value)
    except (TypeError, ValueError):
        return str(value)


def _phase_color(state: str, color: bool) -> str:
    if not color:
        return state
    tint = {
        "ready": _GREEN, "warming": _YELLOW,
        "draining": _YELLOW, "sleeping": _DIM,
    }.get(state, _RED)
    return f"{tint}{state}{_RESET}"


def render_frame(
    snap: dict, color: bool = True, signal: Optional[dict] = None
) -> str:
    """One frame of the fleet view as a string (pure — tested directly)."""
    bold = _BOLD if color else ""
    dim = _DIM if color else ""
    reset = _RESET if color else ""
    lines = []
    replicas = snap.get("replicas") or {}
    tenants = snap.get("tenants") or {}
    # Fleet-wide, not per-engine: sheds happen at router admission, so
    # they belong in the header, never in an engine row.
    total_sheds = sum(
        int(t.get("sheds_total") or 0) for t in tenants.values()
        if isinstance(t, dict)
    )
    lines.append(
        f"{bold}pst-top{reset}  replica={snap.get('replica')} "
        f"replicas={len(replicas)} synced={snap.get('synced')} "
        f"sheds={total_sheds}"
    )
    ages = ", ".join(
        f"{rid}{'*' if info.get('self') else ''}"
        f"({_fmt(info.get('sync_age_s'), '.1f', '0.0')}s)"
        for rid, info in sorted(replicas.items())
    )
    lines.append(f"{dim}membership: {ages}{reset}")
    lines.append("")

    header = (
        f"{'ENGINE':<28} {'PHASE':<9} {'BRKR':<9} {'INFL':>5} "
        f"{'KV%':>6} {'HIT%':>6} {'CANARY':>8} {'COMPILES':>8} "
        f"{'HOSTGAP':>8}"
    )
    lines.append(bold + header + reset)
    engines = snap.get("engines") or {}
    for url in sorted(engines):
        e = engines[url]
        kv = e.get("kv_occupancy")
        hit = e.get("prefix_hit_rate")
        canary = e.get("canary_ttft_s")
        lines.append(
            f"{url:<28} "
            f"{_phase_color(str(e.get('state', '?')), color):<9} "
            f"{_fmt(e.get('breaker')):<9} "
            f"{_fmt(e.get('in_flight_total', e.get('in_flight'))):>5} "
            f"{_fmt(kv * 100 if kv is not None else None, '.1f'):>6} "
            f"{_fmt(hit * 100 if hit is not None else None, '.1f'):>6} "
            f"{_fmt(canary * 1000 if canary is not None else None, '.0f'):>7}m "
            f"{_fmt(e.get('compiles_total')):>8} "
            f"{_fmt((e.get('host_gap_p50_s') or 0) * 1000, '.1f'):>7}m"
        )
    if not engines:
        lines.append(f"{dim}(no engines discovered){reset}")
    lines.append("")

    routing = snap.get("routing") or {}
    for rid, r in sorted(routing.items()):
        if not isinstance(r, dict):
            continue
        lines.append(
            f"{dim}routing[{rid}]: {r.get('policy')} "
            f"pins={_fmt(r.get('session_pins'))} "
            f"trie={_fmt(r.get('trie_nodes'))} "
            f"spills={_fmt(r.get('spills_total'))} "
            f"remaps={_fmt(r.get('session_remaps_total'))}{reset}"
        )
    if signal:
        # Capacity / burn pane (GET /autoscale/signal): the SLO-burn and
        # replica-hint view beside the engine table — the operator's
        # "do we need more chips?" answer without a Grafana tab.
        burn = signal.get("burn_rates") or {}
        sat = signal.get("saturation")
        tint = ""
        if color:
            tint = (
                _RED if signal.get("page_burning")
                else _YELLOW if (sat or 0) >= 0.5 else _GREEN
            )
        lines.append(
            f"{bold}capacity{reset}  "
            f"{tint}saturation={_fmt(sat, '.2f')}{reset} "
            f"burn(5m/1h/6h)="
            f"{_fmt(burn.get('5m'), '.2f')}/"
            f"{_fmt(burn.get('1h'), '.2f')}/"
            f"{_fmt(burn.get('6h'), '.2f')} "
            f"queue={_fmt(signal.get('queue_depth'))}"
            f"(slope {_fmt(signal.get('queue_depth_slope_per_s'), '+.2f')}/s) "
            f"kv_headroom={_fmt(signal.get('kv_headroom'), '.2f')} "
            f"ready={_fmt(signal.get('engines_ready'))} "
            f"hint={tint}{_fmt(signal.get('replica_hint'))}{reset}"
        )
        lines.append("")
    if tenants:
        lines.append(bold + (
            f"{'TENANT':<16} {'TIER':<12} {'W':>5} {'QUEUE':>6} "
            f"{'ADMITTED':>9} {'SHEDS':>6}"
        ) + reset)
        for name in sorted(tenants):
            t = tenants[name]
            if not isinstance(t, dict):
                continue
            lines.append(
                f"{name:<16} {_fmt(t.get('tier')):<12} "
                f"{_fmt(t.get('weight'), '.1f'):>5} "
                f"{_fmt(t.get('queue_depth')):>6} "
                f"{_fmt(t.get('admitted_total')):>9} "
                f"{_fmt(t.get('sheds_total')):>6}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="pst-top", description="live terminal fleet view (/debug/fleet)"
    )
    p.add_argument("--router", default="http://127.0.0.1:8001",
                   help="router base URL (any replica serves the merged view)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between refreshes")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit")
    p.add_argument("--json", dest="as_json", action="store_true",
                   help="print the raw /debug/fleet JSON (implies --once "
                        "semantics per frame; for scripts and tests)")
    p.add_argument("--api-key", default=None,
                   help="bearer token when the router guards /debug/fleet")
    p.add_argument("--no-color", dest="color", action="store_false",
                   default=sys.stdout.isatty())
    args = p.parse_args(argv)

    while True:
        try:
            snap = fetch_snapshot(args.router, api_key=args.api_key)
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"pst-top: cannot reach {args.router}/debug/fleet: {e}",
                  file=sys.stderr)
            if args.once or args.as_json:
                return 1
            # pstlint: disable=async-blocking(pst-top is a synchronous CLI — no event loop exists in this process; the sleep IS the poll interval)
            time.sleep(args.interval)
            continue
        if args.as_json:
            print(json.dumps(snap, indent=2, sort_keys=True))
            return 0
        signal = fetch_signal(args.router, api_key=args.api_key)
        frame = render_frame(snap, color=args.color, signal=signal)
        if args.once:
            print(frame)
            return 0
        sys.stdout.write(_CLEAR + frame + "\n")
        sys.stdout.flush()
        # pstlint: disable=async-blocking(pst-top is a synchronous CLI — no event loop exists in this process; the sleep IS the poll interval)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
