"""Lightweight in-process request tracing (Dapper/OTel span model).

Always-on, zero-hard-dependency: spans are plain objects recorded into a
bounded ring buffer of completed request timelines (served at
``GET /debug/requests``) and fed into the ``pst_stage_duration_seconds``
histogram (:mod:`.metrics`). When the optional OpenTelemetry SDK is
installed AND ``OTEL_EXPORTER_OTLP_ENDPOINT`` is configured
(``utils_tracing.init_otel``), every completed span is mirrored to the
real SDK so the same timelines land in Jaeger/Tempo — but nothing here
ever *requires* the SDK.

Propagation is standard W3C Trace Context: one ``traceparent``
(``00-<32 hex trace id>-<16 hex span id>-01``) plus ``X-Request-Id``
travels on every outbound hop, so one trace id spans router admission →
routing → every proxy attempt / retry / hedge leg → engine queue →
prefill → decode.

Timing discipline: span starts/ends ride ``time.monotonic()`` (durations
survive wall-clock adjustments); each trace anchors one wall-clock
timestamp at creation purely so timelines can be displayed in real time.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..logging_utils import init_logger
from .metrics import observe_stage

logger = init_logger(__name__)

TRACEPARENT_HEADER = "traceparent"
REQUEST_ID_HEADER = "X-Request-Id"

# Bounds so a pathological request can never balloon a timeline.
_MAX_SPANS_PER_TRACE = 128
_MAX_EVENTS_PER_SPAN = 32


def error_headers(source=None, extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Headers for an error response: ``X-Request-Id`` plus ``extra``.

    The sanctioned builder the ``hop-contract`` pstlint check recognizes
    (docs/static-analysis.md): every 4xx/5xx constructed in router/obs/
    resilience code passes its headers through here so the request id
    survives even on paths that bypass the tracing middleware's
    ``setdefault`` (e.g. responses prepared inside streaming handlers).

    ``source`` may be the request id string, anything with a mapping
    ``.get`` (an ``aiohttp.web.Request`` — reads the id the tracing
    middleware stored), or None. With no id resolvable the header is
    omitted so the middleware's setdefault (which knows the real id)
    fills it rather than this helper inventing a second one.
    """
    headers: Dict[str, str] = dict(extra) if extra else {}
    request_id: Optional[str] = None
    if isinstance(source, str):
        request_id = source
    elif source is not None:
        getter = getattr(source, "get", None)
        if getter is not None:
            request_id = getter("request_id")
        if not request_id:
            req_headers = getattr(source, "headers", None)
            if req_headers is not None:
                request_id = req_headers.get(REQUEST_ID_HEADER)
    if request_id:
        headers.setdefault(REQUEST_ID_HEADER, request_id)
    return headers


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


# (trace_id, span_id) ints the OTel mirror forces onto the next SDK span,
# so exported spans carry the SAME ids as the in-process recorder — parent
# links resolve and one request renders as one tree in Jaeger/Tempo.
_FORCED_OTEL_IDS: "contextvars.ContextVar[Optional[Tuple[int, int]]]" = (
    contextvars.ContextVar("pst_forced_otel_ids", default=None)
)


class MirroredIdGenerator:
    """OTel SDK id generator (duck-typed ``IdGenerator``) that yields the
    recorder's ids when the mirror is replaying a span, random ids
    otherwise. Installed by ``utils_tracing.init_otel``."""

    def __init__(self):
        self._rand = random.Random()

    def generate_trace_id(self) -> int:
        forced = _FORCED_OTEL_IDS.get()
        if forced is not None:
            return forced[0]
        return self._rand.getrandbits(128) or 1

    def generate_span_id(self) -> int:
        forced = _FORCED_OTEL_IDS.get()
        if forced is not None:
            return forced[1]
        return self._rand.getrandbits(64) or 1


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a W3C traceparent header, or
    None for anything malformed (a bad header from one client must start a
    fresh trace, never fail the request)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    _, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return trace_id.lower(), span_id.lower()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


class Span:
    """One named stage of a request. ``end()`` is idempotent and feeds the
    stage-duration histogram + the OTel mirror."""

    __slots__ = (
        "name", "span_id", "parent_id", "start_mono", "end_mono",
        "attributes", "events", "_trace",
    )

    def __init__(
        self,
        trace: "RequestTrace",
        name: str,
        parent_id: Optional[str],
        attributes: Optional[dict] = None,
        start_mono: Optional[float] = None,
    ):
        self._trace = trace
        self.name = name
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.start_mono = start_mono if start_mono is not None else time.monotonic()
        self.end_mono: Optional[float] = None
        self.attributes: dict = dict(attributes) if attributes else {}
        self.events: List[dict] = []

    @property
    def trace_id(self) -> str:
        return self._trace.trace_id

    def traceparent(self) -> Optional[str]:
        """Outbound W3C header naming THIS span as the parent of whatever
        the next hop records."""
        return format_traceparent(self._trace.trace_id, self.span_id)

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs) -> None:
        if len(self.events) >= _MAX_EVENTS_PER_SPAN:
            return
        self.events.append({
            "name": name,
            "at_ms": round((time.monotonic() - self._trace.t0_mono) * 1000.0, 3),
            "attributes": attrs,
        })

    @property
    def duration_s(self) -> Optional[float]:
        if self.end_mono is None:
            return None
        return self.end_mono - self.start_mono

    def end(self, end_mono: Optional[float] = None) -> None:
        if self.end_mono is not None:
            return
        self.end_mono = end_mono if end_mono is not None else time.monotonic()
        self._trace._on_span_end(self)

    def to_dict(self, t0_mono: float) -> dict:
        end = self.end_mono if self.end_mono is not None else time.monotonic()
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ms": round((self.start_mono - t0_mono) * 1000.0, 3),
            "duration_ms": round((end - self.start_mono) * 1000.0, 3),
            "attributes": self.attributes,
            "events": self.events,
        }


class RequestTrace:
    """All spans of one request on this component, rooted at ``root``.

    ``finish()`` ends the root span and flushes the completed timeline to
    the recorder's ring buffer; it is idempotent, so a middleware can call
    it in a ``finally`` regardless of how the handler exited."""

    def __init__(
        self,
        recorder: "SpanRecorder",
        request_id: str,
        name: str = "request",
        trace_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
        attributes: Optional[dict] = None,
    ):
        self.recorder = recorder
        self.request_id = request_id
        self.trace_id = trace_id or new_trace_id()
        self.t0_mono = time.monotonic()
        self.t0_wall = time.time()
        self.spans: List[Span] = []
        self._finished = False
        self.root = self.span(
            name, parent_id=parent_span_id, attributes=attributes
        )

    # -- span creation -----------------------------------------------------

    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        attributes: Optional[dict] = None,
        parent_id: Optional[str] = None,
    ) -> Span:
        """Start a child span (of ``parent``, default the root)."""
        if parent_id is None:
            parent_id = (
                parent.span_id if parent is not None
                else (self.root.span_id if self.spans else None)
            )
        s = Span(self, name, parent_id, attributes)
        if len(self.spans) < _MAX_SPANS_PER_TRACE:
            self.spans.append(s)
        return s

    def record_span(
        self,
        name: str,
        duration_s: float,
        end_mono: Optional[float] = None,
        parent: Optional[Span] = None,
        attributes: Optional[dict] = None,
    ) -> Span:
        """Record an already-elapsed stage post-hoc (the engine reconstructs
        queue/prefill/decode from Sequence timestamps after the fact)."""
        end = end_mono if end_mono is not None else time.monotonic()
        s = self.span(name, parent=parent, attributes=attributes)
        s.start_mono = end - max(duration_s, 0.0)
        s.end(end_mono=end)
        return s

    def add_event(self, name: str, **attrs) -> None:
        self.root.add_event(name, **attrs)

    # -- completion --------------------------------------------------------

    def _on_span_end(self, span: Span) -> None:
        # The trace id rides along as an OpenMetrics exemplar: the
        # histogram bucket this stage lands in links straight back to
        # this request's /debug/requests timeline.
        observe_stage(
            self.recorder.component, span.name, span.duration_s or 0.0,
            trace_id=self.trace_id,
        )
        self.recorder._mirror_otel(self, span)

    def finish(self, status: Optional[int] = None) -> None:
        if self._finished:
            return
        self._finished = True
        if status is not None:
            self.root.set_attribute("http.status_code", status)
        self.root.end()
        self.recorder._flush(self)

    def to_dict(self) -> dict:
        end = self.root.end_mono or time.monotonic()
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "component": self.recorder.component,
            "start_time": self.t0_wall,
            "duration_ms": round((end - self.root.start_mono) * 1000.0, 3),
            "status": self.root.attributes.get("http.status_code"),
            "spans": [s.to_dict(self.t0_mono) for s in self.spans],
        }


class _NoopSpan:
    """Inert span: call sites never need ``if span is not None`` guards."""

    __slots__ = ()
    name = ""
    span_id = ""
    parent_id = None
    attributes: dict = {}
    events: list = []
    duration_s = None

    def traceparent(self) -> Optional[str]:
        return None

    def set_attribute(self, key, value):
        return self

    def add_event(self, name, **attrs):
        pass

    def end(self, end_mono=None):
        pass


class _NoopTrace:
    """Inert trace returned when tracing is disabled."""

    __slots__ = ()
    trace_id = ""
    request_id = ""
    root = _NoopSpan()
    spans: list = []

    def span(self, name, parent=None, attributes=None, parent_id=None):
        return NOOP_SPAN

    def record_span(self, name, duration_s, end_mono=None, parent=None,
                    attributes=None):
        return NOOP_SPAN

    def add_event(self, name, **attrs):
        pass

    def finish(self, status=None):
        pass


NOOP_SPAN = _NoopSpan()
NOOP_TRACE = _NoopTrace()


class SpanRecorder:
    """Per-component span sink: stage histogram + OTel mirror + a bounded
    ring buffer of completed request timelines for ``/debug/requests``."""

    def __init__(self, component: str, buffer: int = 256, enabled: bool = True):
        self.component = component
        # `enabled` gates tracing wholesale (spans, histograms, propagation);
        # `buffer` only sizes the /debug/requests ring — 0 disables that
        # endpoint while tracing keeps running.
        self.enabled = bool(enabled)
        self.buffer_size = max(buffer, 0)
        self._ring: "deque[dict]" = deque(maxlen=max(self.buffer_size, 1))
        self._lock = threading.Lock()

    @property
    def debug_endpoint_enabled(self) -> bool:
        """Whether GET /debug/requests should serve (vs 404): needs tracing
        on AND a non-zero ring."""
        return self.enabled and self.buffer_size > 0

    # -- trace creation ----------------------------------------------------

    def trace(
        self,
        request_id: str,
        headers=None,
        name: str = "request",
        attributes: Optional[dict] = None,
    ) -> RequestTrace:
        """Root trace for one request, joining the caller's trace when a
        valid ``traceparent`` came in on ``headers``."""
        if not self.enabled:
            return NOOP_TRACE
        trace_id = parent_span = None
        if headers is not None:
            parsed = parse_traceparent(headers.get(TRACEPARENT_HEADER))
            if parsed is not None:
                trace_id, parent_span = parsed
        return RequestTrace(
            self, request_id, name=name, trace_id=trace_id,
            parent_span_id=parent_span, attributes=attributes,
        )

    # -- ring buffer -------------------------------------------------------

    def _flush(self, trace: RequestTrace) -> None:
        if self.buffer_size <= 0:
            return
        with self._lock:
            self._ring.append(trace.to_dict())

    def timelines(
        self, limit: Optional[int] = None, request_id: Optional[str] = None
    ) -> List[dict]:
        """Completed request timelines, most recent first."""
        with self._lock:
            items = list(self._ring)
        items.reverse()
        if request_id is not None:
            items = [t for t in items if t["request_id"] == request_id]
        if limit is not None and limit >= 0:
            items = items[:limit]
        return items

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- OTel mirror -------------------------------------------------------

    def _mirror_otel(self, trace: RequestTrace, span: Span) -> None:
        """Replay a completed span into the real OTel SDK (when
        ``utils_tracing.init_otel`` activated it). Best-effort by design:
        any SDK hiccup is swallowed — the in-process recorder is the
        source of truth."""
        from ..utils_tracing import otel_active

        if not otel_active():
            return
        try:
            from opentelemetry import trace as ot
            from opentelemetry.trace import (
                NonRecordingSpan,
                SpanContext,
                TraceFlags,
                set_span_in_context,
            )

            ctx = None
            parent_id = span.parent_id
            if parent_id:
                parent_ctx = SpanContext(
                    trace_id=int(trace.trace_id, 16),
                    span_id=int(parent_id, 16),
                    is_remote=False,
                    trace_flags=TraceFlags(0x01),
                )
                ctx = set_span_in_context(NonRecordingSpan(parent_ctx))
            start_wall = trace.t0_wall + (span.start_mono - trace.t0_mono)
            end_wall = trace.t0_wall + (
                (span.end_mono or span.start_mono) - trace.t0_mono
            )
            tracer = ot.get_tracer("production_stack_tpu")
            attrs = {
                k: v for k, v in span.attributes.items()
                if isinstance(v, (str, bool, int, float))
            }
            attrs["pst.request_id"] = trace.request_id
            attrs["pst.trace_id"] = trace.trace_id
            # Force the recorder's ids onto the SDK span (via the
            # MirroredIdGenerator init_otel installs) so exported parent
            # links resolve to spans that actually exist.
            token = _FORCED_OTEL_IDS.set(
                (int(trace.trace_id, 16), int(span.span_id, 16))
            )
            try:
                otspan = tracer.start_span(
                    span.name, context=ctx,
                    start_time=int(start_wall * 1e9), attributes=attrs,
                )
            finally:
                _FORCED_OTEL_IDS.reset(token)
            for ev in span.events:
                otspan.add_event(
                    ev["name"],
                    {
                        k: v for k, v in ev["attributes"].items()
                        if isinstance(v, (str, bool, int, float))
                    },
                    # The event's real wall time — mirroring runs at span
                    # end, and defaulting to now() would pile every event
                    # at the end of the exported span.
                    timestamp=int(
                        (trace.t0_wall + ev["at_ms"] / 1000.0) * 1e9
                    ),
                )
            otspan.end(end_time=int(end_wall * 1e9))
        except Exception as e:  # noqa: BLE001 — mirroring is best-effort
            logger.debug("otel span mirror failed: %s", e)
