"""Replicated, sharded remote-KV client (docs/kvserver.md).

:class:`ShardedKVClient` wraps one
:class:`~production_stack_tpu.engine.cache_tiering.RemoteKVClient` per
kvserver shard behind the SAME call surface, so the tiered allocator, the
streamed-handoff publisher and the consumer prefetcher are shard-oblivious
— ``--remote-kv-url`` simply grows commas.

Placement: blocks map to shards by their content chunk hash over the
shared consistent-hash ring (:mod:`production_stack_tpu.hashring` — the
same class, vnode count and key scheme the router uses), with
``replication`` (R) distinct owners per block; manifests replicate to the
request id's owner set the same way. Every process that touches a block —
producer engine, consumer engine, fake engine, the shard's own
anti-entropy sweep — computes identical owner sets, which is what makes
"replica" a property of the ring rather than of any coordinator.

Fan-out and failover:

- **puts** fan to all R owners; a page counts as published when at least
  one owner stored it (the survivors' copies are what the degradation
  matrix leans on — one shard SIGKILLed mid-handoff must not fail the
  transfer).
- **reads** walk the ring order from the block's position: owners first,
  then the remaining shards (so blocks placed under an older ring epoch
  stay findable after a shard join — rebalance never loses data, it only
  adds a hop until read-repair re-homes the block). The walk skips shards
  whose circuit breaker refuses (the same 3-state breaker machinery the
  router's proxy uses, fed here from per-call outcomes), fails over on
  error/miss/corrupt, and each hop is one bounded attempt under the
  caller's remaining deadline (the per-shard client's own jittered retry
  covers transient blips).
- **read-repair**: a block served by anything but its first healthy owner
  is re-pushed to the owners that missed, counted in
  ``pst_kv_read_repairs_total`` — the on-demand half of replica healing
  (the kvserver's anti-entropy sweep is the background half).
- **integrity**: digest verification lives in the per-shard client
  (every framed read is checked before deserialization); a corrupt copy
  is quarantined on its shard and the walk continues to the next replica,
  so corruption degrades to at worst a recompute, never a wrong page.

Thread contract: engine step/worker/executor threads all call in here,
and :class:`~production_stack_tpu.resilience.breaker.CircuitBreaker` is
asyncio-single-thread code — every breaker touch goes through one lock.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..hashring import ConsistentHashRing
from ..logging_utils import init_logger
from ..obs.metrics import note_read_repair
from ..resilience.breaker import CircuitBreaker

logger = init_logger(__name__)

# Shard breakers trip faster than router↔engine ones (3 vs 5 failures,
# 5 s vs 10 s recovery): a dead shard costs every read a timeout until
# the breaker opens, and the replica walk makes skipping cheap.
SHARD_FAILURE_THRESHOLD = 3
SHARD_RECOVERY_TIME_S = 5.0


class ShardedKVClient:
    """R-way replicated client over N kvserver shards (docs/kvserver.md)."""

    def __init__(
        self,
        urls: Sequence[str],
        replication: int = 2,
        timeout: float = 5.0,
    ):
        from ..engine.cache_tiering import RemoteKVClient

        self.urls = [u.rstrip("/") for u in urls if u]
        if not self.urls:
            raise ValueError("ShardedKVClient needs at least one shard URL")
        self.replication = min(max(int(replication), 1), len(self.urls))
        self.timeout = timeout
        self._ring = ConsistentHashRing()
        self._ring.update(self.urls)
        self._clients: Dict[str, RemoteKVClient] = {
            u: RemoteKVClient(u, timeout=timeout) for u in self.urls
        }
        # pstlint: owned-by=lock:_breaker_lock
        self._breakers: Dict[str, CircuitBreaker] = {
            u: CircuitBreaker(
                u,
                failure_threshold=SHARD_FAILURE_THRESHOLD,
                recovery_time=SHARD_RECOVERY_TIME_S,
            )
            for u in self.urls
        }
        self._breaker_lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "integrity_failures": 0,
            "read_repairs": 0,
            "failovers": 0,
            "retries": 0,
        }

    # -- ring placement ---------------------------------------------------

    def owners(self, key) -> List[str]:
        """The R-member replica owner set for a block hash / request id."""
        return self._ring.get_nodes(str(key), self.replication)

    def _walk(self, key) -> List[str]:
        """Ring-order read walk: the owner set first, then every remaining
        shard — the tail keeps pre-join blocks findable after a rebalance."""
        return self._ring.get_nodes(str(key), len(self.urls))

    # -- breaker gossip ---------------------------------------------------

    def _admits(self, url: str) -> bool:
        with self._breaker_lock:
            return self._breakers[url].allows()

    def _record(self, url: str, ok: bool) -> None:
        with self._breaker_lock:
            if ok:
                self._breakers[url].record_success()
            else:
                self._breakers[url].record_failure()

    def shard_health(self) -> Dict[str, str]:
        """Breaker state per shard (``closed``/``half_open``/``open``) —
        the /debug + stats surface."""
        with self._breaker_lock:
            return {
                u: b.current_state().value for u, b in self._breakers.items()
            }

    def refresh_counters(self) -> None:
        """Fold the per-shard clients' audit counters into this client's
        (integrity failures and retries are counted where they happen)."""
        for key in ("integrity_failures", "retries"):
            self.counters[key] = sum(
                c.counters[key] for c in self._clients.values()
            )

    # -- puts (fan to all owners) ----------------------------------------

    def put(
        self, h: int, k: np.ndarray, v: np.ndarray,
        timeout: Optional[float] = None,
    ) -> bool:
        ok_any = False
        for url in self.owners(h):
            ok = self._clients[url].put(h, k, v, timeout=timeout)
            self._record(url, ok)
            ok_any = ok_any or ok
        return ok_any

    def put_blocks(
        self,
        pages: Sequence[Tuple[int, np.ndarray, np.ndarray]],
        timeout: Optional[float] = None,
    ) -> bool:
        """Fan batched puts to each page's owner set; True when EVERY page
        landed on at least one owner (a wholly-dead shard degrades to
        R-1 copies, not to a failed transfer)."""
        if not pages:
            return True
        by_owner: Dict[str, List[Tuple[int, np.ndarray, np.ndarray]]] = {}
        for page in pages:
            for url in self.owners(page[0]):
                by_owner.setdefault(url, []).append(page)
        owner_ok: Dict[str, bool] = {}
        for url, group in by_owner.items():
            if not self._admits(url):
                owner_ok[url] = False
                continue
            ok = self._clients[url].put_blocks(group, timeout=timeout)
            self._record(url, ok)
            owner_ok[url] = ok
        return all(
            any(owner_ok.get(url, False) for url in self.owners(page[0]))
            for page in pages
        )

    # -- reads (nearest healthy owner, failover, read-repair) -------------

    def get(
        self, h: int, timeout: Optional[float] = None,
        source: str = "restore",
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.timeout
        )
        walk = self._walk(h)
        owner_set = set(self.owners(h))
        missed_owners: List[str] = []
        for i, url in enumerate(walk):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            if not self._admits(url):
                if url in owner_set:
                    missed_owners.append(url)
                continue
            page, status = self._clients[url].get_ex(
                h, timeout=remaining, source=source
            )
            self._record(url, status != "error")
            if page is not None:
                if i > 0:
                    self.counters["failovers"] += 1
                self._repair([(h, *page)], missed_owners)
                return page
            if url in owner_set:
                missed_owners.append(url)
        return None

    def get_blocks(
        self, hashes: Sequence[int], timeout: Optional[float] = None,
        source: str = "match_prefix",
    ) -> "dict[int, Tuple[np.ndarray, np.ndarray]]":
        if not hashes:
            return {}
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.timeout
        )
        # Group by read-walk so each shard sees ONE batched round trip per
        # call (N shards -> at most N rotations of the ring order).
        groups: Dict[tuple, List[int]] = {}
        for h in hashes:
            groups.setdefault(tuple(self._walk(h)), []).append(h)
        found: "dict[int, Tuple[np.ndarray, np.ndarray]]" = {}
        repairs: Dict[str, List[Tuple[int, np.ndarray, np.ndarray]]] = {}
        for walk, group in groups.items():
            owner_set = {
                h: set(self.owners(h)) for h in group
            }
            remaining_hashes = list(group)
            missed: Dict[int, List[str]] = {h: [] for h in group}
            for i, url in enumerate(walk):
                if not remaining_hashes:
                    break
                budget = deadline - time.monotonic()
                if budget <= 0:
                    break
                if not self._admits(url):
                    for h in remaining_hashes:
                        if url in owner_set[h]:
                            missed[h].append(url)
                    continue
                pages, status = self._clients[url].get_blocks_ex(
                    remaining_hashes, timeout=budget, source=source
                )
                self._record(url, status != "error")
                if i > 0 and pages:
                    self.counters["failovers"] += 1
                for h, page in pages.items():
                    found[h] = page
                    for owner in missed[h]:
                        repairs.setdefault(owner, []).append((h, *page))
                still = []
                for h in remaining_hashes:
                    if h in pages:
                        continue
                    if url in owner_set[h]:
                        missed[h].append(url)
                    still.append(h)
                remaining_hashes = still
        for url, batch in repairs.items():
            self._push_repairs(url, batch)
        return found

    def _repair(self, pages, missed_owners: List[str]) -> None:
        for url in missed_owners:
            self._push_repairs(url, pages)

    def _push_repairs(self, url: str, pages) -> None:
        """Re-push blocks an owner was proven to miss (read-repair). Runs
        inline on the read path — bounded by what the read itself just
        observed missing, and the read paths (prefetch executor thread,
        match_prefix walk) already tolerate remote round trips."""
        if not pages or not self._admits(url):
            return
        ok = self._clients[url].put_blocks(pages, timeout=self.timeout)
        self._record(url, ok)
        if ok:
            self.counters["read_repairs"] += len(pages)
            note_read_repair(len(pages))

    # -- manifests (replicated to the request id's owner set) -------------

    def post_manifest(
        self,
        request_id: str,
        hashes: Sequence[int],
        complete: bool = False,
        total_blocks: Optional[int] = None,
        timeout: Optional[float] = None,
    ) -> bool:
        ok_any = False
        for url in self.owners(request_id):
            ok = self._clients[url].post_manifest(
                request_id, hashes, complete=complete,
                total_blocks=total_blocks, timeout=timeout,
            )
            self._record(url, ok)
            ok_any = ok_any or ok
        return ok_any

    def get_manifest(
        self,
        request_id: str,
        wait_s: float = 0.0,
        have: int = -1,
        timeout: Optional[float] = None,
    ) -> Optional[dict]:
        """Owner-walk manifest read: the first healthy owner carries the
        long-poll; on no progress the remaining owners get a quick
        (``wait_s=0``) check so a replica that missed some appends (it was
        down for them) cannot stall the consumer behind a stale view —
        the richest view wins."""
        best: Optional[dict] = None
        poll = wait_s
        for url in self.owners(request_id):
            if not self._admits(url):
                continue
            view = self._clients[url].get_manifest(
                request_id, wait_s=poll, have=have, timeout=timeout
            )
            poll = 0.0  # only the first healthy owner long-polls
            if view is None:
                continue
            if (
                best is None
                or (view.get("complete") and not best.get("complete"))
                or len(view.get("hashes") or [])
                > len(best.get("hashes") or [])
            ):
                best = view
            if best.get("complete") or len(best.get("hashes") or []) > have:
                return best
        return best

    # -- stats ------------------------------------------------------------

    def stats(self) -> Dict[str, object]:
        self.refresh_counters()
        return {
            "shards": len(self.urls),
            "replication": self.replication,
            "shard_health": self.shard_health(),
            **self.counters,
        }
