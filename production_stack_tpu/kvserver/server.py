"""Remote KV block store (`pst-kv-server`) — the LMCache-server analogue.

Reference: the cache-server Deployment running `lmcache_experimental_server`
(`helm/templates/deployment-cache-server.yaml:31-43`), which engines reach
over TCP with a serde format. Here: an aiohttp server speaking the page serde
of :mod:`production_stack_tpu.engine.cache_tiering` over HTTP (TCP/DCN), with
a byte-capacity LRU.

Endpoints:
  PUT  /blocks/{hash}     store one page (raw serde body)
  GET  /blocks/{hash}     fetch one page (404 if absent)
  POST /blocks            store N pages in ONE round trip (framed body)
  GET  /blocks?hashes=    fetch N pages in ONE round trip (framed body;
                          absent hashes are simply omitted from the reply)
  POST /manifests/{rid}   append a disagg-transfer manifest update
  GET  /manifests/{rid}   read a manifest (``?wait_s=`` long-polls for
                          progress past ``?have=`` blocks / completion)
  GET  /stats             occupancy/bytes/hit counters
  GET  /health

The framed batch body is ``repeat([8B hash LE][4B length LE][payload])`` —
hash keys are the engine-side block hashes, payloads are the page serde.

Manifests (docs/disagg.md "Manifest protocol"): the streamed prefill→decode
KV handoff is coordinated by a request-id-keyed manifest. The prefill engine
appends the block-hash list as each prefill chunk's pages are published, and
posts ``complete`` with ``total_blocks`` when the prefill pass finishes; the
decode engine long-polls the manifest and batch-fetches published blocks
while the prefill is still running — transfer overlapped with compute.
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from ..logging_utils import init_logger

logger = init_logger(__name__)

# Manifests older than this are dropped (a crashed decode leg must not pin
# its prefill's manifest forever); sized generously above any request
# deadline the router would still be waiting on.
MANIFEST_TTL_S = 10 * 60.0
MANIFEST_CAP = 4096


def pack_blocks(pages: List[Tuple[int, bytes]]) -> bytes:
    """Frame N (hash, payload) pages into one batch body."""
    parts = []
    for h, data in pages:
        parts.append(int(h).to_bytes(8, "little", signed=False))
        parts.append(len(data).to_bytes(4, "little"))
        parts.append(data)
    return b"".join(parts)


def unpack_blocks(buf: bytes) -> List[Tuple[int, bytes]]:
    """Inverse of :func:`pack_blocks`; raises ValueError on a torn frame."""
    out: List[Tuple[int, bytes]] = []
    off = 0
    n = len(buf)
    while off < n:
        if off + 12 > n:
            raise ValueError("torn batch frame header")
        h = int.from_bytes(buf[off : off + 8], "little")
        ln = int.from_bytes(buf[off + 8 : off + 12], "little")
        off += 12
        if off + ln > n:
            raise ValueError("torn batch frame payload")
        out.append((h, buf[off : off + ln]))
        off += ln
    return out


class BlockStore:
    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._blocks: "collections.OrderedDict[int, bytes]" = collections.OrderedDict()
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Transfer-audit counters (docs/disagg.md): distinguish HTTP round
        # trips from pages moved, so tests can assert the streamed handoff
        # ships each page ONCE and batches N pages per trip.
        self.put_calls = 0
        self.blocks_put = 0
        self.get_calls = 0

    def put(self, h: int, data: bytes) -> None:
        self.blocks_put += 1
        if len(data) > self.max_bytes:
            return  # unstorable; never evict the fleet's cache trying
        if h in self._blocks:
            self.bytes_used -= len(self._blocks.pop(h))
        while self._blocks and self.bytes_used + len(data) > self.max_bytes:
            _, old = self._blocks.popitem(last=False)
            self.bytes_used -= len(old)
            self.evictions += 1
        self._blocks[h] = data
        self.bytes_used += len(data)

    def get(self, h: int) -> Optional[bytes]:
        data = self._blocks.get(h)
        if data is None:
            self.misses += 1
            return None
        self._blocks.move_to_end(h)
        self.hits += 1
        return data

    def contains(self, h: int) -> bool:
        return h in self._blocks


class ManifestStore:
    """Request-id-keyed disagg-transfer manifests with change signaling."""

    def __init__(self):
        self._manifests: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self._events: Dict[str, asyncio.Event] = {}

    def _prune(self, now: float) -> None:
        cutoff = now - MANIFEST_TTL_S
        stale = [
            rid for rid, m in self._manifests.items() if m["ts"] < cutoff
        ]
        for rid in stale:
            self._manifests.pop(rid, None)
            self._events.pop(rid, None)
        while len(self._manifests) > MANIFEST_CAP:
            rid, _ = self._manifests.popitem(last=False)
            self._events.pop(rid, None)
        if len(self._events) > 2 * MANIFEST_CAP:
            # Events registered by pollers whose manifest never arrived
            # (producer crashed / transfer fault) are not covered by the
            # manifest-keyed pruning above — bound them separately.
            self._events = {
                rid: ev for rid, ev in self._events.items()
                if rid in self._manifests
            }

    def update(
        self,
        rid: str,
        hashes: List[int],
        complete: bool,
        total_blocks: Optional[int],
    ) -> dict:
        now = time.time()
        self._prune(now)
        m = self._manifests.get(rid)
        if m is None:
            m = {"hashes": [], "complete": False, "total_blocks": None,
                 "ts": now}
            self._manifests[rid] = m
        seen = set(m["hashes"])
        for h in hashes:
            if h not in seen:
                m["hashes"].append(int(h))
                seen.add(h)
        if complete:
            m["complete"] = True
        if total_blocks is not None:
            m["total_blocks"] = int(total_blocks)
        m["ts"] = now
        ev = self._events.get(rid)
        if ev is not None:
            ev.set()
        return m

    def view(self, rid: str) -> Optional[dict]:
        m = self._manifests.get(rid)
        if m is None:
            return None
        return {
            "request_id": rid,
            "hashes": list(m["hashes"]),
            "complete": m["complete"],
            "total_blocks": m["total_blocks"],
        }

    async def wait(self, rid: str, have: int, wait_s: float) -> Optional[dict]:
        """Long-poll: return as soon as the manifest has more than ``have``
        blocks or is complete, else after ``wait_s``."""
        deadline = time.monotonic() + max(wait_s, 0.0)
        try:
            while True:
                # Clear BEFORE checking: an update() that lands between
                # the manifest check and the wait sets the event and must
                # not be erased, or the poll stalls a full wait cycle.
                ev = self._events.setdefault(rid, asyncio.Event())
                ev.clear()
                m = self._manifests.get(rid)
                if m is not None and (
                    len(m["hashes"]) > have or m["complete"]
                ):
                    return self.view(rid)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self.view(rid)
                try:
                    await asyncio.wait_for(
                        ev.wait(), timeout=min(remaining, 1.0)
                    )
                except asyncio.TimeoutError:
                    pass
        finally:
            if rid not in self._manifests:
                # This poller registered the event for a manifest that
                # never arrived (producer crashed / transfer fault): drop
                # it, or every failed transfer would leak one Event.
                self._events.pop(rid, None)

    def __len__(self) -> int:
        return len(self._manifests)


def create_kv_server_app(max_bytes: int = 8 << 30) -> web.Application:
    store = BlockStore(max_bytes)
    manifests = ManifestStore()
    app = web.Application(client_max_size=256 << 20)
    app["store"] = store
    app["manifests"] = manifests

    async def put_block(request: web.Request) -> web.Response:
        h = int(request.match_info["hash"])
        store.put_calls += 1
        store.put(h, await request.read())
        return web.json_response({"status": "ok"})

    async def put_blocks(request: web.Request) -> web.Response:
        """Batched put: N pages, one round trip (docs/disagg.md)."""
        store.put_calls += 1
        try:
            pages = unpack_blocks(await request.read())
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        for h, data in pages:
            store.put(h, data)
        return web.json_response({"status": "ok", "stored": len(pages)})

    async def get_block(request: web.Request) -> web.Response:
        if "hashes" in request.query:
            return await get_blocks(request)
        store.get_calls += 1
        data = store.get(int(request.match_info["hash"]))
        if data is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(body=data, content_type="application/octet-stream")

    async def get_blocks(request: web.Request) -> web.Response:
        """Batched get: ``?hashes=h1,h2`` → framed body of present pages
        (absent hashes simply omitted; the caller diffs)."""
        store.get_calls += 1
        try:
            hashes = [
                int(h) for h in request.query.get("hashes", "").split(",") if h
            ]
        except ValueError:
            return web.json_response(
                {"error": "hashes must be integers"}, status=400
            )
        pages = []
        for h in hashes:
            data = store.get(h)
            if data is not None:
                pages.append((h, data))
        return web.Response(
            body=pack_blocks(pages),
            content_type="application/octet-stream",
            headers={"X-PST-Blocks": str(len(pages))},
        )

    async def post_manifest(request: web.Request) -> web.Response:
        rid = request.match_info["rid"]
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — malformed update
            return web.json_response({"error": "invalid JSON"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be an object"},
                                     status=400)
        try:
            hashes = [int(h) for h in body.get("hashes") or []]
            total = body.get("total_blocks")
            total = int(total) if total is not None else None
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "hashes/total_blocks must be integers"}, status=400
            )
        m = manifests.update(rid, hashes, bool(body.get("complete")), total)
        return web.json_response(
            {"status": "ok", "blocks": len(m["hashes"]),
             "complete": m["complete"]}
        )

    async def get_manifest(request: web.Request) -> web.Response:
        rid = request.match_info["rid"]
        try:
            wait_s = float(request.query.get("wait_s", 0))
            have = int(request.query.get("have", -1))
        except ValueError:
            return web.json_response(
                {"error": "wait_s/have must be numbers"}, status=400
            )
        if wait_s > 0:
            view = await manifests.wait(rid, have, min(wait_s, 30.0))
        else:
            view = manifests.view(rid)
        if view is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(view)

    async def contains(request: web.Request) -> web.Response:
        body = await request.json()
        return web.json_response(
            {"present": [store.contains(int(h)) for h in body.get("hashes", [])]}
        )

    async def stats(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "num_blocks": len(store._blocks),
                "bytes_used": store.bytes_used,
                "max_bytes": store.max_bytes,
                "hits": store.hits,
                "misses": store.misses,
                "evictions": store.evictions,
                "put_calls": store.put_calls,
                "blocks_put": store.blocks_put,
                "get_calls": store.get_calls,
                "manifests": len(manifests),
            }
        )

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    app.router.add_post("/blocks", put_blocks)
    app.router.add_get("/blocks", get_blocks)
    app.router.add_put("/blocks/{hash}", put_block)
    app.router.add_get("/blocks/{hash}", get_block)
    app.router.add_post("/manifests/{rid}", post_manifest)
    app.router.add_get("/manifests/{rid}", get_manifest)
    app.router.add_post("/contains", contains)
    app.router.add_get("/stats", stats)
    app.router.add_get("/health", health)
    return app


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="production-stack-tpu remote KV store")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--max-bytes", type=int, default=8 << 30)
    args = p.parse_args(argv)
    web.run_app(
        create_kv_server_app(args.max_bytes),
        host=args.host, port=args.port, access_log=None,
    )


if __name__ == "__main__":
    main()
