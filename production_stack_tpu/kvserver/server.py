"""Remote KV block store (`pst-kv-server`) — the LMCache-server analogue.

Reference: the cache-server Deployment running `lmcache_experimental_server`
(`helm/templates/deployment-cache-server.yaml:31-43`), which engines reach
over TCP with a serde format. Here: an aiohttp server speaking the page serde
of :mod:`production_stack_tpu.engine.cache_tiering` over HTTP (TCP/DCN), with
a byte-capacity LRU.

Endpoints:
  PUT  /blocks/{hash}     store one page (raw serde body; optional
                          ``X-PST-Digest`` header, verified at ingest)
  GET  /blocks/{hash}     fetch one page (404 if absent; the stored digest
                          rides back in ``X-PST-Digest``)
  POST /blocks            store N pages in ONE round trip (framed body)
  GET  /blocks?hashes=    fetch N pages in ONE round trip (framed body;
                          absent hashes are simply omitted from the reply)
  POST /manifests/{rid}   append a disagg-transfer manifest update
  GET  /manifests/{rid}   read a manifest (``?wait_s=`` long-polls for
                          progress past ``?have=`` blocks / completion)
  POST /contains          presence probe for N hashes (read-repair and the
                          anti-entropy sweep key on this)
  POST /admin/quarantine  drop named blocks (a client that detected a
                          digest mismatch evicts THIS replica's copy)
  POST /admin/fail        fault injection: ``corrupt`` | ``slow`` |
                          ``drop_manifest`` (chaos legs + bench)
  POST /admin/heal        clear injected faults
  GET  /ring              this shard's view of the ring (peers,
                          replication, sweep interval)
  GET  /stats             occupancy/bytes/hit/integrity counters
  GET  /health

The framed batch body is ``repeat([8B hash LE][4B length LE][16B blake2b
digest][payload])`` — hash keys are the engine-side block hashes (which
key the *token ids*, not the bytes), payloads are the page serde, and the
digest is BLAKE2b-128 over the payload bytes. The digest is computed by
the producer at pack time, stored verbatim, and served verbatim: a replica
whose copy rotted (or a fault-injected corruption) is detected by the
*reader*, because recomputing the digest server-side at serve time would
launder storage corruption into a "valid" frame. docs/kvserver.md.

Manifests (docs/disagg.md "Manifest protocol"): the streamed prefill→decode
KV handoff is coordinated by a request-id-keyed manifest. The prefill engine
appends the block-hash list as each prefill chunk's pages are published, and
posts ``complete`` with ``total_blocks`` when the prefill pass finishes; the
decode engine long-polls the manifest and batch-fetches published blocks
while the prefill is still running — transfer overlapped with compute.
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

from aiohttp import web

from ..logging_utils import init_logger

logger = init_logger(__name__)

# Manifests older than this are dropped (a crashed decode leg must not pin
# its prefill's manifest forever); sized generously above any request
# deadline the router would still be waiting on.
MANIFEST_TTL_S = 10 * 60.0
MANIFEST_CAP = 4096

# BLAKE2b digest width carried per frame. 128 bits: collision-irrelevant
# (integrity check, not addressing) and 16 bytes of overhead on multi-KiB
# page payloads.
DIGEST_SIZE = 16
_FRAME_HEADER = 8 + 4 + DIGEST_SIZE

# Blocks examined per anti-entropy pass: bounds one sweep's /contains +
# re-push work on a full shard so the sweep never monopolizes the loop.
SWEEP_SAMPLE_BLOCKS = 2048


def block_digest(data: bytes) -> bytes:
    """BLAKE2b-128 over the page serde bytes — the end-to-end integrity
    token every framed block carries (computed where the bytes are born,
    verified wherever they are consumed)."""
    return hashlib.blake2b(data, digest_size=DIGEST_SIZE).digest()


def pack_blocks(pages: Sequence[tuple]) -> bytes:
    """Frame N pages into one batch body.

    Items are ``(hash, payload)`` — the digest is computed here — or
    ``(hash, payload, digest)`` for callers re-shipping stored frames
    (read-repair, the anti-entropy sweep) where the ORIGINAL producer
    digest must travel, not a fresh one over possibly-rotted bytes.
    """
    parts = []
    for page in pages:
        if len(page) == 3:
            h, data, digest = page
        else:
            h, data = page
            digest = block_digest(data)
        parts.append(int(h).to_bytes(8, "little", signed=False))
        parts.append(len(data).to_bytes(4, "little"))
        parts.append(digest)
        parts.append(data)
    return b"".join(parts)


def unpack_blocks_ex(
    buf: bytes, corrupt: Optional[List[int]] = None
) -> List[Tuple[int, bytes, bytes]]:
    """Inverse of :func:`pack_blocks`, digest-verified.

    Raises ValueError on a torn frame. A digest mismatch raises too —
    unless ``corrupt`` is given, in which case the bad block's hash is
    appended there and the block is *skipped* (client read paths: the
    caller quarantines that replica's copy and fails over; a corrupt page
    must never reach decode, docs/kvserver.md).
    """
    out: List[Tuple[int, bytes, bytes]] = []
    off = 0
    n = len(buf)
    while off < n:
        if off + _FRAME_HEADER > n:
            raise ValueError("torn batch frame header")
        h = int.from_bytes(buf[off : off + 8], "little")
        ln = int.from_bytes(buf[off + 8 : off + 12], "little")
        digest = buf[off + 12 : off + _FRAME_HEADER]
        off += _FRAME_HEADER
        if off + ln > n:
            raise ValueError("torn batch frame payload")
        data = buf[off : off + ln]
        off += ln
        if block_digest(data) != digest:
            if corrupt is None:
                raise ValueError(f"digest mismatch for block {h}")
            corrupt.append(h)
            continue
        out.append((h, data, digest))
    return out


def unpack_blocks(
    buf: bytes, corrupt: Optional[List[int]] = None
) -> List[Tuple[int, bytes]]:
    """:func:`unpack_blocks_ex` without the digest column (most callers
    only need the verified payloads)."""
    return [(h, data) for h, data, _ in unpack_blocks_ex(buf, corrupt)]


class BlockStore:
    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._blocks: "collections.OrderedDict[int, Tuple[bytes, bytes]]" = (
            collections.OrderedDict()
        )
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Transfer-audit counters (docs/disagg.md): distinguish HTTP round
        # trips from pages moved, so tests can assert the streamed handoff
        # ships each page ONCE and batches N pages per trip.
        self.put_calls = 0
        self.blocks_put = 0
        self.get_calls = 0
        # Integrity-audit counters (docs/kvserver.md): ingest-side digest
        # rejects and client-reported quarantines.
        self.integrity_rejects = 0
        self.quarantined = 0

    def put(self, h: int, data: bytes, digest: Optional[bytes] = None) -> None:
        self.blocks_put += 1
        if len(data) > self.max_bytes:
            return  # unstorable; never evict the fleet's cache trying
        if digest is None:
            digest = block_digest(data)
        if h in self._blocks:
            self.bytes_used -= len(self._blocks.pop(h)[0])
        while self._blocks and self.bytes_used + len(data) > self.max_bytes:
            _, (old, _d) = self._blocks.popitem(last=False)
            self.bytes_used -= len(old)
            self.evictions += 1
        self._blocks[h] = (data, digest)
        self.bytes_used += len(data)

    def get(self, h: int) -> Optional[bytes]:
        item = self.get_with_digest(h)
        return None if item is None else item[0]

    def get_with_digest(self, h: int) -> Optional[Tuple[bytes, bytes]]:
        item = self._blocks.get(h)
        if item is None:
            self.misses += 1
            return None
        self._blocks.move_to_end(h)
        self.hits += 1
        return item

    def contains(self, h: int) -> bool:
        return h in self._blocks

    def quarantine(self, hashes: Sequence[int]) -> int:
        """Drop named blocks (a reader detected a digest mismatch on this
        replica's copy). Returns how many were actually present."""
        dropped = 0
        for h in hashes:
            item = self._blocks.pop(int(h), None)
            if item is not None:
                self.bytes_used -= len(item[0])
                dropped += 1
        self.quarantined += dropped
        return dropped

    def sample_hashes(self, limit: int) -> List[int]:
        """Up to ``limit`` most-recently-used block hashes (the
        anti-entropy sweep's working set — hot blocks first, bounded)."""
        return list(reversed(self._blocks.keys()))[:limit]


class FaultState:
    """Injected-fault state (POST /admin/fail; docs/kvserver.md).

    ``corrupt``: flip a byte in each *served* block payload (the stored
    digest still rides along, so readers detect the damage — this is the
    rotted-replica simulation). ``slow``: delay every block/manifest
    handler by ``delay_s``. ``drop_manifest``: acknowledge manifest
    appends but discard them (the consumer's long-poll starves into the
    fused fallback). ``count`` bounds how many operations are affected
    (<= 0 = until /admin/heal), mirroring the fake engine's fault surface.
    """

    def __init__(self) -> None:
        self.mode: Optional[str] = None
        self.remaining = 0
        self.delay_s = 0.25
        self.injected = 0

    def arm(self, mode: str, count: int, delay_s: float) -> None:
        self.mode = mode
        self.remaining = count
        self.delay_s = delay_s

    def heal(self) -> None:
        self.mode = None
        self.remaining = 0

    def take(self, mode: str) -> bool:
        """Consume one fault of ``mode`` if armed; False otherwise."""
        if self.mode != mode:
            return False
        if self.remaining > 0:
            self.remaining -= 1
            if self.remaining == 0:
                self.mode = None
        self.injected += 1
        return True


def _flip_byte(data: bytes) -> bytes:
    if not data:
        return data
    i = len(data) // 2
    return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1 :]


class ManifestStore:
    """Request-id-keyed disagg-transfer manifests with change signaling."""

    def __init__(self):
        self._manifests: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self._events: Dict[str, asyncio.Event] = {}

    def _prune(self, now: float) -> None:
        cutoff = now - MANIFEST_TTL_S
        stale = [
            rid for rid, m in self._manifests.items() if m["ts"] < cutoff
        ]
        for rid in stale:
            self._manifests.pop(rid, None)
            self._events.pop(rid, None)
        while len(self._manifests) > MANIFEST_CAP:
            rid, _ = self._manifests.popitem(last=False)
            self._events.pop(rid, None)
        if len(self._events) > 2 * MANIFEST_CAP:
            # Events registered by pollers whose manifest never arrived
            # (producer crashed / transfer fault) are not covered by the
            # manifest-keyed pruning above — bound them separately.
            self._events = {
                rid: ev for rid, ev in self._events.items()
                if rid in self._manifests
            }

    def update(
        self,
        rid: str,
        hashes: List[int],
        complete: bool,
        total_blocks: Optional[int],
    ) -> dict:
        now = time.time()
        self._prune(now)
        m = self._manifests.get(rid)
        if m is None:
            m = {"hashes": [], "complete": False, "total_blocks": None,
                 "ts": now}
            self._manifests[rid] = m
        seen = set(m["hashes"])
        for h in hashes:
            if h not in seen:
                m["hashes"].append(int(h))
                seen.add(h)
        if complete:
            m["complete"] = True
        if total_blocks is not None:
            m["total_blocks"] = int(total_blocks)
        m["ts"] = now
        # Every producer append refreshes the manifest's eviction rank as
        # well as its TTL: cap-pressure eviction pops the LRU end, and
        # without the move an actively-streaming transfer created early
        # (a slow, long prefill) was the FIRST thing 4096 younger
        # manifests pushed out — its consumer saw the manifest vanish
        # mid-prefill and timed out the whole transfer into a recompute
        # (tests/test_kvserver_ring.py::test_manifest_active_survives_cap).
        self._manifests.move_to_end(rid)
        # Re-check the cap after the insert: pruning only before it would
        # leave the store sitting one over between updates. ``rid`` was
        # just moved to the MRU end, so it can never be its own evictee.
        while len(self._manifests) > MANIFEST_CAP:
            evict, _ = self._manifests.popitem(last=False)
            self._events.pop(evict, None)
        ev = self._events.get(rid)
        if ev is not None:
            ev.set()
        return m

    def view(self, rid: str) -> Optional[dict]:
        m = self._manifests.get(rid)
        if m is None:
            return None
        return {
            "request_id": rid,
            "hashes": list(m["hashes"]),
            "complete": m["complete"],
            "total_blocks": m["total_blocks"],
        }

    async def wait(self, rid: str, have: int, wait_s: float) -> Optional[dict]:
        """Long-poll: return as soon as the manifest has more than ``have``
        blocks or is complete, else after ``wait_s``."""
        deadline = time.monotonic() + max(wait_s, 0.0)
        try:
            while True:
                # Clear BEFORE checking: an update() that lands between
                # the manifest check and the wait sets the event and must
                # not be erased, or the poll stalls a full wait cycle.
                ev = self._events.setdefault(rid, asyncio.Event())
                ev.clear()
                m = self._manifests.get(rid)
                if m is not None and (
                    len(m["hashes"]) > have or m["complete"]
                ):
                    return self.view(rid)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self.view(rid)
                try:
                    await asyncio.wait_for(
                        ev.wait(), timeout=min(remaining, 1.0)
                    )
                except asyncio.TimeoutError:
                    pass
        finally:
            if rid not in self._manifests:
                # This poller registered the event for a manifest that
                # never arrived (producer crashed / transfer fault): drop
                # it, or every failed transfer would leak one Event.
                self._events.pop(rid, None)

    def __len__(self) -> int:
        return len(self._manifests)


def create_kv_server_app(
    max_bytes: int = 8 << 30,
    peers: Optional[Sequence[str]] = None,
    self_url: Optional[str] = None,
    replication: int = 2,
    sweep_interval_s: float = 0.0,
) -> web.Application:
    """One kvserver shard.

    ``peers`` (every shard's base URL, this one included, as the clients
    address them) + ``self_url`` make the shard ring-aware: it can answer
    GET /ring and run the anti-entropy sweep — every ``sweep_interval_s``
    it samples its hottest blocks, computes each block's owner set over
    the shared consistent-hash ring, probes co-owners with POST /contains
    and re-pushes missing replicas (stored digests travel verbatim). A
    restarted-empty shard is thus backfilled by its peers within one
    sweep interval, complementing the client-side read-repair that heals
    on demand. Without ``peers`` the shard behaves exactly as before.
    """
    store = BlockStore(max_bytes)
    manifests = ManifestStore()
    faults = FaultState()
    peer_list = [p.rstrip("/") for p in (peers or []) if p]
    app = web.Application(client_max_size=256 << 20)
    app["store"] = store
    app["manifests"] = manifests
    app["faults"] = faults
    app["peers"] = peer_list
    app["self_url"] = (self_url or "").rstrip("/")
    app["replication"] = max(int(replication), 1)
    app["sweep_interval_s"] = float(sweep_interval_s)
    app["anti_entropy_pushes"] = 0
    app["anti_entropy_sweeps"] = 0

    async def _maybe_slow() -> None:
        if faults.take("slow"):
            await asyncio.sleep(faults.delay_s)

    def _served(h: int, data: bytes, digest: bytes) -> Tuple[bytes, bytes]:
        """Apply the ``corrupt`` fault to one outgoing block: the payload
        is damaged but the STORED digest still rides along — exactly what
        a rotted replica looks like to a verifying reader."""
        if faults.take("corrupt"):
            return _flip_byte(data), digest
        return data, digest

    async def put_block(request: web.Request) -> web.Response:
        await _maybe_slow()
        h = int(request.match_info["hash"])
        store.put_calls += 1
        data = await request.read()
        digest: Optional[bytes] = None
        header = request.headers.get("X-PST-Digest")
        if header:
            try:
                digest = bytes.fromhex(header)
            except ValueError:
                return web.json_response(
                    {"error": "X-PST-Digest must be hex"}, status=400
                )
            if block_digest(data) != digest:
                store.integrity_rejects += 1
                return web.json_response(
                    {"error": "digest mismatch"}, status=400
                )
        store.put(h, data, digest)
        return web.json_response({"status": "ok"})

    async def put_blocks(request: web.Request) -> web.Response:
        """Batched put: N pages, one round trip (docs/disagg.md). Frames
        are digest-verified at ingest — a block corrupted in flight is
        rejected here (400) instead of poisoning a replica."""
        await _maybe_slow()
        store.put_calls += 1
        try:
            pages = unpack_blocks_ex(await request.read())
        except ValueError as e:
            store.integrity_rejects += 1
            return web.json_response({"error": str(e)}, status=400)
        for h, data, digest in pages:
            store.put(h, data, digest)
        return web.json_response({"status": "ok", "stored": len(pages)})

    async def get_block(request: web.Request) -> web.Response:
        if "hashes" in request.query:
            return await get_blocks(request)
        await _maybe_slow()
        store.get_calls += 1
        item = store.get_with_digest(int(request.match_info["hash"]))
        if item is None:
            return web.json_response({"error": "not found"}, status=404)
        data, digest = _served(int(request.match_info["hash"]), *item)
        return web.Response(
            body=data,
            content_type="application/octet-stream",
            headers={"X-PST-Digest": digest.hex()},
        )

    async def get_blocks(request: web.Request) -> web.Response:
        """Batched get: ``?hashes=h1,h2`` → framed body of present pages
        (absent hashes simply omitted; the caller diffs)."""
        await _maybe_slow()
        store.get_calls += 1
        try:
            hashes = [
                int(h) for h in request.query.get("hashes", "").split(",") if h
            ]
        except ValueError:
            return web.json_response(
                {"error": "hashes must be integers"}, status=400
            )
        pages = []
        for h in hashes:
            item = store.get_with_digest(h)
            if item is not None:
                data, digest = _served(h, *item)
                pages.append((h, data, digest))
        return web.Response(
            body=pack_blocks(pages),
            content_type="application/octet-stream",
            headers={"X-PST-Blocks": str(len(pages))},
        )

    async def post_manifest(request: web.Request) -> web.Response:
        await _maybe_slow()
        rid = request.match_info["rid"]
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 — malformed update
            return web.json_response({"error": "invalid JSON"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be an object"},
                                     status=400)
        try:
            hashes = [int(h) for h in body.get("hashes") or []]
            total = body.get("total_blocks")
            total = int(total) if total is not None else None
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "hashes/total_blocks must be integers"}, status=400
            )
        if faults.take("drop_manifest"):
            # Acknowledged but discarded: the producer believes the append
            # landed while the consumer's long-poll starves — the
            # slow-prefill manifest-loss failure mode, on demand.
            return web.json_response(
                {"status": "ok", "blocks": 0, "complete": False}
            )
        m = manifests.update(rid, hashes, bool(body.get("complete")), total)
        return web.json_response(
            {"status": "ok", "blocks": len(m["hashes"]),
             "complete": m["complete"]}
        )

    async def get_manifest(request: web.Request) -> web.Response:
        await _maybe_slow()
        rid = request.match_info["rid"]
        try:
            wait_s = float(request.query.get("wait_s", 0))
            have = int(request.query.get("have", -1))
        except ValueError:
            return web.json_response(
                {"error": "wait_s/have must be numbers"}, status=400
            )
        if wait_s > 0:
            view = await manifests.wait(rid, have, min(wait_s, 30.0))
        else:
            view = manifests.view(rid)
        if view is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(view)

    async def contains(request: web.Request) -> web.Response:
        body = await request.json()
        return web.json_response(
            {"present": [store.contains(int(h)) for h in body.get("hashes", [])]}
        )

    async def quarantine(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            hashes = [int(h) for h in body.get("hashes") or []]
        except Exception:  # noqa: BLE001 — malformed quarantine request
            return web.json_response({"error": "invalid body"}, status=400)
        dropped = store.quarantine(hashes)
        logger.warning(
            "quarantined %d/%d blocks on reader-reported digest mismatch",
            dropped, len(hashes),
        )
        return web.json_response({"status": "ok", "dropped": dropped})

    async def admin_fail(request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001
            body = {}
        mode = body.get("mode")
        if mode not in ("corrupt", "slow", "drop_manifest"):
            return web.json_response(
                {"error": "mode must be corrupt|slow|drop_manifest"},
                status=400,
            )
        faults.arm(
            mode,
            int(body.get("count", 0)),
            float(body.get("delay_s", 0.25)),
        )
        return web.json_response({"status": "ok", "mode": mode})

    async def admin_heal(request: web.Request) -> web.Response:
        faults.heal()
        return web.json_response({"status": "ok"})

    async def ring(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "peers": app["peers"],
                "self": app["self_url"],
                "replication": app["replication"],
                "sweep_interval_s": app["sweep_interval_s"],
            }
        )

    async def stats(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "num_blocks": len(store._blocks),
                "bytes_used": store.bytes_used,
                "max_bytes": store.max_bytes,
                "hits": store.hits,
                "misses": store.misses,
                "evictions": store.evictions,
                "put_calls": store.put_calls,
                "blocks_put": store.blocks_put,
                "get_calls": store.get_calls,
                "manifests": len(manifests),
                "integrity_rejects": store.integrity_rejects,
                "quarantined": store.quarantined,
                "faults_injected": faults.injected,
                "anti_entropy_sweeps": app["anti_entropy_sweeps"],
                "anti_entropy_pushes": app["anti_entropy_pushes"],
            }
        )

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    app.router.add_post("/blocks", put_blocks)
    app.router.add_get("/blocks", get_blocks)
    app.router.add_put("/blocks/{hash}", put_block)
    app.router.add_get("/blocks/{hash}", get_block)
    app.router.add_post("/manifests/{rid}", post_manifest)
    app.router.add_get("/manifests/{rid}", get_manifest)
    app.router.add_post("/contains", contains)
    app.router.add_post("/admin/quarantine", quarantine)
    app.router.add_post("/admin/fail", admin_fail)
    app.router.add_post("/admin/heal", admin_heal)
    app.router.add_get("/ring", ring)
    app.router.add_get("/stats", stats)
    app.router.add_get("/health", health)

    if peer_list and app["self_url"] and app["sweep_interval_s"] > 0:
        app.cleanup_ctx.append(_anti_entropy_ctx)
    return app


async def _sweep_once(app: web.Application, session) -> int:
    """One anti-entropy pass: for each sampled local block whose owner set
    includes a peer missing it, re-push the stored frame (original digest)
    there. Returns blocks pushed; every per-peer failure is swallowed —
    a down peer is exactly the situation the sweep exists to heal later."""
    from ..hashring import ConsistentHashRing

    store: BlockStore = app["store"]
    self_url: str = app["self_url"]
    replication: int = app["replication"]
    ring = ConsistentHashRing()
    ring.update(app["peers"])
    # Owner sets per sampled block; only blocks this shard co-owns matter
    # (a block left here by an old ring epoch still serves reads via the
    # clients' ring-order failover walk).
    by_peer: Dict[str, List[int]] = collections.defaultdict(list)
    for h in store.sample_hashes(SWEEP_SAMPLE_BLOCKS):
        owners = ring.get_nodes(str(h), replication)
        if self_url not in owners:
            continue
        for o in owners:
            if o != self_url:
                by_peer[o].append(h)
    pushed = 0
    for peer, hashes in by_peer.items():
        try:
            async with session.post(
                f"{peer}/contains", json={"hashes": hashes}
            ) as r:
                if r.status != 200:
                    continue
                present = (await r.json()).get("present") or []
        except Exception:  # noqa: BLE001 — peer down; next sweep retries
            continue
        missing = [
            h for h, there in zip(hashes, present) if not there
        ]
        if not missing:
            continue
        frames = []
        for h in missing:
            item = store.get_with_digest(h)
            if item is not None:
                frames.append((h, item[0], item[1]))
        if not frames:
            continue
        try:
            async with session.post(
                f"{peer}/blocks", data=pack_blocks(frames)
            ) as r:
                if r.status == 200:
                    pushed += len(frames)
        except Exception:  # noqa: BLE001
            continue
    return pushed


async def _anti_entropy_ctx(app: web.Application):
    import aiohttp

    async def _loop() -> None:
        timeout = aiohttp.ClientTimeout(total=10.0)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            while True:
                await asyncio.sleep(app["sweep_interval_s"])
                try:
                    app["anti_entropy_pushes"] += await _sweep_once(
                        app, session
                    )
                except Exception as e:  # noqa: BLE001 — sweep must survive
                    logger.debug("anti-entropy sweep failed: %s", e)
                app["anti_entropy_sweeps"] += 1

    task = asyncio.create_task(_loop(), name="kv-anti-entropy")
    yield
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="production-stack-tpu remote KV store")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--max-bytes", type=int, default=8 << 30)
    p.add_argument("--peers", default=None,
                   help="comma-separated base URLs of EVERY ring shard "
                        "(this one included) — enables GET /ring and the "
                        "anti-entropy sweep")
    p.add_argument("--self-url", default=None,
                   help="this shard's own base URL as it appears in "
                        "--peers")
    p.add_argument("--replication", type=int, default=2,
                   help="replicas per block the ring places (must match "
                        "the engines' --kv-replication)")
    p.add_argument("--sweep-interval-s", type=float, default=30.0,
                   help="seconds between anti-entropy passes (0 disables; "
                        "effective only with --peers/--self-url)")
    args = p.parse_args(argv)
    peers = [u for u in (args.peers or "").split(",") if u]
    web.run_app(
        create_kv_server_app(
            args.max_bytes,
            peers=peers,
            self_url=args.self_url,
            replication=args.replication,
            sweep_interval_s=args.sweep_interval_s,
        ),
        host=args.host, port=args.port, access_log=None,
    )


if __name__ == "__main__":
    main()
