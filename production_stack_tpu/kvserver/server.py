"""Remote KV block store (`pst-kv-server`) — the LMCache-server analogue.

Reference: the cache-server Deployment running `lmcache_experimental_server`
(`helm/templates/deployment-cache-server.yaml:31-43`), which engines reach
over TCP with a serde format. Here: an aiohttp server speaking the page serde
of :mod:`production_stack_tpu.engine.cache_tiering` over HTTP (TCP/DCN), with
a byte-capacity LRU.

Endpoints:
  PUT  /blocks/{hash}     store one page (raw serde body)
  GET  /blocks/{hash}     fetch one page (404 if absent)
  POST /contains          {"hashes": [...]} → {"present": [bool, ...]}
  GET  /stats             occupancy/bytes/hit counters
  GET  /health
"""

from __future__ import annotations

import argparse
import collections
from typing import Optional

from aiohttp import web

from ..logging_utils import init_logger

logger = init_logger(__name__)


class BlockStore:
    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._blocks: "collections.OrderedDict[int, bytes]" = collections.OrderedDict()
        self.bytes_used = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def put(self, h: int, data: bytes) -> None:
        if len(data) > self.max_bytes:
            return  # unstorable; never evict the fleet's cache trying
        if h in self._blocks:
            self.bytes_used -= len(self._blocks.pop(h))
        while self._blocks and self.bytes_used + len(data) > self.max_bytes:
            _, old = self._blocks.popitem(last=False)
            self.bytes_used -= len(old)
            self.evictions += 1
        self._blocks[h] = data
        self.bytes_used += len(data)

    def get(self, h: int) -> Optional[bytes]:
        data = self._blocks.get(h)
        if data is None:
            self.misses += 1
            return None
        self._blocks.move_to_end(h)
        self.hits += 1
        return data

    def contains(self, h: int) -> bool:
        return h in self._blocks


def create_kv_server_app(max_bytes: int = 8 << 30) -> web.Application:
    store = BlockStore(max_bytes)
    app = web.Application(client_max_size=256 << 20)
    app["store"] = store

    async def put_block(request: web.Request) -> web.Response:
        h = int(request.match_info["hash"])
        store.put(h, await request.read())
        return web.json_response({"status": "ok"})

    async def get_block(request: web.Request) -> web.Response:
        data = store.get(int(request.match_info["hash"]))
        if data is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.Response(body=data, content_type="application/octet-stream")

    async def contains(request: web.Request) -> web.Response:
        body = await request.json()
        return web.json_response(
            {"present": [store.contains(int(h)) for h in body.get("hashes", [])]}
        )

    async def stats(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "num_blocks": len(store._blocks),
                "bytes_used": store.bytes_used,
                "max_bytes": store.max_bytes,
                "hits": store.hits,
                "misses": store.misses,
                "evictions": store.evictions,
            }
        )

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    app.router.add_put("/blocks/{hash}", put_block)
    app.router.add_get("/blocks/{hash}", get_block)
    app.router.add_post("/contains", contains)
    app.router.add_get("/stats", stats)
    app.router.add_get("/health", health)
    return app


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="production-stack-tpu remote KV store")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--max-bytes", type=int, default=8 << 30)
    args = p.parse_args(argv)
    web.run_app(
        create_kv_server_app(args.max_bytes),
        host=args.host, port=args.port, access_log=None,
    )


if __name__ == "__main__":
    main()
