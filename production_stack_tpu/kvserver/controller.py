"""Cache controller (`pst-kv-controller`): fleet-wide KV location index.

The role LMCache's controller plays for the reference's KV-aware routing
(`routing_logic.py:287-299` sends a `LookupMsg`; the Go picker hits `/lookup`
HTTP — `kv_aware_picker.go:92-133`). Engines periodically report the chunk
hashes their caches hold; the router asks which engine holds the longest
prefix of a prompt's chunk hashes.

Endpoints:
  POST /register    {"url", "model", "hashes": [...], "replace": bool}
  POST /deregister  {"url"}
  POST /lookup      {"model", "hashes": [...]} →
                    {"matches": {url: matched_token_count}}
  GET  /instances   debug listing
  GET  /health

Matching walks the prompt's chunk-hash chain in order and counts consecutive
chunks present per engine — chunk hashes commit to their full prefix
(kvcache/hashing.py), so presence of chunk i implies content-equality of
everything before it.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import time
from typing import Dict, Set

from aiohttp import web

from ..kvcache.hashing import CHUNK_TOKENS
from ..logging_utils import init_logger
from ..obs.tasks import spawn_owned

logger = init_logger(__name__)


class ControllerState:
    def __init__(self, instance_ttl: float = 120.0):
        # model -> url -> set(chunk hashes)
        self.instances: Dict[str, Dict[str, Set[int]]] = {}
        self.last_seen: Dict[str, float] = {}
        self.instance_ttl = instance_ttl

    def register(self, url: str, model: str, hashes, replace: bool) -> None:
        per_model = self.instances.setdefault(model, {})
        if replace or url not in per_model:
            per_model[url] = set()
        per_model[url].update(int(h) for h in hashes)
        self.last_seen[url] = time.time()

    def deregister(self, url: str) -> None:
        for per_model in self.instances.values():
            per_model.pop(url, None)
        self.last_seen.pop(url, None)

    def expire(self) -> None:
        cutoff = time.time() - self.instance_ttl
        stale = [u for u, t in self.last_seen.items() if t < cutoff]
        for u in stale:
            self.deregister(u)

    def lookup(self, model: str, hashes) -> Dict[str, int]:
        self.expire()
        per_model = self.instances.get(model) or {}
        matches: Dict[str, int] = {}
        for url, have in per_model.items():
            n = 0
            for h in hashes:
                if int(h) in have:
                    n += 1
                else:
                    break
            if n:
                matches[url] = n * CHUNK_TOKENS
        return matches


def create_controller_app(instance_ttl: float = 120.0) -> web.Application:
    state = ControllerState(instance_ttl)
    app = web.Application()
    app["state"] = state

    async def register(request: web.Request) -> web.Response:
        body = await request.json()
        state.register(
            body["url"],
            body.get("model", ""),
            body.get("hashes", []),
            bool(body.get("replace", False)),
        )
        return web.json_response({"status": "ok"})

    async def deregister(request: web.Request) -> web.Response:
        body = await request.json()
        state.deregister(body["url"])
        return web.json_response({"status": "ok"})

    async def lookup(request: web.Request) -> web.Response:
        body = await request.json()
        hashes = body.get("hashes")
        if not hashes and body.get("text"):
            # Gateway pickers hold raw text, not token ids: byte-tokenize
            # (the fleet-wide fallback tokenizer) and chunk-hash here so the
            # C++ picker needs no tokenizer of its own.
            from ..engine.tokenizer import ByteTokenizer
            from ..kvcache.hashing import chunk_hashes

            ids = ByteTokenizer().encode(body["text"])
            hashes = chunk_hashes(ids)
        matches = state.lookup(body.get("model", ""), hashes or [])
        return web.json_response({"matches": matches})

    async def instances(request: web.Request) -> web.Response:
        # Expire here too: lookup() used to be the only caller of expire(),
        # so engines that deregistered-but-were-never-looked-up kept dead
        # URLs alive in this listing indefinitely.
        state.expire()
        return web.json_response(
            {
                model: {url: len(hashes) for url, hashes in per_model.items()}
                for model, per_model in state.instances.items()
            }
        )

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    app.router.add_post("/register", register)
    app.router.add_post("/deregister", deregister)
    app.router.add_post("/lookup", lookup)
    app.router.add_get("/instances", instances)
    app.router.add_get("/health", health)

    async def _expire_loop(app: web.Application) -> None:
        # Periodic expiry so stale engines age out even with zero traffic
        # (lookups and /instances both expire inline, but an idle
        # controller should not hold dead URLs for days).
        interval = max(1.0, instance_ttl / 2)
        while True:
            await asyncio.sleep(interval)
            state.expire()

    async def _start_expiry(app: web.Application) -> None:
        app["expire_task"] = spawn_owned(_expire_loop(app), name="kv-controller-expiry")

    async def _stop_expiry(app: web.Application) -> None:
        task = app.get("expire_task")
        if task is not None:
            task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await task

    app.on_startup.append(_start_expiry)
    app.on_cleanup.append(_stop_expiry)
    return app


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="production-stack-tpu KV cache controller")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--instance-ttl", type=float, default=120.0)
    args = p.parse_args(argv)
    web.run_app(
        create_controller_app(args.instance_ttl),
        host=args.host, port=args.port, access_log=None,
    )


if __name__ == "__main__":
    main()
