from .mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_PIPELINE,
    AXIS_SEQUENCE,
    AXIS_TENSOR,
    MeshConfig,
    build_mesh,
    local_mesh,
)
