"""Multi-host runtime: process boot, host-0 serving, control-plane broadcast.

The reference serves multi-host models by wrapping vLLM in a Ray cluster
(`helm/templates/ray-cluster.yaml:3-15,520,560-566`): the head pod runs the
HTTP server, workers join via Ray, NCCL carries tensors. TPU-native there is
no Ray: every host runs the *same* SPMD program under ``jax.distributed``,
XLA moves tensors over ICI/DCN, and the only extra machinery needed is a
small control plane:

- :func:`maybe_init_distributed` — ``jax.distributed.initialize`` from env
  (K8s JobSet/LeaderWorkerSet downward-API env vars; see
  ``helm/templates/multihost-engine.yaml``).
- :func:`is_primary` — host 0 binds the OpenAI HTTP server; other hosts run
  the follower loop (`run_follower` in ``engine.multihost``), mirroring the
  "vllm serve on head" split of ``ray-cluster.yaml:520``.
- :class:`HostBridge` — broadcasts per-step batch descriptions from host 0 to
  all hosts so every process enters the same jitted computation. Payloads are
  pickled and length-prefixed over ``multihost_utils.broadcast_one_to_all``
  (a DCN all-reduce under the hood) — the TPU replacement for Ray RPC.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

from ..logging_utils import init_logger

logger = init_logger(__name__)

# Env surface (set by the Helm multi-host template / JobSet downward API).
ENV_COORDINATOR = "PST_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "PST_NUM_PROCESSES"
ENV_PROCESS_ID = "PST_PROCESS_ID"

_initialized = False


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0

    @classmethod
    def from_env(cls) -> "DistributedConfig":
        return cls(
            coordinator_address=os.environ.get(ENV_COORDINATOR),
            num_processes=int(os.environ.get(ENV_NUM_PROCESSES, "1")),
            process_id=int(os.environ.get(ENV_PROCESS_ID, "0")),
        )

    @property
    def enabled(self) -> bool:
        return self.num_processes > 1


def maybe_init_distributed(cfg: Optional[DistributedConfig] = None) -> bool:
    """Boot the JAX distributed runtime when configured. Idempotent.

    Returns True when running multi-process. On TPU pod slices with no
    explicit env, ``jax.distributed.initialize()`` auto-detects via the TPU
    metadata server — so bare ``initialize()`` is attempted when the backend
    is TPU even without PST_* env.
    """
    global _initialized
    cfg = cfg or DistributedConfig.from_env()
    if _initialized:
        return jax.process_count() > 1
    if cfg.enabled:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
        _initialized = True
        logger.info(
            "distributed runtime up: process %d/%d, %d global devices",
            jax.process_index(), jax.process_count(), len(jax.devices()),
        )
        return True
    return False


def is_primary() -> bool:
    """True on the host that should bind the HTTP server (ray head analogue)."""
    return jax.process_index() == 0


class HostBridge:
    """Host-0 → all-hosts control broadcast for per-step batch metadata.

    Every SPMD process must issue identical XLA computations; the scheduler
    runs on host 0 only, so each step's logical batch is shipped to the
    followers before the jitted call. Two-phase fixed-shape broadcast (length
    then padded payload) because ``broadcast_one_to_all`` needs matching
    pytree structure on every host.
    """

    def __init__(self, chunk: int = 1 << 20):
        from jax.experimental import multihost_utils

        self._mh = multihost_utils
        self.chunk = chunk

    def publish(self, obj: Any) -> Any:
        """On host 0: broadcast ``obj``; on followers: receive it."""
        if is_primary():
            payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
            n = len(payload)
        else:
            payload, n = b"", 0
        n = int(self._mh.broadcast_one_to_all(np.int64(n)))
        nchunks = -(-n // self.chunk) or 1
        buf = np.zeros(nchunks * self.chunk, np.uint8)
        if is_primary():
            buf[: len(payload)] = np.frombuffer(payload, np.uint8)
        buf = np.asarray(self._mh.broadcast_one_to_all(buf))
        if is_primary():
            return obj
        return pickle.loads(buf[:n].tobytes())

    def barrier(self, name: str = "pst") -> None:
        self._mh.sync_global_devices(name)
