"""Device-mesh construction for the TPU serving engine.

The reference stack expresses parallelism as vLLM/Ray/NCCL configuration
(``helm/templates/deployment-vllm-multi.yaml:155-158`` tensor parallel,
``helm/templates/ray-cluster.yaml:560-566`` pipeline parallel). TPU-native,
every strategy is a named axis of one ``jax.sharding.Mesh``; XLA inserts the
ICI/DCN collectives implied by sharding annotations — there is no NCCL/Ray
equivalent to manage.

Axes (any may be size 1):

- ``dp``  — data parallel: independent decode batches / cache shards.
- ``pp``  — pipeline parallel: layer stages (DCN-friendly, crosses slices).
- ``tp``  — tensor parallel: attention heads / MLP hidden (innermost: rides
  ICI, where all-reduce bandwidth is highest).
- ``sp``  — sequence/context parallel for long-context ring attention.
- ``ep``  — expert parallel (MoE models).

Convention: ``tp`` is the fastest-varying (innermost) axis so tensor-parallel
collectives stay on ICI neighbors; ``dp``/``pp`` are outermost and may span
DCN. This mirrors the scaling-book recipe: pick the mesh, annotate shardings,
let XLA place collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DATA = "dp"
AXIS_PIPELINE = "pp"
AXIS_TENSOR = "tp"
AXIS_SEQUENCE = "sp"
AXIS_EXPERT = "ep"

# Outer→inner order used for every mesh this package builds.
MESH_AXIS_ORDER = (AXIS_DATA, AXIS_PIPELINE, AXIS_SEQUENCE, AXIS_EXPERT, AXIS_TENSOR)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Parallelism degrees. ``total() `` must divide the device count."""

    data_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    sequence_parallel_size: int = 1
    expert_parallel_size: int = 1
    tensor_parallel_size: int = 1

    def total(self) -> int:
        return (
            self.data_parallel_size
            * self.pipeline_parallel_size
            * self.sequence_parallel_size
            * self.expert_parallel_size
            * self.tensor_parallel_size
        )

    def sizes(self) -> List[int]:
        return [
            self.data_parallel_size,
            self.pipeline_parallel_size,
            self.sequence_parallel_size,
            self.expert_parallel_size,
            self.tensor_parallel_size,
        ]


def build_mesh(
    config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build the engine mesh over ``devices`` (default: all JAX devices).

    Devices are arranged so ``tp`` groups are contiguous in device order —
    on real TPU slices, contiguous device order tracks physical ICI
    adjacency, keeping the hot all-reduces off DCN.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = config.total()
    if n > len(devices):
        raise ValueError(
            f"mesh needs {n} devices ({config}), only {len(devices)} available"
        )
    grid = np.array(devices[:n], dtype=object).reshape(config.sizes())
    return Mesh(grid, MESH_AXIS_ORDER)


def local_mesh(tensor_parallel_size: Optional[int] = None) -> Mesh:
    """Single-axis-of-interest mesh over local devices (tp only).

    The common single-slice serving case: all chips in one tensor-parallel
    group (``--tensor-parallel-size`` analogue of
    ``deployment-vllm-multi.yaml:155-158``).
    """
    n = tensor_parallel_size or len(jax.devices())
    return build_mesh(MeshConfig(tensor_parallel_size=n))


def largest_pow2_leq(n: int) -> int:
    return 1 << (n.bit_length() - 1) if n > 0 else 1


def auto_mesh_config(n_devices: int, max_tp: int = 8) -> MeshConfig:
    """Heuristic mesh for ``n_devices``: fill tp up to ``max_tp``, rest dp."""
    tp = math.gcd(largest_pow2_leq(n_devices), max_tp)
    while n_devices % tp:
        tp //= 2
    return MeshConfig(tensor_parallel_size=tp, data_parallel_size=n_devices // tp)
