"""The single source of truth for the configuration contract.

Modeled on :mod:`production_stack_tpu.obs.metric_registry`: every router
CLI flag and every engine :class:`EngineConfig` field is declared ONCE
here, naming where it surfaces — the helm values path, the schema entry,
the template that emits it, and the docs file carrying its flag-table
row. The ``config-contract`` pstlint check verifies all five surfaces
agree in both directions:

- a parser flag with no :class:`ConfigSpec` is an undeclared knob;
- a spec with no parser flag is stale;
- a ``helm``-scoped flag must exist in ``helm/values.yaml`` AND
  ``helm/values.schema.json`` AND be emitted by its template AND match
  the parser default (unless ``default_differs`` documents why not);
- a ``cli-only`` flag must NOT be emitted by any template (emission
  means it grew a helm surface and must be reclassified);
- every ``routerSpec.*`` values/schema leaf must be claimed by a spec or
  by :data:`ROUTER_HELM_NON_FLAG` — a helm knob no flag consumes is
  exactly the "configured in values.yaml, silently ignored by the pod"
  drift class this registry exists to kill.

Kept importable with zero third-party dependencies so the analyzer and
CI consume it on a bare checkout. Scope values:

- ``helm``: user-settable values knob, wired through a template.
- ``template``: emitted by a template with a fixed or derived value
  (``$(POD_NAME)``, rendered service URLs) — no user values knob.
- ``cli-only``: no helm surface by design; reachable via
  ``routerSpec.extraArgs`` when needed. ``note`` says why.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

HELM = "helm"
TEMPLATE = "template"
CLI_ONLY = "cli-only"

ROUTER_TEMPLATE = "helm/templates/deployment-router.yaml"
ENGINE_TEMPLATE = "helm/templates/deployment-engine.yaml"

_ROUTER_DOC = "docs/router.md"
_RESILIENCE_DOC = "docs/resilience.md"
_HA_DOC = "docs/router-ha.md"


@dataclasses.dataclass(frozen=True)
class ConfigSpec:
    """One router CLI flag's contract across the five surfaces."""

    flag: str
    scope: str = HELM
    helm: Optional[str] = None        # values.yaml path (scope=helm)
    template: Optional[str] = None    # template emitting the flag
    doc: str = _ROUTER_DOC            # docs file with the flag row
    # Reason the parser default and the values.yaml default differ on
    # purpose (empty = they must match).
    default_differs: str = ""
    # Why there is no helm knob (scope=cli-only) / how the template
    # derives the value (scope=template).
    note: str = ""
    # Negation alias (--no-*): checked for parser existence + template
    # emission only; the positive twin carries the helm contract.
    negation_of: Optional[str] = None
    # String the template actually emits when it differs from ``flag``
    # (default-on booleans are rendered via their --no-* twin).
    emit: Optional[str] = None


def _helm(
    flag: str,
    path: str,
    doc: str = _ROUTER_DOC,
    default_differs: str = "",
) -> ConfigSpec:
    return ConfigSpec(
        flag, HELM, helm=path, template=ROUTER_TEMPLATE, doc=doc,
        default_differs=default_differs,
    )


def _tpl(flag: str, note: str, doc: str = _ROUTER_DOC) -> ConfigSpec:
    return ConfigSpec(
        flag, TEMPLATE, template=ROUTER_TEMPLATE, doc=doc, note=note
    )


def _cli(flag: str, note: str, doc: str = _ROUTER_DOC) -> ConfigSpec:
    return ConfigSpec(flag, CLI_ONLY, doc=doc, note=note)


# One entry per ``add_argument`` call in router/parser.py, same order.
ROUTER_FLAGS: Tuple[ConfigSpec, ...] = (
    _cli("--config", "bootstrap defaults file; helm renders flags directly"),
    _tpl("--host", "always 0.0.0.0 in a pod"),
    _helm("--port", "routerSpec.containerPort",
          default_differs="chart standardizes every pod port at 8000; "
          "bare CLI keeps 8001 to coexist with a local engine"),
    _helm("--service-discovery", "routerSpec.serviceDiscovery",
          default_differs="the chart is k8s-native (discovery=k8s); bare "
          "CLI defaults to static for local runs"),
    _cli("--k8s-service-discovery-type",
         "pod-ip is right inside the chart's own Service mesh; "
         "service-name mode is an extraArgs escape hatch"),
    _helm("--static-backends", "routerSpec.staticBackends"),
    _helm("--static-models", "routerSpec.staticModels"),
    _cli("--static-aliases", "static discovery detail; extraArgs"),
    _cli("--static-model-labels", "static discovery detail; extraArgs"),
    _cli("--static-model-types", "static discovery detail; extraArgs"),
    _cli("--static-pools", "static discovery detail; extraArgs — helm "
         "fleets declare disagg pools via servingEngineSpec.modelSpec[]."
         "pool, surfaced as the pst-pool pod label (docs/disagg.md)"),
    _cli("--static-backend-health-checks",
         "k8s discovery has readiness probes; static probing is extraArgs"),
    _cli("--health-check-interval", "companion of static health checks"),
    _tpl("--k8s-namespace", "rendered from .Release.Namespace"),
    _cli("--k8s-port", "chart engines always listen on 8000 (the default)"),
    _helm("--k8s-label-selector", "routerSpec.k8sLabelSelector",
          default_differs="the chart pins its own release labels; bare "
          "CLI defaults to no selector (all pods)"),
    _helm("--routing-logic", "routerSpec.routingLogic"),
    _helm("--session-key", "routerSpec.sessionKey"),
    _helm("--kv-aware-threshold", "routerSpec.kvAwareThreshold"),
    _helm("--fleet-eviction-ratio", "routerSpec.fleet.evictionRatio"),
    _helm("--fleet-load-factor", "routerSpec.fleet.loadFactor"),
    _tpl("--cache-controller-url",
         "rendered kv-controller service URL when "
         "kvControllerSpec.enableController"),
    _cli("--tokenizer-name", "kvaware hashing detail; extraArgs"),
    _helm("--prefill-model-labels", "routerSpec.prefillModelLabels"),
    _helm("--decode-model-labels", "routerSpec.decodeModelLabels"),
    ConfigSpec("--disagg-overlap", HELM,
               helm="routerSpec.disagg.overlap",
               template=ROUTER_TEMPLATE, emit="--no-disagg-overlap",
               note="default-on: the template renders the negation when "
               "disagg.overlap is false"),
    ConfigSpec("--no-disagg-overlap", TEMPLATE, template=ROUTER_TEMPLATE,
               negation_of="--disagg-overlap",
               note="emitted when disagg.overlap is false"),
    _helm("--admission-rate", "routerSpec.resilience.admissionRate",
          doc=_RESILIENCE_DOC),
    _helm("--admission-burst", "routerSpec.resilience.admissionBurst",
          doc=_RESILIENCE_DOC),
    _helm("--admission-queue-size", "routerSpec.resilience.admissionQueueSize",
          doc=_RESILIENCE_DOC),
    _helm("--admission-queue-timeout",
          "routerSpec.resilience.admissionQueueTimeout", doc=_RESILIENCE_DOC),
    _helm("--proxy-retries", "routerSpec.resilience.proxyRetries",
          doc=_RESILIENCE_DOC),
    _helm("--retry-backoff", "routerSpec.resilience.retryBackoff",
          doc=_RESILIENCE_DOC),
    _helm("--proxy-connect-timeout",
          "routerSpec.resilience.proxyConnectTimeout", doc=_RESILIENCE_DOC),
    _helm("--proxy-read-timeout", "routerSpec.resilience.proxyReadTimeout",
          doc=_RESILIENCE_DOC),
    _helm("--breaker-failure-threshold",
          "routerSpec.resilience.breakerFailureThreshold",
          doc=_RESILIENCE_DOC),
    _helm("--breaker-recovery-time",
          "routerSpec.resilience.breakerRecoveryTime", doc=_RESILIENCE_DOC),
    _helm("--breaker-half-open-probes",
          "routerSpec.resilience.breakerHalfOpenProbes", doc=_RESILIENCE_DOC),
    _helm("--tenant-isolation", "routerSpec.tenancy.enabled"),
    _helm("--tenant-config", "routerSpec.tenancy.configFile"),
    _helm("--tenant-default-weight", "routerSpec.tenancy.defaultWeight"),
    _helm("--tenant-default-tier", "routerSpec.tenancy.defaultTier"),
    _cli("--tenant-header", "identity-header rename is a gateway-"
         "integration detail; extraArgs"),
    _helm("--default-deadline-ms", "routerSpec.resilience.defaultDeadlineMs",
          doc=_RESILIENCE_DOC),
    _helm("--hedge-enabled", "routerSpec.resilience.hedge.enabled",
          doc=_RESILIENCE_DOC),
    _helm("--hedge-delay-ms", "routerSpec.resilience.hedge.delayMs",
          doc=_RESILIENCE_DOC),
    _helm("--hedge-quantile", "routerSpec.resilience.hedge.quantile",
          doc=_RESILIENCE_DOC),
    _helm("--hedge-max-outstanding-ratio",
          "routerSpec.resilience.hedge.maxOutstandingRatio",
          doc=_RESILIENCE_DOC),
    _helm("--stream-resume", "routerSpec.resilience.streamResume.enabled",
          doc=_RESILIENCE_DOC),
    _helm("--stream-resume-max-legs",
          "routerSpec.resilience.streamResume.maxLegs", doc=_RESILIENCE_DOC),
    ConfigSpec("--tracing", HELM, helm="routerSpec.observability.tracing",
               template=ROUTER_TEMPLATE, emit="--no-tracing",
               note="default-on: the template renders the negation when "
               "observability.tracing is false"),
    ConfigSpec("--no-tracing", TEMPLATE, template=ROUTER_TEMPLATE,
               negation_of="--tracing",
               note="emitted when observability.tracing is false"),
    _helm("--debug-requests-buffer",
          "routerSpec.observability.debugRequestsBuffer"),
    _helm("--log-format", "routerSpec.observability.logFormat"),
    _helm("--slo-ttft-ms", "routerSpec.observability.sloTtftMs"),
    _helm("--canary-interval",
          "routerSpec.observability.canary.intervalSeconds",
          default_differs="CLI default 0 keeps probing off; the helm knob "
          "is gated on canary.enabled and then defaults to 15s"),
    _helm("--canary-timeout", "routerSpec.observability.canary.timeoutSeconds"),
    ConfigSpec("--capacity-signal", HELM,
               helm="routerSpec.observability.capacitySignal",
               template=ROUTER_TEMPLATE, emit="--no-capacity-signal",
               note="default-on: the template renders the negation when "
               "observability.capacitySignal is false"),
    ConfigSpec("--no-capacity-signal", TEMPLATE, template=ROUTER_TEMPLATE,
               negation_of="--capacity-signal",
               note="emitted when observability.capacitySignal is false"),
    _helm("--state-backend", "routerSpec.stateBackend.type", doc=_HA_DOC),
    _tpl("--state-peers",
         "rendered dns:// spec of the headless peer service", doc=_HA_DOC),
    _helm("--state-sync-interval",
          "routerSpec.stateBackend.syncIntervalSeconds", doc=_HA_DOC),
    _helm("--state-peer-timeout",
          "routerSpec.stateBackend.peerTimeoutSeconds", doc=_HA_DOC),
    _tpl("--state-replica-id", "rendered $(POD_NAME)", doc=_HA_DOC),
    _helm("--engine-stats-interval", "routerSpec.engineScrapeInterval"),
    _helm("--request-stats-window", "routerSpec.requestStatsWindow"),
    _cli("--log-stats", "human-readable stdout loop; operators use /metrics"),
    _cli("--log-stats-interval", "companion of --log-stats"),
    _cli("--enable-batch-api", "batch/files API needs a volume story the "
         "chart does not ship yet; extraArgs"),
    _cli("--batch-db-path", "companion of --enable-batch-api"),
    _cli("--file-storage-class", "companion of --enable-batch-api"),
    _cli("--file-storage-path", "companion of --enable-batch-api"),
    _cli("--batch-processor", "companion of --enable-batch-api"),
    _helm("--sentry-dsn", "routerSpec.sentryDsn"),
    _cli("--sentry-traces-sample-rate", "sentry tuning detail; extraArgs"),
    _cli("--sentry-profile-session-sample-rate",
         "sentry tuning detail; extraArgs"),
    _tpl("--dynamic-config-json",
         "/config/dynamic.json from the rendered ConfigMap when "
         "routerSpec.dynamicConfig is set"),
    _cli("--callbacks", "arbitrary-code hook; mount your own module and "
         "wire via extraArgs"),
    _cli("--request-rewriter", "experimental; extraArgs"),
    _cli("--feature-gates", "experimental features; extraArgs"),
    _cli("--pii-analyzer", "experimental (PIIDetection gate); extraArgs"),
    _cli("--pii-types", "experimental (PIIDetection gate); extraArgs"),
    _cli("--semantic-cache-model", "experimental (SemanticCache gate)"),
    _cli("--semantic-cache-dir", "experimental (SemanticCache gate)"),
    _cli("--semantic-cache-threshold", "experimental (SemanticCache gate)"),
    _cli("--semantic-cache-embedder", "experimental (SemanticCache gate)"),
    _cli("--semantic-cache-embed-model", "experimental (SemanticCache gate)"),
    _tpl("--api-key",
         "$(PST_API_KEY) from servingEngineSpec.apiKeySecret — the fleet "
         "shares one key, so the router enforces and forwards the same "
         "secret the engines check"),
    _cli("--log-level", "debug knob; extraArgs"),
)

# routerSpec.* values/schema keys that are deliberately NOT CLI flags
# (deployment shape, not router configuration). Prefix semantics: a key
# equal to an entry or nested under it is allowed.
ROUTER_HELM_NON_FLAG: Tuple[str, ...] = (
    "routerSpec.enableRouter",
    "routerSpec.replicaCount",
    "routerSpec.image",
    "routerSpec.serviceType",
    "routerSpec.servicePort",
    "routerSpec.resources",
    "routerSpec.extraArgs",
    "routerSpec.dynamicConfig",
    "routerSpec.hpa",
    "routerSpec.podDisruptionBudget",
    # Gate knob: enables canary probing; the flags it gates
    # (--canary-interval/--canary-timeout) carry their own specs.
    "routerSpec.observability.canary.enabled",
)


@dataclasses.dataclass(frozen=True)
class EngineFieldSpec:
    """One :class:`EngineConfig` field's contract.

    ``flag`` is the engine CLI option (None = embedded-only field with no
    CLI surface); ``helm`` the values path under the modelSpec example
    (None = cli-only). ``emit`` overrides the string searched for in the
    engine template when the emission differs from ``flag`` (negation
    flags, renamed options).
    """

    field: str
    flag: Optional[str]
    helm: Optional[str] = None
    emit: Optional[str] = None
    default_differs: str = ""
    note: str = ""


def _ms(path: str) -> str:
    return "servingEngineSpec.modelSpec[]." + path


_SIZED = ("the committed modelSpec is the sized 8B reference example, "
          "not the engine's neutral default")

# One entry per EngineConfig dataclass field, declaration order.
ENGINE_FIELDS: Tuple[EngineFieldSpec, ...] = (
    EngineFieldSpec("model", "--model", _ms("model"),
                    default_differs=_SIZED),
    EngineFieldSpec("tokenizer", "--tokenizer",
                    note="defaults to the model directory"),
    EngineFieldSpec("served_model_name", "--served-model-name",
                    _ms("servedModelName"), default_differs=_SIZED),
    EngineFieldSpec("max_model_len", "--max-model-len",
                    _ms("engineConfig.maxModelLen"), default_differs=_SIZED),
    EngineFieldSpec("block_size", "--block-size",
                    _ms("engineConfig.blockSize")),
    EngineFieldSpec("num_kv_blocks", "--num-kv-blocks",
                    note="sized from the HBM budget by default"),
    EngineFieldSpec("hbm_utilization", "--gpu-memory-utilization",
                    _ms("engineConfig.hbmUtilization")),
    EngineFieldSpec("max_num_seqs", "--max-num-seqs",
                    _ms("engineConfig.maxNumSeqs")),
    EngineFieldSpec("max_prefill_tokens", "--max-num-batched-tokens",
                    _ms("engineConfig.maxNumBatchedTokens")),
    EngineFieldSpec("tensor_parallel_size", "--tensor-parallel-size",
                    _ms("engineConfig.tensorParallelSize"),
                    default_differs=_SIZED),
    EngineFieldSpec("data_parallel_size", "--data-parallel-size",
                    _ms("engineConfig.dataParallelSize")),
    EngineFieldSpec("pipeline_parallel_size", "--pipeline-parallel-size",
                    _ms("engineConfig.pipelineParallelSize")),
    EngineFieldSpec("sequence_parallel_size", "--sequence-parallel-size",
                    _ms("engineConfig.sequenceParallelSize")),
    EngineFieldSpec("expert_parallel_size", "--expert-parallel-size",
                    _ms("engineConfig.expertParallelSize")),
    EngineFieldSpec("kv_cache_dtype", "--kv-cache-dtype",
                    _ms("engineConfig.kvCacheDtype")),
    EngineFieldSpec("quantization", "--quantization",
                    _ms("engineConfig.quantization")),
    EngineFieldSpec("attn_impl", "--attn-impl",
                    _ms("engineConfig.attnImpl"),
                    default_differs="the chart targets TPU node pools "
                    "(pallas); the engine's neutral default is auto"),
    EngineFieldSpec("moe_impl", "--moe-impl",
                    note="MoE kernel selection; extraArgs"),
    EngineFieldSpec("enable_prefix_caching", "--enable-prefix-caching",
                    _ms("engineConfig.enablePrefixCaching"),
                    emit="--no-enable-prefix-caching"),
    EngineFieldSpec("num_decode_steps", "--num-decode-steps",
                    _ms("engineConfig.numDecodeSteps"),
                    default_differs=_SIZED),
    EngineFieldSpec("adaptive_decode_steps", "--adaptive-decode-steps",
                    _ms("engineConfig.adaptiveDecodeSteps")),
    EngineFieldSpec("adaptive_decode_quiet_s", "--adaptive-decode-quiet-s",
                    note="adaptive-burst tuning; extraArgs"),
    EngineFieldSpec("adaptive_decode_min_running",
                    "--adaptive-decode-min-running",
                    note="adaptive-burst tuning; extraArgs"),
    EngineFieldSpec("min_decode_bucket", "--min-decode-bucket",
                    note="lattice floor tuning; extraArgs"),
    EngineFieldSpec("speculative_ngram", "--speculative-ngram",
                    note="speculation is opt-in via extraArgs"),
    EngineFieldSpec("ngram_min", "--ngram-min",
                    note="companion of --speculative-ngram"),
    EngineFieldSpec("ngram_max", "--ngram-max",
                    note="companion of --speculative-ngram"),
    EngineFieldSpec("ngram_lookback", "--ngram-lookback",
                    note="companion of --speculative-ngram"),
    EngineFieldSpec("async_decode", None,
                    note="embedded-only experiment, superseded by "
                    "overlap_decode"),
    EngineFieldSpec("overlap_decode", "--overlap-decode",
                    note="default-on; --no-overlap-decode is the CLI "
                    "escape hatch"),
    EngineFieldSpec("enforce_eager", None,
                    note="reserved; XLA always compiles"),
    EngineFieldSpec("seed", "--seed", note="debug determinism; extraArgs"),
    EngineFieldSpec("cpu_offload_blocks", "--cpu-offload-blocks",
                    _ms("kvCache.cpuOffloadBlocks"),
                    default_differs="the chart provisions a host-DRAM "
                    "page pool; the engine default is off"),
    EngineFieldSpec("remote_kv_url", "--remote-kv-url",
                    note="rendered cache-server URL when "
                    "kvCache.useRemoteStore (template-derived)"),
    EngineFieldSpec("cache_controller_url", "--cache-controller-url",
                    note="rendered kv-controller URL when "
                    "kvControllerSpec.enableController (template-derived)"),
    EngineFieldSpec("engine_url", "--engine-url",
                    note="self-URL for controller reports; the pod "
                    "derives it from $(POD_IP)"),
    EngineFieldSpec("enable_lora", "--enable-lora",
                    _ms("lora.enabled"),
                    default_differs="gated emission: the flag only "
                    "renders when lora.enabled"),
    EngineFieldSpec("max_loras", "--max-loras",
                    note="LoRA capacity tuning; extraArgs"),
    EngineFieldSpec("max_lora_rank", "--max-lora-rank",
                    note="LoRA capacity tuning; extraArgs"),
    EngineFieldSpec("lora_dir", "--lora-dir", _ms("lora.adapterDir"),
                    default_differs="gated emission with the chart's "
                    "shared adapter volume path"),
    EngineFieldSpec("kv_swap", "--kv-swap", _ms("engineConfig.kvSwap"),
                    emit="--no-kv-swap"),
    EngineFieldSpec("swap_quantum_tokens", "--swap-quantum-tokens",
                    _ms("engineConfig.swapQuantumTokens")),
    EngineFieldSpec("swap_stash_blocks", "--swap-stash-blocks",
                    _ms("engineConfig.swapStashBlocks")),
    EngineFieldSpec("kv_role", "--kv-role", _ms("kvCache.kvRole")),
    EngineFieldSpec("kv_prefetch_depth", "--kv-prefetch-depth",
                    _ms("kvCache.kvPrefetchDepth")),
    EngineFieldSpec("kv_transfer_timeout_s", "--kv-transfer-timeout-s",
                    _ms("kvCache.kvTransferTimeoutS")),
    EngineFieldSpec("kv_replication", "--kv-replication",
                    _ms("kvCache.kvReplication")),
    EngineFieldSpec("deadline_shedding", "--deadline-shedding",
                    "servingEngineSpec.deadlineShedding",
                    emit="--no-deadline-shedding"),
    EngineFieldSpec("tenant_fairness", "--tenant-fairness",
                    "servingEngineSpec.tenantFairness",
                    emit="--no-tenant-fairness"),
    EngineFieldSpec("warmup", "--warmup", "servingEngineSpec.warmup.mode",
                    default_differs="helm deploys warmed (full); bare CLI "
                    "and embedded runs default to off so dev loops stay "
                    "instant"),
    EngineFieldSpec("warmup_bucket_budget", "--warmup-bucket-budget",
                    "servingEngineSpec.warmup.bucketBudget"),
    EngineFieldSpec("compile_cache_dir", "--compile-cache-dir",
                    "servingEngineSpec.warmup.cacheDir"),
    EngineFieldSpec("flight_buffer", "--flight-buffer",
                    "servingEngineSpec.observability.flightBuffer"),
    EngineFieldSpec("flight_snapshot_dir", "--flight-snapshot-dir",
                    "servingEngineSpec.observability.flightSnapshotDir"),
    EngineFieldSpec("cost_attribution", "--cost-attribution",
                    "servingEngineSpec.observability.costAttribution",
                    emit="--no-cost-attribution"),
)

@dataclasses.dataclass(frozen=True)
class AutoscaleKeySpec:
    """One ``spec.autoscale.<key>`` TPURuntime knob's contract.

    The autoscale knobs live in the CRD, not in helm (the chart does
    not render TPURuntime CRs — per-pool policy is declarative), so
    their four surfaces are: the CRD openAPI schema
    (:data:`OPERATOR_CRD`), the C++ reconciler that consumes them
    (:data:`OPERATOR_RECONCILERS` reads ``as.at("<key>")``), the
    committed sample CR (:data:`OPERATOR_SAMPLE`), and the docs page
    (:data:`AUTOSCALE_DOC`). The config-contract check proves all four
    in both directions — a CRD key no reconciler reads is
    configuration theater, a reconciler read the CRD does not declare
    is an undocumented knob.
    """

    key: str
    note: str = ""


OPERATOR_CRD = "operator/crds/crds.yaml"
OPERATOR_RECONCILERS = "operator/src/reconcilers.cc"
OPERATOR_SAMPLE = "operator/config/samples/tpuruntime.yaml"
AUTOSCALE_DOC = "docs/autoscaling.md"

AUTOSCALE_KEYS: Tuple[AutoscaleKeySpec, ...] = (
    AutoscaleKeySpec("minReplicas", "floor; 0 allowed with scaleToZero"),
    AutoscaleKeySpec("maxReplicas", "ceiling, clamps any replica hint"),
    AutoscaleKeySpec("scaleDownStabilizationS",
                     "cooldown after any scale event"),
    AutoscaleKeySpec("drainDeadlineS",
                     "blocking-drain bound per scale-down victim"),
    AutoscaleKeySpec("idleVerdicts",
                     "consecutive idle passes arming the shrink paths"),
    AutoscaleKeySpec("scaleToZero",
                     "park a single slept standby at sustained idle"),
)

ROUTER_BY_FLAG: Dict[str, ConfigSpec] = {s.flag: s for s in ROUTER_FLAGS}
ENGINE_BY_FIELD: Dict[str, EngineFieldSpec] = {
    s.field: s for s in ENGINE_FIELDS
}
