"""lock-discipline: declared shared state is only mutated by its owner.

The router holds all routing state in one process today — breaker
registry, stats windows, stream journals, prefix hashtrie — and stays
correct because asyncio gives it one thread and each structure has ONE
writer surface (a lock, or a single writer task/method family). ROADMAP
item 5 (router data-plane scale-out) is exactly the refactor where a
second writer slips in: a new code path mutates ``engine_stats`` off the
scrape loop, or touches trie nodes without the node lock, and nothing
fails until replicas disagree under load. This check makes the ownership
machine-readable and enforced.

Grammar (on the state's declaration line, or the line above):

- ``# pstlint: owned-by=lock:<attr>`` — mutations of this attribute on a
  receiver ``r`` must sit inside ``with r.<attr-of-lock>`` /
  ``async with r.<lock>`` (textual receiver match), or inside a function
  annotated ``# pstlint: holds=r.<lock>``.
- ``# pstlint: owned-by=task:<fn>[,<fn>...]`` — mutations are legal only
  inside the named functions/methods (``*`` suffix globs allowed, e.g.
  ``task:on_request_*``) plus the object's own ``__init__`` (mutations of
  ``self.<attr>`` — a different receiver's state mutated from an
  unrelated ``__init__`` is a second writer like any other).

A "mutation" is: rebinding the attribute, item assignment/deletion on
it, augmented assignment, or calling a mutating method (``append``,
``add``, ``pop``, ``update``, ``clear``, ...) on it. Matching is by
attribute name within the declaring file — aliasing through locals or
cross-module mutation is out of reach by design (documented in
docs/static-analysis.md); the point is to catch the easy-to-write,
hard-to-debug direct second writer.

Suppress with ``# pstlint: disable=lock-discipline(<reason>)``.

Backend discipline (router HA, ROADMAP item 5 landed): on the
routing-state surfaces — ``resilience/``, ``router/routing/``,
``router/stats/``, ``router/state/`` and ``router/service_discovery.py``
— every *mutable container* attribute assigned in an ``__init__`` must
declare its writer surface with ``owned-by=lock:…`` / ``owned-by=task:…``,
or declare that the state is coordinated through the
:class:`~production_stack_tpu.router.state.StateBackend` with
``owned-by=backend:<surface>`` (no same-file mutation checking then —
the backend owns the merge semantics). Undeclared mutable state on these
surfaces is exactly how a second replica-divergent writer slips in after
the scale-out refactor, so it fails CI at the declaration, not in an
incident.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, SourceFile

CHECK_ID = "lock-discipline"
DESCRIPTION = (
    "mutations of owned-by annotated shared state outside the owning "
    "lock or single-writer task"
)

_MUTATORS = {
    "append", "appendleft", "add", "pop", "popleft", "popitem", "clear",
    "update", "insert", "remove", "extend", "extendleft", "setdefault",
    "discard", "sort", "reverse",
}


class _Owned:
    def __init__(self, attr: str, kind: str, spec: str, line: int,
                 is_global: bool) -> None:
        self.attr = attr
        self.kind = kind  # "lock" | "task"
        self.spec = spec
        self.line = line
        # Declared as a module-level bare name (vs an instance/class
        # attribute): only then does a bare-name write count as a
        # mutation — otherwise locals that happen to share the attribute
        # name would false-positive.
        self.is_global = is_global


def _collect_owned(src: SourceFile) -> Dict[str, _Owned]:
    """attr-name -> ownership, from annotated declarations."""
    owned: Dict[str, _Owned] = {}
    if src.tree is None:
        return owned
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = src.annotation_at(node.lineno, "owned-by")
            if value is None:
                continue
            kind, _, spec = value.partition(":")
            kind = kind.strip()
            spec = spec.strip()
            if kind not in ("lock", "task") or not spec:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                attr: Optional[str] = None
                is_global = False
                if isinstance(tgt, ast.Attribute):
                    attr = tgt.attr
                elif isinstance(tgt, ast.Name):
                    attr = tgt.id
                    is_global = True
                if attr:
                    owned[attr] = _Owned(attr, kind, spec, node.lineno,
                                         is_global)
    return owned


def _mutated_target(node: ast.AST) -> Optional[Tuple[str, str, ast.AST]]:
    """(attr, receiver_text, site) when ``node`` mutates ``recv.attr`` or
    a bare annotated global. receiver_text is '' for bare names."""
    def from_expr(expr: ast.AST) -> Optional[Tuple[str, str, ast.AST]]:
        # recv.attr  /  recv.attr[...]  (unwrap one subscript level)
        if isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Attribute):
            try:
                recv = ast.unparse(expr.value)
            except Exception:  # pragma: no cover — exotic receiver
                return None
            return expr.attr, recv, expr
        if isinstance(expr, ast.Name):
            return expr.id, "", expr
        return None

    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            hit = from_expr(tgt)
            if hit:
                return hit
    elif isinstance(node, ast.AugAssign):
        return from_expr(node.target)
    elif isinstance(node, ast.Delete):
        for tgt in node.targets:
            hit = from_expr(tgt)
            if hit:
                return hit
    elif isinstance(node, ast.Call):
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
        ):
            return from_expr(node.func.value)
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, src: SourceFile, owned: Dict[str, _Owned]) -> None:
        self.src = src
        self.owned = owned
        self.findings: List[Finding] = []
        self.func_stack: List[ast.AST] = []
        self.with_stack: List[str] = []

    # -- context tracking --------------------------------------------------

    def _visit_func(self, node: ast.AST) -> None:
        self.func_stack.append(node)
        saved = self.with_stack
        self.with_stack = []  # with-blocks do not span function boundaries
        self.generic_visit(node)
        self.with_stack = saved
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _visit_with(self, node: ast.AST) -> None:
        ctxs = []
        for item in node.items:
            try:
                ctxs.append(ast.unparse(item.context_expr))
            except Exception:  # pragma: no cover
                pass
        self.with_stack.extend(ctxs)
        self.generic_visit(node)
        del self.with_stack[len(self.with_stack) - len(ctxs):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- the rule ----------------------------------------------------------

    def _enclosing_name(self) -> Optional[str]:
        if not self.func_stack:
            return None
        fn = self.func_stack[-1]
        return getattr(fn, "name", None)

    def _holds(self, wanted: str) -> bool:
        if wanted in self.with_stack:
            return True
        for fn in self.func_stack:
            line = getattr(fn, "lineno", None)
            if line is None:
                continue
            held = self.src.annotation_at(line, "holds")
            if held is not None and held.strip() == wanted:
                return True
        return False

    def _check(self, node: ast.AST) -> None:
        hit = _mutated_target(node)
        if hit is None:
            return
        attr, recv, site = hit
        owner = self.owned.get(attr)
        if owner is None:
            return
        if not recv and not owner.is_global:
            # Bare-name write, but the state is an attribute: this is a
            # local variable that shares the name, not the shared state.
            return
        fn_name = self._enclosing_name()
        if fn_name == "__init__" and recv == "self":
            # Construction of the object's OWN state in its __init__ is
            # the legal first write. A different receiver (some other
            # object's owned state mutated from an unrelated __init__) is
            # a second writer like any other and falls through.
            return
        if fn_name is None and not recv:
            # The module-level declaration/rebind of an annotated global
            # is its first write; attribute mutations at module level
            # still get checked below.
            return
        if owner.kind == "task":
            allowed = [p.strip() for p in owner.spec.split(",") if p.strip()]
            if fn_name is not None and any(
                fnmatch.fnmatchcase(fn_name, pat) for pat in allowed
            ):
                return
            self.findings.append(Finding(
                CHECK_ID, self.src.rel, site.lineno, site.col_offset,
                "%r is owned by writer task/method(s) %s (declared line "
                "%d) but is mutated here in %r — a second writer surface "
                "breaks the single-writer contract ROADMAP item 5 scales "
                "out on" % (attr, owner.spec, owner.line,
                            fn_name or "<module level>"),
            ))
        else:  # lock
            wanted = "%s.%s" % (recv, owner.spec) if recv else owner.spec
            if self._holds(wanted):
                return
            self.findings.append(Finding(
                CHECK_ID, self.src.rel, site.lineno, site.col_offset,
                "%r is owned by lock %r (declared line %d) but is mutated "
                "here outside 'with %s' (and no enclosing '# pstlint: "
                "holds=%s')" % (attr, owner.spec, owner.line, wanted, wanted),
            ))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check(node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        self._check(node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._check(node)
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# Backend discipline: new mutable state on routing-state surfaces must
# declare its writer (owned-by=lock:/task:) or its replication contract
# (owned-by=backend:...). Scope = the state ROADMAP item 5 replicated.
# ---------------------------------------------------------------------------

_BACKEND_SCOPE_DIRS = (
    "resilience/", "router/routing/", "router/stats/", "router/state/",
)

_MUTABLE_CONSTRUCTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "bytearray",
}


def _in_backend_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    if rel.endswith("router/service_discovery.py"):
        return True
    return any(d in rel for d in _BACKEND_SCOPE_DIRS)


def _mutable_initializer(value: ast.AST) -> Optional[str]:
    """Name of the mutable container this expression constructs, if any."""
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(value, ast.Call) and not value.args and not value.keywords:
        name = None
        if isinstance(value.func, ast.Name):
            name = value.func.id
        elif isinstance(value.func, ast.Attribute):
            name = value.func.attr
        if name in _MUTABLE_CONSTRUCTORS:
            return name
    return None


def _check_backend_discipline(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    if src.tree is None:
        return findings
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        init = next(
            (
                n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n.name == "__init__"
            ),
            None,
        )
        if init is None:
            continue
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            kind = _mutable_initializer(value)
            if kind is None:
                continue
            for tgt in targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                if src.annotation_at(node.lineno, "owned-by") is not None:
                    continue
                findings.append(Finding(
                    CHECK_ID, src.rel, node.lineno, node.col_offset,
                    "mutable state %r (%s) on a routing-state surface "
                    "(class %s) declares no writer: annotate "
                    "'# pstlint: owned-by=lock:<attr>' / "
                    "'owned-by=task:<fns>' for single-writer local state, "
                    "or 'owned-by=backend:<surface>' when the state is "
                    "replicated/coordinated through the router "
                    "StateBackend — undeclared state is how replica-"
                    "divergent second writers slip in"
                    % (tgt.attr, kind, cls.name),
                ))
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if src.tree is None:
            continue
        if _in_backend_scope(src.rel):
            findings.extend(_check_backend_discipline(src))
        owned = _collect_owned(src)
        if not owned:
            continue
        v = _Visitor(src, owned)
        v.visit(src.tree)
        findings.extend(v.findings)
    return findings
