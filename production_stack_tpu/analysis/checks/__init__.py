"""Check registry: one module per project invariant.

Each check module exposes ``CHECK_ID`` (the name used in suppression
comments and ``--checks``), ``DESCRIPTION`` (one line for ``--list-checks``)
and ``run(project) -> list[Finding]``.
"""

from __future__ import annotations

import types
from typing import Dict

from . import (
    app_scope,
    async_blocking,
    config_contract,
    hop_contract,
    lock_discipline,
    lock_order,
    metric_registry,
    recompile_risk,
    task_lifecycle,
)

ALL_CHECKS = (
    async_blocking,
    recompile_risk,
    hop_contract,
    metric_registry,
    lock_discipline,
    task_lifecycle,
    lock_order,
    app_scope,
    config_contract,
)

CHECKS_BY_ID: Dict[str, types.ModuleType] = {
    c.CHECK_ID: c for c in ALL_CHECKS
}
