"""app-scope: no module-level mutable state in ``router/``.

ROADMAP item 5(b): the router's last module singletons made two router
apps in one process *last-app-wins* — the second ``create_app`` silently
repointed discovery/routing/stats lookups at its own instances. The
refactor moved every such service into the context-bound app scope
(:mod:`production_stack_tpu.router.appscope`, bound to the ``aiohttp``
app by the factory, per request by the middleware, and per background
loop via task context inheritance). This check is the enforcement half:
the pattern cannot grow back.

Inside ``router/`` (every module under that package), two shapes fail:

1. **Module-level mutable container** — ``x = {}`` / ``[]`` / ``set()``
   / ``deque()`` / ``defaultdict()`` / ... assigned to a module-level
   name. Exemptions: ``UPPER_CASE`` names (read-only constants by
   convention — the check trusts the convention, not the mutability) and
   ``contextvars.ContextVar`` declarations (the sanctioned mechanism:
   values are per context, so apps cannot bleed).
2. **``global`` rebind** — any ``global X`` statement inside a function.
   That is the last-app-wins singleton idiom itself (``initialize_*``
   rebinding a module default); app-scoped services never need it.

Fix direction, not suppression direction: store the instance in the app
scope (``appscope.scoped_set``), inject it via the app factory
(``app["..."]``), or — for replicated state — flow it through the router
``StateBackend``. Suppress only with a reason naming why the state is
genuinely process-scoped (``# pstlint: disable=app-scope(<why>)``).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Project, SourceFile

CHECK_ID = "app-scope"
DESCRIPTION = (
    "module-level mutable state / global rebinds in router/ (app state "
    "must be app-factory injected or flow through the StateBackend)"
)

# collections.Counter is deliberately absent: the name collides with the
# prometheus_client Counter constructor, and Prometheus metric objects
# ARE process-global by design (one exposition registry per process).
_MUTABLE_CONSTRUCTORS = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "bytearray",
}
_SANCTIONED_CONSTRUCTORS = {"ContextVar"}


def _in_router(rel: str) -> bool:
    return "router" in rel.replace("\\", "/").split("/")


def _constructor_name(value: ast.AST) -> Optional[str]:
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(value, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(value, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
    return None


def _is_constant_name(name: str) -> bool:
    stripped = name.lstrip("_")
    return bool(stripped) and stripped == stripped.upper()


def _check_module_level(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    assert src.tree is not None
    for node in src.tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        ctor = _constructor_name(value)
        if ctor in _SANCTIONED_CONSTRUCTORS:
            continue
        if ctor not in _MUTABLE_CONSTRUCTORS:
            continue
        for tgt in targets:
            if not isinstance(tgt, ast.Name):
                continue
            if _is_constant_name(tgt.id):
                continue
            if tgt.id.startswith("__") and tgt.id.endswith("__"):
                continue  # module protocol names (__all__, ...)
            findings.append(Finding(
                CHECK_ID, src.rel, node.lineno, node.col_offset,
                "module-level mutable %s %r in router/: with two router "
                "apps in one process this is shared (or last-app-wins) "
                "state — move it into the app scope "
                "(appscope.scoped_set/app[...]), flow it through the "
                "StateBackend, or rename it UPPER_CASE if it is a "
                "genuinely read-only constant" % (ctor, tgt.id),
            ))
    return findings


def _check_global_rebinds(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    assert src.tree is not None
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Global):
            findings.append(Finding(
                CHECK_ID, src.rel, node.lineno, node.col_offset,
                "'global %s' in router/: rebinding a module default is "
                "the last-app-wins singleton idiom — the second app's "
                "initialize_* silently repoints every ambient lookup. "
                "Store the instance in the app scope instead "
                "(appscope.scoped_set; see router/appscope.py)"
                % ", ".join(node.names),
            ))
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if src.tree is None or not _in_router(src.rel):
            continue
        findings.extend(_check_module_level(src))
        findings.extend(_check_global_rebinds(src))
    return findings
