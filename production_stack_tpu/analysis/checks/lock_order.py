"""lock-order: no awaits while holding an annotated lock; no order cycles.

Builds on the ``owned-by=lock:<attr>`` annotations lock-discipline
introduced (PR 7/9): those name the locks that guard shared router/engine
state. Two new rules ride the same grammar:

1. **await-under-lock** — inside a ``with``/``async with`` region that
   acquires an annotated lock, no ``await`` may appear (nested function
   bodies excluded — they run elsewhere). For an ``asyncio`` lock this is
   a latency/consistency hazard: the holder parks mid-critical-section
   and every other task serializes behind a suspended coroutine (the
   hashtrie walk rule — materialize, release, THEN await — exists
   precisely to avoid this). For a *sync* ``threading`` lock acquired in
   a coroutine it is worse: the lock is held across a suspension point on
   the event-loop thread, and any other coroutine trying to take it
   blocks the whole loop.
2. **lock-order** — every *nesting* of one annotated lock's region inside
   another's (same file or not) contributes a directed edge
   ``outer -> inner`` to a tree-wide acquisition-order graph; a cycle in
   that graph is an ABBA deadlock waiting for the right interleaving, and
   fails the lint naming the cycle.

Known limits (documented approximations): locks are identified by their
*attribute name* tree-wide — two unrelated locks that share a name merge
into one graph node (rename one), and hand-over-hand locking on a
hierarchy of SAME-named locks (the hashtrie's per-node ``lock``) is
deliberately exempt from the order graph (a self-edge is not an ABBA).
Suppress with ``# pstlint: disable=lock-order(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple, Union

from ..core import Finding, Project, SourceFile

CHECK_ID = "lock-order"
DESCRIPTION = (
    "no await inside an annotated-lock region; lock-acquisition-order "
    "graph must be acyclic"
)


def _lock_attrs(src: SourceFile) -> Set[str]:
    """Lock attribute names declared by ``owned-by=lock:<attr>``
    annotations in this file."""
    out: Set[str] = set()
    for ann in src.annotations.values():
        value = ann.get("owned-by")
        if value is None:
            continue
        kind, _, spec = value.partition(":")
        if kind.strip() == "lock" and spec.strip():
            out.add(spec.strip())
    return out


def _acquired_lock(item: ast.withitem, locks: Set[str]) -> Optional[str]:
    """The annotated lock attr this with-item acquires, if any: matches
    ``<recv>.<attr>`` and bare ``<attr>`` context expressions, including
    ``<lock>.acquire_timeout()``-style wrapper calls on the lock."""
    expr: ast.AST = item.context_expr
    if isinstance(expr, ast.Call):
        expr = expr.func
        if isinstance(expr, ast.Attribute):
            # with self._lock.something(): the receiver is the lock.
            if expr.attr not in locks:
                expr = expr.value
    if isinstance(expr, ast.Attribute) and expr.attr in locks:
        return expr.attr
    if isinstance(expr, ast.Name) and expr.id in locks:
        return expr.id
    return None


class _Visitor(ast.NodeVisitor):
    """Tracks the stack of held annotated locks; records awaits under
    them and nesting edges between them."""

    def __init__(self, src: SourceFile, locks: Set[str]) -> None:
        self.src = src
        self.locks = locks
        self.findings: List[Finding] = []
        # (attr, is_async_with) innermost-last.
        self.held: List[Tuple[str, bool]] = []
        # outer -> {inner}, with one witness site per edge.
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # -- scope handling ----------------------------------------------------

    def _visit_func(self, node: ast.AST) -> None:
        saved = self.held
        self.held = []  # a nested def's body runs outside this region
        self.generic_visit(node)
        self.held = saved

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func
    visit_Lambda = _visit_func

    # -- with regions ------------------------------------------------------

    def _visit_with(
        self, node: Union[ast.With, ast.AsyncWith], is_async: bool
    ) -> None:
        # Runtime order: item 1's context expr evaluates BEFORE any lock
        # of this statement is held, item 2's evaluates while item 1's
        # lock IS held, and so on — so each context expr is visited with
        # exactly the locks acquired so far on the held stack, then the
        # item's own lock (if annotated) is pushed for the rest.
        acquired: List[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            attr = _acquired_lock(item, self.locks)
            if attr is None:
                continue
            for outer, _ in self.held:
                if outer != attr:
                    self.edges.setdefault(
                        (outer, attr), (self.src.rel, node.lineno)
                    )
            acquired.append(attr)
            self.held.append((attr, is_async))
        for stmt in node.body:
            self.visit(stmt)
        if acquired:
            del self.held[-len(acquired):]

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node, is_async=True)

    # -- the await rule ----------------------------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        if self.held:
            attr, is_async = self.held[-1]
            if is_async:
                msg = (
                    "await while holding annotated asyncio lock %r: the "
                    "critical section parks mid-flight and every waiter "
                    "serializes behind a suspended coroutine — copy what "
                    "you need, release, then await (hashtrie walk rule)"
                    % attr
                )
            else:
                msg = (
                    "await while holding annotated SYNC lock %r: the "
                    "thread lock stays held across a suspension point, so "
                    "any coroutine contending for it blocks the entire "
                    "event loop" % attr
                )
            self.findings.append(Finding(
                CHECK_ID, self.src.rel, node.lineno, node.col_offset, msg
            ))
        self.generic_visit(node)


def _find_cycle(
    edges: Dict[Tuple[str, str], Tuple[str, int]]
) -> Optional[List[str]]:
    """First cycle in the order graph (DFS), as the node path, or None."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    path: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        path.append(n)
        for m in sorted(graph[n]):
            if color[m] == GRAY:
                return path[path.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc is not None:
                    return cyc
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc is not None:
                return cyc
    return None


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    all_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for src in project.files:
        if src.tree is None:
            continue
        locks = _lock_attrs(src)
        if not locks:
            continue
        v = _Visitor(src, locks)
        v.visit(src.tree)
        findings.extend(v.findings)
        for edge, site in v.edges.items():
            all_edges.setdefault(edge, site)
    cycle = _find_cycle(all_edges)
    if cycle is not None:
        # Attribute the finding to a witness edge on the cycle.
        first_edge = (cycle[0], cycle[1])
        rel, line = all_edges.get(
            first_edge, next(iter(all_edges.values()))
        )
        findings.append(Finding(
            CHECK_ID, rel, line, 0,
            "lock-acquisition-order cycle: %s — two tasks taking these "
            "locks in opposite orders deadlock under the right "
            "interleaving; pick one global order and refactor the "
            "acquisition against it" % " -> ".join(cycle),
        ))
    return findings
