"""recompile-risk: every jitted dispatch is covered by the warmup lattice.

PR 6 killed the 120 s live-traffic p99 by enumerating the padded
shape-bucket lattice in ``engine/precompile.py`` and compiling it before
``/ready`` flips. That guarantee is structural, not magical: it holds
exactly as long as (a) every jitted dispatch derives its telemetry shape
key through the registered bucket helpers (so warmup and live traffic
land on the SAME key and the compile-detection registry treats warmed
shapes as seen), and (b) every dispatch's bucket family is enumerated by
``enumerate_lattice``. A new jit site, or a family quietly dropped from
the enumeration, reintroduces the cold tail with zero failing tests —
until a bench run eats it. This check fails the diff instead.

Rules (scope: ``engine/``):

1. **Lattice families.** ``enumerate_lattice`` in ``precompile.py`` must
   construct ``Bucket("<kind>", ...)`` literals; the set of kinds is the
   registered family set.
2. **Dispatch families.** Every ``ENGINE_TELEMETRY.record_dispatch`` /
   ``_record_warmup`` call site's bucket family — derived from the
   ``batch_bucket`` label grammar (``b{N}`` decode, ``b{N}xn{S}``
   decode_burst, ``b{N}xt{C}`` prefill, ``b{N}xk{K}`` spec_verify,
   ``t{T}`` encode) — must be a registered family.
3. **Shape keys.** The ``key`` argument of every dispatch-recording call
   must derive from a registered bucket helper (``_tel_key`` /
   ``_prefill_tel``), be a tuple rooted at ``self._tel_scope``, or be
   forwarded by a registered forwarder (``_record_warmup``).
4. **Jit registration.** Every ``jax.jit(...)`` call site in ``engine/``
   must carry ``# pstlint: jit-family=<family>[,<family>...]`` naming
   registered families the warmup lattice drives through it (on the call
   line or the line above), or a justified suppression for deliberate
   one-time compiles.
5. **Warmup drivers.** For every registered family, the runner must
   define ``_warmup_<family>`` so the lattice walk can actually compile
   it.

Suppress with ``# pstlint: disable=recompile-risk(<reason>)``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import (
    Finding,
    FunctionStack,
    Project,
    SourceFile,
    assignments_in,
    dotted_name,
    keyword_arg,
    literal_str,
)

CHECK_ID = "recompile-risk"
DESCRIPTION = (
    "jitted dispatches must use registered shape-key helpers and be "
    "covered by precompile.py's lattice enumeration"
)

_KEY_HELPERS = {"_tel_key", "_prefill_tel"}
_KEY_FORWARDERS = {"_record_warmup"}
_DISPATCH_FUNCS = {"record_dispatch", "_record_warmup"}
_SCOPE_ATTR = "_tel_scope"

# The shape_bucket label grammar (mirrors Bucket.label in precompile.py).
_LABEL_FAMILIES: Tuple[Tuple[re.Pattern, str], ...] = (
    (re.compile(r"^b\{?.*xn"), "decode_burst"),
    (re.compile(r"^b\{?.*xt"), "prefill"),
    (re.compile(r"^b\{?.*xk"), "spec_verify"),
    (re.compile(r"^b"), "decode"),
    (re.compile(r"^t"), "encode"),
)


def _label_pattern(node: ast.AST) -> Optional[str]:
    """Static skeleton of a bucket label: literal parts of an f-string
    with ``{`` marking interpolations (``f"b{B}xn{n}"`` -> ``b{xn{``)."""
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("{")
        return "".join(parts)
    lit = literal_str(node)
    return lit


def _family_of_label(pattern: str) -> Optional[str]:
    for rx, family in _LABEL_FAMILIES:
        if rx.search(pattern):
            return family
    return None


def lattice_families(precompile: SourceFile) -> Tuple[Set[str], int]:
    """(families constructed inside enumerate_lattice, its line)."""
    families: Set[str] = set()
    line = 1
    if precompile.tree is None:
        return families, line
    for node in ast.walk(precompile.tree):
        if isinstance(node, ast.FunctionDef) and node.name == "enumerate_lattice":
            line = node.lineno
            for call in ast.walk(node):
                if (
                    isinstance(call, ast.Call)
                    and (dotted_name(call.func) or "").split(".")[-1] == "Bucket"
                    and call.args
                ):
                    kind = literal_str(call.args[0])
                    if kind is None:
                        kind = next((
                            literal_str(kw.value) for kw in call.keywords
                            if kw.arg == "kind"
                        ), None)
                    if kind:
                        families.add(kind)
    return families, line


class _DispatchVisitor(FunctionStack):
    """Collects dispatch-recording call sites and jit call sites."""

    def __init__(self, src: SourceFile) -> None:
        super().__init__()
        self.src = src
        self.dispatches: List[Tuple[ast.Call, Optional[ast.AST]]] = []
        self.jit_sites: List[ast.Call] = []

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        last = (name or "").split(".")[-1]
        if last in _DISPATCH_FUNCS:
            self.dispatches.append((node, self.current_function))
        if last == "jit" and name in ("jax.jit", "jit"):
            self.jit_sites.append(node)
        self.generic_visit(node)


def _is_registered_key(
    node: ast.AST, func: Optional[ast.AST], depth: int = 0
) -> bool:
    """Does the shape-key expression derive from a registered helper?"""
    if depth > 3:
        return False
    if isinstance(node, ast.Call):
        last = (dotted_name(node.func) or "").split(".")[-1]
        return last in _KEY_HELPERS
    if isinstance(node, ast.Tuple) and node.elts:
        head = dotted_name(node.elts[0])
        return head is not None and head.endswith("." + _SCOPE_ATTR)
    if isinstance(node, ast.Name) and func is not None:
        # Parameter of a registered forwarder?
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if func.name in _KEY_FORWARDERS:
                params = {a.arg for a in func.args.args}
                if node.id in params:
                    return True
        rhs = assignments_in(func).get(node.id)
        if rhs is not None and not (
            isinstance(rhs, ast.Name) and rhs.id == node.id
        ):
            return _is_registered_key(rhs, func, depth + 1)
    return False


def _dispatch_family(
    call: ast.Call, func: Optional[ast.AST]
) -> Tuple[Optional[str], Optional[str]]:
    """(family, how) for a dispatch call, from the batch_bucket label
    grammar, falling back to the literal ``kind`` argument."""
    bucket = keyword_arg(call, "batch_bucket")
    if bucket is None and len(call.args) >= 4:
        bucket = call.args[3]
    if bucket is not None:
        if isinstance(bucket, ast.Name) and func is not None:
            rhs = assignments_in(func).get(bucket.id)
            if rhs is not None:
                bucket = rhs
        pattern = _label_pattern(bucket)
        if pattern is not None:
            fam = _family_of_label(pattern)
            if fam is not None:
                return fam, "label %r" % pattern
    kind = literal_str(call.args[0]) if call.args else None
    if kind is not None:
        return kind, "kind literal %r" % kind
    return None, None


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    engine_files = [
        f for f in project.in_dir("engine") if f.tree is not None
    ]
    if not engine_files:
        return findings
    # Cross-file anchors resolve from the repo root so a subset lint
    # (a single engine file) sees the same lattice and warmup drivers a
    # full-tree lint does.
    precompile = project.resolve("engine/precompile.py")
    if precompile is None:
        # An engine without a lattice enumeration has no warmup story at
        # all — flag once, on any engine file.
        findings.append(Finding(
            CHECK_ID, engine_files[0].rel, 1, 0,
            "no engine/precompile.py found: jitted dispatches have no "
            "ahead-of-time lattice to be covered by",
        ))
        return findings
    runner = project.resolve("engine/runner.py")
    anchor_rels = {f.rel for f in engine_files}
    for anchor in (precompile, runner):
        if anchor is not None and anchor.rel not in anchor_rels:
            engine_files.append(anchor)
            anchor_rels.add(anchor.rel)

    families, lattice_line = lattice_families(precompile)
    if not families:
        findings.append(Finding(
            CHECK_ID, precompile.rel, lattice_line, 0,
            "enumerate_lattice constructs no Bucket(<kind>) literals — "
            "the warmup lattice is empty and every live shape recompiles",
        ))

    warmup_methods: Set[str] = set()
    for src in engine_files:
        tree = src.tree
        if tree is None:  # a resolved anchor may fail to parse
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name.startswith("_warmup_"):
                    warmup_methods.add(node.name[len("_warmup_"):])

    for src in engine_files:
        tree = src.tree
        if tree is None:
            continue
        v = _DispatchVisitor(src)
        v.visit(tree)

        for call, func in v.dispatches:
            last = (dotted_name(call.func) or "").split(".")[-1]
            # Shape-key derivation (rule 3). record_dispatch(kind, key, ...)
            # and _record_warmup(kind, key, seconds, label) both carry the
            # key at positional index 1.
            key = call.args[1] if len(call.args) >= 2 else keyword_arg(call, "key")
            if key is None or not _is_registered_key(key, func):
                findings.append(Finding(
                    CHECK_ID, src.rel, call.lineno, call.col_offset,
                    "%s call's shape key does not derive from a registered "
                    "bucket helper (%s) — warmup and live traffic would "
                    "disagree on shape identity and the compile registry "
                    "stops being trustworthy"
                    % (last, "/".join(sorted(_KEY_HELPERS))),
                ))
            # Family coverage (rule 2). Registered forwarders relay their
            # caller's kind/label parameters verbatim — the family is
            # checked at each caller, not inside the forwarder.
            if (
                isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef))
                and func.name in _KEY_FORWARDERS
            ):
                continue
            family, how = _dispatch_family(call, func)
            if family is None:
                findings.append(Finding(
                    CHECK_ID, src.rel, call.lineno, call.col_offset,
                    "%s call's bucket family is not statically resolvable "
                    "(batch_bucket is neither an f-string label nor "
                    "traceable) — annotate or restructure so the lattice "
                    "coverage is checkable" % last,
                ))
            elif families and family not in families:
                findings.append(Finding(
                    CHECK_ID, src.rel, call.lineno, call.col_offset,
                    "dispatch family %r (from %s) is not enumerated by "
                    "enumerate_lattice in %s — live traffic on this path "
                    "compiles AFTER /ready flips (the BENCH_r05 120 s p99 "
                    "class of bug)" % (family, how, precompile.rel),
                ))

        # Jit registration (rule 4).
        for call in v.jit_sites:
            ann = src.annotation_at(call.lineno, "jit-family")
            if ann is None:
                findings.append(Finding(
                    CHECK_ID, src.rel, call.lineno, call.col_offset,
                    "jax.jit call site carries no '# pstlint: "
                    "jit-family=<family>' annotation — new jit sites must "
                    "name the lattice family whose warmup compiles them "
                    "(or carry a justified suppression for a deliberate "
                    "one-time compile)",
                ))
                continue
            for fam in (f.strip() for f in ann.split(",")):
                if families and fam not in families:
                    findings.append(Finding(
                        CHECK_ID, src.rel, call.lineno, call.col_offset,
                        "jit-family annotation names %r, which "
                        "enumerate_lattice does not construct — either "
                        "the family was removed from the lattice (cold "
                        "tail regression) or the annotation is stale"
                        % fam,
                    ))

    # Warmup drivers (rule 5).
    for fam in sorted(families):
        if fam not in warmup_methods:
            findings.append(Finding(
                CHECK_ID, precompile.rel, lattice_line, 0,
                "lattice family %r has no _warmup_%s driver in the runner "
                "— enumerate_lattice promises coverage the warmup walk "
                "cannot deliver" % (fam, fam),
            ))
    return findings
