"""config-contract: flags, helm values, schema, templates and docs agree.

The deployment surface of this project is five-layered: the router's
argparse flags (``router/parser.py``), the engine's
:class:`EngineConfig` fields, ``helm/values.yaml``, the values schema
(``helm/values.schema.json``), the deployment templates that turn values
into flags, and the docs flag tables. Before this check they drifted
silently — a values knob the template never emitted was "configured"
and ignored, a flag default changed without its values twin, a schema
key outlived its knob. Each of those is a real user-facing bug.

:mod:`production_stack_tpu.analysis.config_registry` is the single
source of truth; this check proves it against every surface, both
directions:

- **parser <-> registry**: every router ``add_argument`` flag has a
  :class:`ConfigSpec`; every spec's flag exists in the parser.
- **helm-scoped flags**: the values path exists in values.yaml AND in
  the schema, the template emits the flag, and the parser default equals
  the values.yaml default (``default_differs`` documents deliberate
  divergence — empty reason = drift).
- **cli-only flags**: NOT emitted by any template (emission means the
  flag silently grew a helm surface and must be reclassified).
- **reverse helm sweep**: every ``routerSpec.*`` leaf in values.yaml and
  in the schema is claimed by a spec or by ``ROUTER_HELM_NON_FLAG``;
  schema keys must also exist in values.yaml (a schema-only key is a
  ghost knob).
- **engine**: every ``EngineConfig`` field has an
  :class:`EngineFieldSpec` (and vice versa), declared flags exist in
  ``engine/server.py``'s parser, helm-backed fields are in the schema
  and emitted by the engine template, and values.yaml engineConfig
  defaults match the dataclass defaults unless reasoned.
- **docs**: every router flag's ``doc`` file mentions the flag.

The registry is executed from the scanned tree (stdlib-only module), so
fixtures can carry their own registry; helm/docs anchors resolve from
the project root, so subset lints see the same contract a full lint
does. Suppress with ``# pstlint: disable=config-contract(<reason>)``.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import simpleyaml
from ..core import Finding, Project, SourceFile

CHECK_ID = "config-contract"
DESCRIPTION = (
    "router flags / EngineConfig fields <-> config_registry <-> helm "
    "values/schema/templates <-> docs, both directions"
)

_REGISTRY_REL = "analysis/config_registry.py"
_PARSER_REL = "router/parser.py"
_ENGINE_CONFIG_REL = "engine/config.py"
_ENGINE_SERVER_REL = "engine/server.py"
_VALUES_REL = "helm/values.yaml"
_SCHEMA_REL = "helm/values.schema.json"


def _flag_re(flag: str) -> "re.Pattern[str]":
    return re.compile(r"(?<![\w-])%s(?![\w-])" % re.escape(flag))


def _emits(template_text: str, flag: str) -> bool:
    return bool(_flag_re(flag).search(template_text))


class _ParsedFlag:
    def __init__(self, flag: str, default: Any, action: Optional[str],
                 line: int) -> None:
        self.flag = flag
        self.default = default
        self.action = action
        self.line = line


def parser_flags(src: SourceFile) -> Dict[str, _ParsedFlag]:
    """flag -> (default, action, line) from ``add_argument`` calls."""
    out: Dict[str, _ParsedFlag] = {}
    if src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            continue
        names = [
            a.value for a in node.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        if not names or not names[0].startswith("--"):
            continue
        default: Any = None
        action: Optional[str] = None
        for kw in node.keywords:
            if kw.arg == "default":
                try:
                    default = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    default = None
            elif kw.arg == "action" and isinstance(kw.value, ast.Constant):
                action = str(kw.value.value)
        if action == "store_true" and default is None:
            default = False
        out[names[0]] = _ParsedFlag(names[0], default, action, node.lineno)
    return out


def parser_option_strings(src: SourceFile) -> List[str]:
    """Every option string (including aliases) across add_argument calls."""
    out: List[str] = []
    if src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            out.extend(
                a.value for a in node.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str)
            )
    return out


def engine_config_fields(src: SourceFile) -> Dict[str, Tuple[Any, int]]:
    """field -> (default, line) from the EngineConfig dataclass body."""
    out: Dict[str, Tuple[Any, int]] = {}
    if src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "EngineConfig"):
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                default: Any = None
                if stmt.value is not None:
                    try:
                        default = ast.literal_eval(stmt.value)
                    except (ValueError, SyntaxError):
                        default = None
                out[stmt.target.id] = (default, stmt.lineno)
    return out


def _exec_registry(src: SourceFile) -> Optional[Dict[str, Any]]:
    """Execute the (stdlib-only) registry module from the scanned tree so
    fixtures can carry their own registry. A real (temporary) module
    entry is needed because ``@dataclass`` resolves string annotations
    through ``sys.modules[cls.__module__]``."""
    import sys
    import types

    mod_name = "pstlint_config_registry_under_lint"
    module = types.ModuleType(mod_name)
    sys.modules[mod_name] = module
    try:
        code = compile(src.text, src.rel, "exec")
        exec(code, module.__dict__)  # noqa: S102 — our own registry module
    except Exception:
        return None
    finally:
        sys.modules.pop(mod_name, None)
    return dict(module.__dict__)


def _norm(value: Any) -> Any:
    """Normalize for default comparison: None ≈ "" ≈ [], numbers by
    value (5 == 5.0), everything else as-is."""
    if value is None or value == "" or value == []:
        return None
    if isinstance(value, bool):
        return value
    if isinstance(value, (int, float)):
        return float(value)
    return value


def _schema_has(schema: Any, path: str) -> bool:
    cur = schema
    for part in path.split("."):
        take_first = part.endswith("[]")
        key = part[:-2] if take_first else part
        if not isinstance(cur, dict):
            return False
        props = cur.get("properties")
        if not isinstance(props, dict) or key not in props:
            return False
        cur = props[key]
        if take_first:
            if not isinstance(cur, dict) or "items" not in cur:
                return False
            cur = cur["items"]
    return True


def _read_text(root: Path, rel: str) -> Optional[str]:
    path = root / rel
    if not path.exists():
        return None
    return path.read_text(encoding="utf-8")


def _claimed(path: str, claimed_paths: Sequence[str],
             allow_prefixes: Sequence[str]) -> bool:
    for c in claimed_paths:
        if path == c or path.startswith(c + "."):
            return True
    for p in allow_prefixes:
        if path == p or path.startswith(p + "."):
            return True
    return False


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []

    parser_src = project.resolve(_PARSER_REL)
    registry_src = project.resolve(_REGISTRY_REL)
    if parser_src is None:
        return findings  # nothing to check against in this tree
    if registry_src is None:
        findings.append(Finding(
            CHECK_ID, parser_src.rel, 1, 0,
            "router flags exist but no %s declares the configuration "
            "contract" % _REGISTRY_REL,
        ))
        return findings
    namespace = _exec_registry(registry_src)
    if namespace is None:
        findings.append(Finding(
            CHECK_ID, registry_src.rel, 1, 0,
            "config registry failed to execute — it must stay a "
            "stdlib-only module the analyzer can load on a bare checkout",
        ))
        return findings
    router_specs = list(namespace.get("ROUTER_FLAGS") or ())
    engine_specs = list(namespace.get("ENGINE_FIELDS") or ())
    non_flag = tuple(namespace.get("ROUTER_HELM_NON_FLAG") or ())

    flags = parser_flags(parser_src)
    by_flag = {s.flag: s for s in router_specs}

    # -- parser <-> registry, both directions ------------------------------
    for flag, parsed in sorted(flags.items()):
        if flag not in by_flag:
            findings.append(Finding(
                CHECK_ID, parser_src.rel, parsed.line, 0,
                "flag %r has no ConfigSpec in %s — declare it (helm-backed, "
                "template-derived, or cli-only with a reason) so the helm/"
                "schema/docs surfaces stay provably in sync" % (
                    flag, registry_src.rel),
            ))
    for spec in router_specs:
        if spec.flag not in flags:
            findings.append(Finding(
                CHECK_ID, registry_src.rel, 1, 0,
                "ConfigSpec %r names a flag router/parser.py does not "
                "define — stale declaration" % spec.flag,
            ))

    # -- helm anchors ------------------------------------------------------
    values_text = _read_text(project.root, _VALUES_REL)
    schema_text = _read_text(project.root, _SCHEMA_REL)
    values: Any = None
    schema: Any = None
    if values_text is not None:
        try:
            values = simpleyaml.parse(values_text)
        except simpleyaml.SimpleYamlError as e:
            findings.append(Finding(
                CHECK_ID, registry_src.rel, 1, 0,
                "%s is outside the analyzer's YAML subset (%s) — simplify "
                "it or extend analysis/simpleyaml.py" % (_VALUES_REL, e),
            ))
    if schema_text is not None:
        try:
            schema = json.loads(schema_text)
        except ValueError:
            findings.append(Finding(
                CHECK_ID, registry_src.rel, 1, 0,
                "%s is not valid JSON" % _SCHEMA_REL,
            ))
    templates: Dict[str, Optional[str]] = {}

    def template_text(rel: Optional[str]) -> Optional[str]:
        if rel is None:
            return None
        if rel not in templates:
            templates[rel] = _read_text(project.root, rel)
        return templates[rel]

    docs: Dict[str, Optional[str]] = {}

    def doc_text(rel: str) -> Optional[str]:
        if rel not in docs:
            docs[rel] = _read_text(project.root, rel)
        return docs[rel]

    all_template_text = ""
    for rel in (
        namespace.get("ROUTER_TEMPLATE"), namespace.get("ENGINE_TEMPLATE")
    ):
        text = template_text(rel if isinstance(rel, str) else None)
        if text:
            all_template_text += text

    # -- per-spec surface checks ------------------------------------------
    helm_scope = str(namespace.get("HELM", "helm"))
    tpl_scope = str(namespace.get("TEMPLATE", "template"))
    cli_scope = str(namespace.get("CLI_ONLY", "cli-only"))
    claimed_router_paths = [
        s.helm for s in router_specs if s.scope == helm_scope and s.helm
    ]

    for spec in router_specs:
        parsed = flags.get(spec.flag)
        if parsed is None:
            continue  # already reported as stale
        tpl = template_text(spec.template)
        if spec.scope == helm_scope:
            if not spec.helm:
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "helm-scoped spec %r declares no values path" % spec.flag,
                ))
                continue
            if values is not None:
                found, helm_default = simpleyaml.resolve(values, spec.helm)
                if not found:
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "spec %r claims helm path %r but %s has no such "
                        "key — users cannot set the knob the contract "
                        "promises" % (spec.flag, spec.helm, _VALUES_REL),
                    ))
                elif not spec.default_differs and not spec.negation_of:
                    if _norm(helm_default) != _norm(parsed.default):
                        findings.append(Finding(
                            CHECK_ID, parser_src.rel, parsed.line, 0,
                            "default drift for %s: parser default %r != "
                            "values.yaml %s default %r — change both "
                            "together, or record the reason in the spec's "
                            "default_differs" % (
                                spec.flag, parsed.default, spec.helm,
                                helm_default),
                        ))
            if schema is not None and not _schema_has(schema, spec.helm):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "spec %r: helm path %r is absent from %s — helm lint "
                    "would reject the documented knob" % (
                        spec.flag, spec.helm, _SCHEMA_REL),
                ))
            emit = getattr(spec, "emit", None) or spec.flag
            if tpl is not None and not _emits(tpl, emit):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "spec %r: %s never emits %r — the values knob is "
                    "configured and silently ignored by the pod" % (
                        spec.flag, spec.template, emit),
                ))
        elif spec.scope == tpl_scope:
            if tpl is not None and not _emits(tpl, spec.flag):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "template-scoped spec %r: %s never emits the flag" % (
                        spec.flag, spec.template),
                ))
        elif spec.scope == cli_scope:
            if all_template_text and _emits(all_template_text, spec.flag):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "cli-only spec %r IS emitted by a helm template — it "
                    "grew a helm surface; reclassify it as helm/template "
                    "scoped with the proper values path" % spec.flag,
                ))
        else:
            findings.append(Finding(
                CHECK_ID, registry_src.rel, 1, 0,
                "spec %r has unknown scope %r" % (spec.flag, spec.scope),
            ))
        # Docs row (every scope): the doc file must mention the flag.
        dtext = doc_text(spec.doc)
        if dtext is not None and not _flag_re(spec.flag).search(dtext):
            findings.append(Finding(
                CHECK_ID, registry_src.rel, 1, 0,
                "flag %s is not documented in %s (its declared doc "
                "file) — the flag table is the operator contract" % (
                    spec.flag, spec.doc),
            ))

    # -- reverse sweep: routerSpec values/schema leaves --------------------
    if values is not None and isinstance(values, dict):
        router_values = values.get("routerSpec")
        for path in simpleyaml.leaf_paths(
            router_values if isinstance(router_values, dict) else {},
            "routerSpec",
        ):
            if not _claimed(path, claimed_router_paths, non_flag):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "values.yaml knob %r is claimed by no ConfigSpec and "
                    "is not in ROUTER_HELM_NON_FLAG — a knob no flag "
                    "consumes is configuration theater" % path,
                ))
    if schema is not None and values is not None:
        props = schema.get("properties") if isinstance(schema, dict) else None
        router_schema = (
            props.get("routerSpec") if isinstance(props, dict) else None
        )

        def schema_leaves(node: Any, prefix: str) -> List[str]:
            out: List[str] = []
            if isinstance(node, dict) and isinstance(
                node.get("properties"), dict
            ):
                for key, sub in node["properties"].items():
                    out.extend(
                        schema_leaves(sub, "%s.%s" % (prefix, key))
                    )
            else:
                out.append(prefix)
            return out

        if isinstance(router_schema, dict):
            for path in schema_leaves(router_schema, "routerSpec"):
                if not _claimed(path, claimed_router_paths, non_flag):
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "schema key %r is claimed by no ConfigSpec and is "
                        "not in ROUTER_HELM_NON_FLAG" % path,
                    ))
                    continue
                found, _ = simpleyaml.resolve(values, path)
                if not found:
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "schema key %r has no values.yaml counterpart — a "
                        "schema-only key is a ghost knob (add the default "
                        "to values.yaml or drop it from the schema)" % path,
                    ))

    # -- engine half -------------------------------------------------------
    engine_cfg_src = project.resolve(_ENGINE_CONFIG_REL)
    if engine_cfg_src is not None and engine_specs:
        fields = engine_config_fields(engine_cfg_src)
        by_field = {s.field: s for s in engine_specs}
        for name, (default, line) in sorted(fields.items()):
            if name not in by_field:
                findings.append(Finding(
                    CHECK_ID, engine_cfg_src.rel, line, 0,
                    "EngineConfig field %r has no EngineFieldSpec in %s"
                    % (name, registry_src.rel),
                ))
        engine_server_src = project.resolve(_ENGINE_SERVER_REL)
        engine_options = (
            parser_option_strings(engine_server_src)
            if engine_server_src is not None else []
        )
        engine_tpl_rel = namespace.get("ENGINE_TEMPLATE")
        engine_tpl = template_text(
            engine_tpl_rel if isinstance(engine_tpl_rel, str) else None
        )
        for spec in engine_specs:
            if spec.field not in fields:
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "EngineFieldSpec %r names a field EngineConfig does "
                    "not define — stale declaration" % spec.field,
                ))
                continue
            if (
                spec.flag is not None
                and engine_options
                and spec.flag not in engine_options
            ):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "EngineFieldSpec %r declares flag %r, which "
                    "engine/server.py's parser does not define" % (
                        spec.field, spec.flag),
                ))
            if spec.helm:
                if schema is not None and not _schema_has(schema, spec.helm):
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "EngineFieldSpec %r: helm path %r absent from %s"
                        % (spec.field, spec.helm, _SCHEMA_REL),
                    ))
                emit = spec.emit or spec.flag
                if (
                    engine_tpl is not None
                    and emit is not None
                    and not _emits(engine_tpl, emit)
                ):
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "EngineFieldSpec %r: engine template never emits "
                        "%r — the %r values knob is configured and "
                        "silently ignored" % (spec.field, emit, spec.helm),
                    ))
                if values is not None and not spec.default_differs:
                    found, helm_default = simpleyaml.resolve(values, spec.helm)
                    if found and _norm(helm_default) != _norm(
                        fields[spec.field][0]
                    ):
                        findings.append(Finding(
                            CHECK_ID, engine_cfg_src.rel,
                            fields[spec.field][1], 0,
                            "default drift for EngineConfig.%s: dataclass "
                            "default %r != values.yaml %s default %r — "
                            "change both together or record "
                            "default_differs" % (
                                spec.field, fields[spec.field][0],
                                spec.helm, helm_default),
                        ))

    # -- operator autoscale knobs (CRD surfaces) ---------------------------
    # spec.autoscale.* lives in the TPURuntime CRD, not helm (the chart
    # renders no CRs); its four surfaces are the CRD schema, the C++
    # reconciler consuming the key, the committed sample CR, and the
    # autoscaling doc. Proved both directions, same philosophy as the
    # routerSpec sweep above.
    autoscale_specs = list(namespace.get("AUTOSCALE_KEYS") or ())
    if autoscale_specs:
        crd_rel = str(namespace.get("OPERATOR_CRD") or "operator/crds/crds.yaml")
        cc_rel = str(
            namespace.get("OPERATOR_RECONCILERS")
            or "operator/src/reconcilers.cc"
        )
        sample_rel = str(
            namespace.get("OPERATOR_SAMPLE")
            or "operator/config/samples/tpuruntime.yaml"
        )
        adoc_rel = str(namespace.get("AUTOSCALE_DOC") or "docs/autoscaling.md")
        crd_text = _read_text(project.root, crd_rel)
        cc_text = _read_text(project.root, cc_rel)
        sample_text = _read_text(project.root, sample_rel)
        adoc_text = _read_text(project.root, adoc_rel)
        declared = {s.key for s in autoscale_specs}

        def _yaml_key(text: str, key: str) -> bool:
            return bool(re.search(
                r"^\s*%s\s*:" % re.escape(key), text, re.MULTILINE
            ))

        for spec in autoscale_specs:
            if crd_text is not None and not _yaml_key(crd_text, spec.key):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "AutoscaleKeySpec %r is absent from %s — the CRD schema "
                    "would reject the documented knob" % (spec.key, crd_rel),
                ))
            if cc_text is not None and '"%s"' % spec.key not in cc_text:
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "AutoscaleKeySpec %r is never read by %s — a CRD knob "
                    "no reconciler consumes is configuration theater" % (
                        spec.key, cc_rel),
                ))
            if sample_text is not None and not _yaml_key(sample_text, spec.key):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "AutoscaleKeySpec %r is missing from the sample CR %s — "
                    "the sample is the values.yaml analogue for CRD knobs" % (
                        spec.key, sample_rel),
                ))
            if adoc_text is not None and not _flag_re(spec.key).search(
                adoc_text
            ):
                findings.append(Finding(
                    CHECK_ID, registry_src.rel, 1, 0,
                    "autoscale knob %r is not documented in %s — the knob "
                    "table is the operator contract" % (spec.key, adoc_rel),
                ))
        # Reverse direction 1: every key under the CRD's autoscale block
        # must be declared.
        if crd_text is not None:
            for key in _crd_autoscale_keys(crd_text):
                if key not in declared and key != "type":
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "CRD autoscale key %r has no AutoscaleKeySpec in "
                        "%s — undeclared knob" % (key, registry_src.rel),
                    ))
        # Reverse direction 2: every spec.autoscale read in the reconciler
        # (`as.at("<key>")`) must be declared.
        if cc_text is not None:
            for key in sorted(set(re.findall(r'\bas\.at\("(\w+)"\)', cc_text))):
                if key not in declared:
                    findings.append(Finding(
                        CHECK_ID, registry_src.rel, 1, 0,
                        "%s reads spec.autoscale.%s but no AutoscaleKeySpec "
                        "declares it — undeclared knob" % (cc_rel, key),
                    ))
    return findings


def _crd_autoscale_keys(crd_text: str) -> List[str]:
    """Keys under the TPURuntime ``autoscale.properties`` block, by
    indentation (the full CRD is outside simpleyaml's subset)."""
    lines = crd_text.splitlines()
    keys: List[str] = []
    i = 0
    while i < len(lines):
        m = re.match(r"^(\s*)autoscale:\s*$", lines[i])
        if not m:
            i += 1
            continue
        base = len(m.group(1))
        i += 1
        prop_indent = None
        while i < len(lines):
            line = lines[i]
            stripped = line.strip()
            if stripped and not stripped.startswith("#"):
                indent = len(line) - len(line.lstrip())
                if indent <= base:
                    break  # dedent: autoscale block ended
                pm = re.match(r"^(\s*)properties:\s*$", line)
                if pm:
                    prop_indent = len(pm.group(1))
                elif (
                    prop_indent is not None
                    and indent == prop_indent + 2
                ):
                    km = re.match(r"^\s*(\w+)\s*:", line)
                    if km:
                        keys.append(km.group(1))
            i += 1
    return keys
