"""hop-contract: every router hop carries the propagation headers, every
error response carries X-Request-Id.

PRs 2-3 made three headers load-bearing on every router->engine hop:
``X-PST-Deadline-Ms`` (budget shedding), ``traceparent`` (one W3C trace
across retries/hedges/resume legs) and ``X-Request-Id`` (log/timeline
join key). An outbound request built by hand silently drops all three —
the engine still answers, nothing fails, and the request simply vanishes
from traces and stops honoring its deadline. Same story for error
responses: PR 3's contract is that every shed/error response names the
request id so a client can quote it back at support.

Two rules:

1. **Outbound headers** (files under ``router/``): any HTTP verb call on
   an aiohttp client session (``session.get/post/put/patch/delete/request``,
   or any receiver ending in ``session``/``sess``) must pass ``headers=``
   derived from a sanctioned builder — ``hop_headers`` (router/hop.py) or
   its request_service wrapper ``_trace_headers`` — either called inline
   or via a name assigned from one. Control-plane loops that originate
   traffic (canary probes, stats scrapes, discovery probes, k8s watches)
   carry file-level suppressions naming why no request context exists.
2. **Error responses** (files under ``router/``, ``obs/``,
   ``resilience/``): a ``web.json_response(...)`` / ``web.Response(...)``
   with a literal ``status=`` >= 400 must include ``X-Request-Id`` in its
   ``headers=`` — inline dict with the literal key, a name assigned from
   one, or a call to a sanctioned error-header builder
   (``error_headers`` / ``_error_headers``).

Suppress with ``# pstlint: disable=hop-contract(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import (
    Finding,
    FunctionStack,
    Project,
    SourceFile,
    assignments_in,
    dotted_name,
    keyword_arg,
    literal_str,
)

CHECK_ID = "hop-contract"
DESCRIPTION = (
    "outbound router hops must propagate deadline/trace/request-id "
    "headers; error responses must carry X-Request-Id"
)

_HTTP_VERBS = {"get", "post", "put", "patch", "delete", "request", "head"}
_SANCTIONED_HEADER_BUILDERS = {"hop_headers", "_trace_headers"}
_SANCTIONED_ERROR_BUILDERS = {"error_headers", "_error_headers"}
_REQUEST_ID_HEADER = "X-Request-Id"


def _is_session_receiver(recv: ast.AST) -> bool:
    """Heuristic: the receiver of a verb call is an HTTP client session.

    Matches names/attributes whose final component ends with ``session``
    or equals ``sess`` (the repo's naming convention for aiohttp client
    sessions), plus the ``aiohttp`` module itself."""
    name = dotted_name(recv)
    if name is None:
        return False
    last = name.split(".")[-1].lower()
    return (
        last.endswith("session") or last == "sess" or name == "aiohttp"
    )


def _builder_call(node: ast.AST, sanctioned: set) -> bool:
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name is not None and name.split(".")[-1] in sanctioned:
            return True
    return False


class _Visitor(FunctionStack):
    def __init__(self, src: SourceFile, check_hops: bool,
                 check_errors: bool) -> None:
        super().__init__()
        self.src = src
        self.check_hops = check_hops
        self.check_errors = check_errors
        self.findings: List[Finding] = []

    def _resolve(self, node: ast.AST) -> ast.AST:
        """One level of name->RHS resolution, searching the enclosing
        functions innermost-first (closures routinely capture headers
        built in the outer handler)."""
        if isinstance(node, ast.Name):
            for func in reversed(self.func_stack):
                rhs = assignments_in(func).get(node.id)
                if rhs is not None:
                    return rhs
        return node

    # -- rule 1: outbound hops --------------------------------------------

    def _check_hop(self, node: ast.Call) -> None:
        if not isinstance(node.func, ast.Attribute):
            return
        if node.func.attr not in _HTTP_VERBS:
            return
        if not _is_session_receiver(node.func.value):
            return
        headers = keyword_arg(node, "headers")
        if headers is not None:
            resolved = self._resolve(headers)
            if _builder_call(resolved, _SANCTIONED_HEADER_BUILDERS):
                return
            # hop_headers(...) piped through a further dict call or
            # conditional is out of reach for one-level resolution; the
            # site then needs a suppression explaining itself.
        self.findings.append(Finding(
            CHECK_ID, self.src.rel, node.lineno, node.col_offset,
            "outbound %s.%s() does not pass headers built by "
            "hop_headers()/_trace_headers() — the deadline/trace/request-id "
            "contract (PRs 2-3) is dropped on this hop"
            % (dotted_name(node.func.value) or "session", node.func.attr),
        ))

    # -- rule 2: error responses ------------------------------------------

    def _error_status(self, node: ast.Call) -> Optional[int]:
        status = keyword_arg(node, "status")
        if isinstance(status, ast.Constant) and isinstance(status.value, int):
            return status.value if status.value >= 400 else None
        return None

    def _headers_carry_request_id(self, node: ast.AST) -> bool:
        node = self._resolve(node)
        if _builder_call(node, _SANCTIONED_ERROR_BUILDERS):
            return True
        if _builder_call(node, _SANCTIONED_HEADER_BUILDERS):
            return True
        if isinstance(node, ast.Dict):
            for key in node.keys:
                ks = literal_str(key) if key is not None else None
                if ks is not None and ks.lower() == _REQUEST_ID_HEADER.lower():
                    return True
        return False

    def _check_error_response(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name is None:
            return
        last = name.split(".")[-1]
        if last not in ("json_response", "Response", "HTTPException"):
            return
        status = self._error_status(node)
        if status is None:
            return
        headers = keyword_arg(node, "headers")
        if headers is not None and self._headers_carry_request_id(headers):
            return
        self.findings.append(Finding(
            CHECK_ID, self.src.rel, node.lineno, node.col_offset,
            "error response (status=%d) does not carry %s — clients and "
            "log correlation lose the request id on exactly the paths "
            "that need it (PR 3 contract)" % (status, _REQUEST_ID_HEADER),
        ))

    def visit_Call(self, node: ast.Call) -> None:
        if self.check_hops:
            self._check_hop(node)
        if self.check_errors:
            self._check_error_response(node)
        self.generic_visit(node)


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if src.tree is None:
            continue
        segs = src.rel.replace("\\", "/").split("/")
        check_hops = "router" in segs
        check_errors = any(p in segs for p in ("router", "obs", "resilience"))
        if not (check_hops or check_errors):
            continue
        v = _Visitor(src, check_hops, check_errors)
        v.visit(src.tree)
        findings.extend(v.findings)
    return findings
