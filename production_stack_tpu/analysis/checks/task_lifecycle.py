"""task-lifecycle: every spawned task is owned, observed, and cancellable.

``asyncio`` holds only *weak* references to tasks: a fire-and-forget
``asyncio.create_task(...)`` can be garbage-collected mid-await (the
PR 10 review found trie-eviction walks collectable mid-walk), and a task
whose exception nobody reads dies silently — the scrape/canary/gossip
loop is simply gone until the metrics flatline. This check makes the
lifecycle contract machine-checked at every ``create_task`` /
``ensure_future`` site tree-wide.

A site is compliant when ONE of the following holds:

1. **Owned**: the site carries ``# pstlint: task-owner=<name>`` (on the
   call's line or the line above) AND the enclosing function stores the
   task under ``<name>`` (attribute ``self.<name> = ...``, subscript
   ``app["<name>"] = ...``, or a registry call ``<name>.add(task)``) AND
   the file contains a cancellation path for ``<name>`` (a ``.cancel()``
   whose receiver resolves — through one level of local assignment or a
   for-loop target — to an expression mentioning ``<name>``).
2. **Awaited**: the task is bound to a local name that the enclosing
   function actually consumes again — ``await``, ``asyncio.gather`` /
   ``asyncio.wait`` / ``wait_for``, ``add_done_callback``, ``.result()``
   — so its exception has an observer. (A local that is *never read
   again* is fire-and-forget with extra steps.)
3. **Suppressed** with a reason
   (``# pstlint: disable=task-lifecycle(<why>)``).

The sanctioned helper :func:`production_stack_tpu.obs.tasks.spawn_owned`
satisfies the contract once, internally (strong registry reference +
logging done-callback), so call sites using it contain no raw
``create_task`` and need nothing.

Known limits (documented approximation, same spirit as lock-discipline):
name matching is textual within the declaring file; a cancellation path
in a *different* module is invisible — move it or suppress with the
location as the reason.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, SourceFile

CHECK_ID = "task-lifecycle"
DESCRIPTION = (
    "create_task/ensure_future sites must be owner-annotated (with a "
    "cancellation path), awaited, or via obs.tasks.spawn_owned"
)

_SPAWN_NAMES = {"create_task", "ensure_future"}


def _is_spawn(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute):
        return func.attr in _SPAWN_NAMES
    if isinstance(func, ast.Name):
        return func.id in _SPAWN_NAMES
    return False


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover — exotic node
        return ""


def _scoped_walk(func: ast.AST) -> List[ast.AST]:
    """Walk ``func``'s body without descending into nested function
    scopes (a nested def's locals are not this function's)."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


class _FuncInfo:
    """Per-function facts needed to judge the spawn sites inside it."""

    def __init__(self, func: ast.AST) -> None:
        self.func = func
        # Local name -> RHS expr (one level; for-loop targets map to the
        # iterable) for cancel-receiver resolution.
        self.assigns: Dict[str, ast.AST] = {}
        # Names read (Load ctx) with their line numbers.
        self.loads: List[Tuple[str, int]] = []
        self.awaited_names: List[str] = []
        for node in _scoped_walk(func):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.assigns[tgt.id] = node.value
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if isinstance(node.target, ast.Name):
                    self.assigns[node.target.id] = node.iter
            elif isinstance(node, ast.withitem):
                if isinstance(node.optional_vars, ast.Name):
                    self.assigns[node.optional_vars.id] = node.context_expr
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                self.loads.append((node.id, node.lineno))
            elif isinstance(node, ast.Await):
                inner = node.value
                if isinstance(inner, ast.Name):
                    self.awaited_names.append(inner.id)

    def reads_after(self, name: str, line: int) -> bool:
        return any(n == name and ln > line for n, ln in self.loads)


def _owner_stored(func: ast.AST, owner: str) -> bool:
    """Does the function store a task under ``owner``? (attribute /
    subscript assignment target, or an ``<owner>.add/append(...)`` call)."""
    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    if owner in _unparse(tgt):
                        return True
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("add", "append")
                and owner in _unparse(f.value)
            ):
                return True
    return False


def _file_cancels(src: SourceFile, owner: str) -> bool:
    """Does any ``.cancel()`` in the file target ``owner`` (directly, or
    through one level of local assignment / for-target resolution)?"""
    if src.tree is None:
        return False
    for fn in ast.walk(src.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        info: Optional[_FuncInfo] = None
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "cancel"
            ):
                continue
            recv = node.func.value
            text = _unparse(recv)
            if owner in text:
                return True
            if isinstance(recv, ast.Name):
                if info is None:
                    info = _FuncInfo(fn)
                resolved = info.assigns.get(recv.id)
                if resolved is not None and owner in _unparse(resolved):
                    return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.findings: List[Finding] = []
        self.func_stack: List[ast.AST] = []

    def _visit_func(self, node: ast.AST) -> None:
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func
    visit_Lambda = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        if _is_spawn(node):
            self._check_site(node)
        self.generic_visit(node)

    # -- the rule ----------------------------------------------------------

    def _check_site(self, call: ast.Call) -> None:
        owner = self.src.annotation_at(call.lineno, "task-owner")
        func = self.func_stack[-1] if self.func_stack else None
        if owner is not None:
            owner = owner.strip()
            stored = func is not None and _owner_stored(func, owner)
            if not stored and self.src.tree is not None:
                # Module-level spawn (rare) — search the whole module.
                stored = _owner_stored(self.src.tree, owner)
            if not stored:
                self.findings.append(Finding(
                    CHECK_ID, self.src.rel, call.lineno, call.col_offset,
                    "task-owner=%r is declared but the task is never stored "
                    "under %r here (assign to an attribute/key named %r or "
                    "add() it to that registry) — a dangling annotation is "
                    "an unowned task with paperwork" % (owner, owner, owner),
                ))
                return
            if not _file_cancels(self.src, owner):
                self.findings.append(Finding(
                    CHECK_ID, self.src.rel, call.lineno, call.col_offset,
                    "task stored under %r has no cancellation path in this "
                    "file: no '.cancel()' ever targets it, so app shutdown "
                    "leaks the task (add a close() that cancels it, or "
                    "suppress with the out-of-file canceller as the reason)"
                    % owner,
                ))
            return

        # No annotation: the site must bind a local the function consumes.
        parent = self._binding_name(call)
        if parent is None:
            self.findings.append(Finding(
                CHECK_ID, self.src.rel, call.lineno, call.col_offset,
                "fire-and-forget task: asyncio keeps only weak task refs "
                "(GC can collect it mid-await) and its exception is never "
                "observed — use obs.tasks.spawn_owned(), store it on an "
                "annotated owner ('# pstlint: task-owner=<attr>' with a "
                "cancellation path), or await/gather it",
            ))
            return
        if func is None:
            return  # module-level local binding: nothing to judge
        info = _FuncInfo(func)
        if parent in info.awaited_names or info.reads_after(
            parent, call.lineno
        ):
            return
        self.findings.append(Finding(
            CHECK_ID, self.src.rel, call.lineno, call.col_offset,
            "task bound to %r is never consumed again in this function "
            "(no await/gather/wait/add_done_callback/read) — its exception "
            "is unobserved and the reference dies with the frame; use "
            "obs.tasks.spawn_owned() or actually await it" % parent,
        ))

    def _binding_name(self, call: ast.Call) -> Optional[str]:
        """The local name the spawn's result is bound to, when the site is
        a simple ``name = create_task(...)`` / ``name = ensure_future(...)``
        (attribute/subscript targets require the task-owner annotation;
        other expression positions count as unbound)."""
        func = self.func_stack[-1] if self.func_stack else None
        scope = func if func is not None else self.src.tree
        if scope is None:
            return None
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and node.value is call:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        return tgt.id
                return None
            if isinstance(node, ast.AnnAssign) and node.value is call:
                if isinstance(node.target, ast.Name):
                    return node.target.id
                return None
        return None


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if src.tree is None:
            continue
        v = _Visitor(src)
        v.visit(src.tree)
        findings.extend(v.findings)
    return findings
