"""metric-registry: every pst metric is declared once, documented once.

``pst_*`` metric names are a public contract — dashboards, recording
rules, burn-rate alerts, bench assertions and operators' PromQL all key
on them. ``production_stack_tpu/obs/metric_registry.py`` is the single
declaration point; this check enforces the triangle:

1. **code -> registry**: every ``Counter("pst...")`` / ``Gauge`` /
   ``Histogram`` constructor in the tree must match a declared
   :class:`MetricSpec` (name AND kind — a counter redeclared as a gauge
   changes its exposition name and silently breaks every consumer).
2. **registry -> code**: a declared metric no constructor registers is
   stale — dashboards would chart a series that never exists.
3. **registry -> docs**: every declared metric's exposition name must
   appear in ``docs/observability.md`` (family wildcards like
   ``pst_resilience_*`` cover their prefix, as before).

The registry module is parsed by AST, not imported, so the check runs on
a bare checkout even if the package does not import.

Suppress with ``# pstlint: disable=metric-registry(<reason>)``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Project, SourceFile, dotted_name, literal_str

CHECK_ID = "metric-registry"
DESCRIPTION = (
    "pst metric constructors must match obs/metric_registry.py; "
    "declarations must be live and documented"
)

_REGISTRY_REL = "obs/metric_registry.py"
_DOC_REL = "docs/observability.md"
_CONSTRUCTORS = {"Counter": "counter", "Gauge": "gauge", "Histogram": "histogram"}
_WILDCARD_RE = re.compile(r"(pst[\w:]*)\*")


def declared_specs(src: SourceFile) -> Dict[str, Tuple[str, int]]:
    """name -> (kind, line) parsed from MetricSpec(...) literals."""
    out: Dict[str, Tuple[str, int]] = {}
    if src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if (dotted_name(node.func) or "").split(".")[-1] != "MetricSpec":
            continue
        args = list(node.args)
        name = literal_str(args[0]) if args else None
        kind: Optional[str] = None
        if len(args) >= 2:
            # Second positional is the kind: either a string literal or
            # one of the COUNTER/GAUGE/HISTOGRAM module constants.
            kind = literal_str(args[1]) or {
                "COUNTER": "counter", "GAUGE": "gauge", "HISTOGRAM": "histogram",
            }.get(dotted_name(args[1]) or "")
        for kw in node.keywords:
            if kw.arg == "name":
                name = literal_str(kw.value)
            elif kw.arg == "kind":
                kind = literal_str(kw.value)
        if name:
            out[name] = (kind or "?", node.lineno)
    return out


def constructed_metrics(
    project: Project,
) -> List[Tuple[str, str, SourceFile, int, int]]:
    """(name, kind, file, line, col) for every pst-prefixed constructor."""
    out = []
    for src in project.files:
        if src.tree is None or src.rel.endswith(_REGISTRY_REL):
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            ctor = (dotted_name(node.func) or "").split(".")[-1]
            kind = _CONSTRUCTORS.get(ctor)
            if kind is None or not node.args:
                continue
            name = literal_str(node.args[0])
            if name is None or not name.startswith("pst"):
                continue
            out.append((name, kind, src, node.lineno, node.col_offset))
    return out


def _exposition(name: str, kind: str) -> str:
    if kind == "counter" and not name.endswith("_total"):
        return name + "_total"
    return name


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    # A subset lint (changed-files workflows) still resolves the registry
    # from the repo root; the reverse (stale) and docs checks below only
    # run when the registry's tree was actually scanned, because "no
    # constructor in scope" is meaningless on a partial file set.
    registry_in_scan = bool(project.find(_REGISTRY_REL))
    registry = project.resolve(_REGISTRY_REL)
    constructed = constructed_metrics(project)
    if registry is None:
        if constructed:
            name, _, src, line, col = constructed[0]
            findings.append(Finding(
                CHECK_ID, src.rel, line, col,
                "pst metrics are constructed but no %s exists to declare "
                "them" % _REGISTRY_REL,
            ))
        return findings
    declared = declared_specs(registry)

    seen_names = set()
    for name, kind, src, line, col in constructed:
        seen_names.add(name)
        spec = declared.get(name)
        if spec is None:
            findings.append(Finding(
                CHECK_ID, src.rel, line, col,
                "metric %r is not declared in %s — add a MetricSpec so "
                "dashboards/rules/docs have one source of truth"
                % (name, registry.rel),
            ))
        elif spec[0] != kind:
            findings.append(Finding(
                CHECK_ID, src.rel, line, col,
                "metric %r is constructed as a %s but declared as a %s in "
                "%s — kind decides the exposition name (_total suffix), "
                "so every consumer breaks" % (name, kind, spec[0], registry.rel),
            ))

    for name, (kind, line) in sorted(declared.items()):
        if registry_in_scan and name not in seen_names:
            findings.append(Finding(
                CHECK_ID, registry.rel, line, 0,
                "declared metric %r has no Counter/Gauge/Histogram "
                "constructor anywhere in the scanned tree — stale "
                "declaration (or the constructor moved out of scan scope)"
                % name,
            ))

    # Docs coverage (absorbs the old scripts/check_metric_docs.py scan).
    doc_path = project.root / _DOC_REL
    if registry_in_scan and doc_path.exists():
        doc_text = doc_path.read_text(encoding="utf-8")
        prefixes = [p for p in _WILDCARD_RE.findall(doc_text) if len(p) > 4]
        for name, (kind, line) in sorted(declared.items()):
            expo = _exposition(name, kind)
            if name in doc_text or expo in doc_text:
                continue
            if any(name.startswith(p) for p in prefixes):
                continue
            findings.append(Finding(
                CHECK_ID, registry.rel, line, 0,
                "declared metric %r is not documented in %s (nor covered "
                "by a family wildcard) — the docs are the operator "
                "contract" % (expo, _DOC_REL),
            ))
    return findings
