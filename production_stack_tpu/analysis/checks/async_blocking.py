"""async-blocking: no synchronous blocking calls on async paths.

The router, resilience layer, observability surface and KV control plane
are one asyncio event loop. A single ``time.sleep``, synchronous
``requests``/``urllib`` call, ``subprocess`` invocation or plain-``open``
file read inside an ``async def`` stalls EVERY in-flight request for its
duration — the class of bug that turns a 5 ms p50 router into a 2 s p99
router with nothing in a profile to show for it. Runtime tests only catch
the blocking calls they happen to drive; this check covers every
``async def`` body in the tree.

Two rules:

1. Inside any ``async def`` body (nested synchronous ``def``/``lambda``
   bodies are excluded — they run wherever they are called), flag calls
   to the known blocking surface: ``time.sleep``, the ``requests``
   module, ``urllib.request.urlopen``, ``subprocess.*``, ``os.system`` /
   ``os.popen`` / ``os.wait*``, builtin ``open``, and the pathlib
   read/write quartet (``read_text``/``write_text``/``read_bytes``/
   ``write_bytes``).
2. ``time.sleep`` anywhere — async or sync — inside the event-loop
   packages (``router/``, ``resilience/``, ``obs/``, ``kvserver/``,
   ``engine/``): sync helpers in these packages are routinely called
   from coroutines, so a hard sleep needs an explicit justification
   (e.g. the runner's device-poll on its dedicated step thread carries a
   suppression naming that thread).

Suppress with ``# pstlint: disable=async-blocking(<reason>)``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ..core import Finding, FunctionStack, Project, SourceFile, dotted_name

CHECK_ID = "async-blocking"
DESCRIPTION = (
    "blocking calls (time.sleep / sync HTTP / sync file IO / subprocess) "
    "on async paths"
)

# Packages whose sync code also may not hard-sleep (rule 2).
_LOOP_PACKAGES = ("router", "resilience", "obs", "kvserver", "engine")

_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep blocks the event loop — use await asyncio.sleep",
    "urllib.request.urlopen": "sync urllib blocks the event loop — use the "
    "shared aiohttp session",
    "os.system": "os.system blocks the event loop — use asyncio.create_subprocess_*",
    "os.popen": "os.popen blocks the event loop — use asyncio.create_subprocess_*",
    "os.wait": "os.wait blocks the event loop",
    "os.waitpid": "os.waitpid blocks the event loop",
    "socket.create_connection": "sync socket connect blocks the event loop",
}
_SUBPROCESS_FUNCS = {"run", "call", "check_call", "check_output", "Popen"}
_PATHLIB_IO = {"read_text", "write_text", "read_bytes", "write_bytes"}


def _requests_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to the ``requests`` module by imports."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "requests":
                    aliases.add(a.asname or "requests")
    return aliases


class _Visitor(FunctionStack):
    def __init__(self, src: SourceFile, loop_package: bool) -> None:
        super().__init__()
        self.src = src
        self.loop_package = loop_package
        self.requests_aliases = (
            _requests_aliases(src.tree) if src.tree else set()
        )
        self.findings: List[Finding] = []

    # A nested sync def inside an async def pops the async context: calls
    # in its body execute wherever the closure runs. FunctionStack already
    # pushes it, and ``in_async_def`` looks only at the innermost frame.

    def _report(self, node: ast.Call, why: str) -> None:
        self.findings.append(Finding(
            CHECK_ID, self.src.rel, node.lineno, node.col_offset, why
        ))

    def _blocking_reason(self, node: ast.Call) -> Optional[str]:
        name = dotted_name(node.func)
        if name is not None:
            if name in _BLOCKING_DOTTED:
                return _BLOCKING_DOTTED[name]
            head = name.split(".")[0]
            if head in self.requests_aliases and "." in name:
                return (
                    "sync 'requests' call blocks the event loop — use the "
                    "shared aiohttp session"
                )
            if head == "subprocess" and name.split(".")[-1] in _SUBPROCESS_FUNCS:
                return (
                    "sync subprocess call blocks the event loop — use "
                    "asyncio.create_subprocess_*"
                )
            if name == "open":
                return (
                    "builtin open() blocks the event loop — use aiofiles "
                    "or a thread executor"
                )
        if isinstance(node.func, ast.Attribute) and node.func.attr in _PATHLIB_IO:
            return (
                "sync file IO (.%s) blocks the event loop — use aiofiles "
                "or a thread executor" % node.func.attr
            )
        return None

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if self.in_async_def:
            why = self._blocking_reason(node)
            if why is not None:
                self._report(node, why)
        elif self.loop_package and name == "time.sleep":
            self._report(node, (
                "time.sleep in an event-loop package: sync helpers here "
                "are called from coroutines — if this sleep runs on a "
                "dedicated thread, say so in a suppression"
            ))
        self.generic_visit(node)


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for src in project.files:
        if src.tree is None:
            continue
        segs = src.rel.replace("\\", "/").split("/")
        loop_package = any(p in segs for p in _LOOP_PACKAGES)
        v = _Visitor(src, loop_package)
        v.visit(src.tree)
        findings.extend(v.findings)
    return findings
