"""pstlint: project-invariant static analysis for production-stack-tpu.

Generic linters know Python; they do not know that this codebase promises
"no blocking call ever parks the router's event loop", "every jitted
dispatch is reachable from the warmup lattice", "every hop carries the
deadline/trace headers", "every ``pst_*`` metric is declared in the
registry", and "shared router state has exactly one writer surface".
Those invariants were bought by PRs 1-6 and are enforced at runtime only
where a test happens to exercise them; this package enforces them at
diff time, across every code path, with plain ``ast`` (no third-party
dependencies, so the CI lint ring needs nothing installed).

CLI: ``python -m production_stack_tpu.analysis.pstlint <paths...>`` or the
``pst-lint`` entry point. See docs/static-analysis.md for the check
catalogue, the suppression syntax (a reason is mandatory), and the
``owned-by`` / ``jit-family`` annotation grammar.
"""

from .core import Finding, Project, SourceFile, load_project  # noqa: F401
