"""A deliberately small YAML-subset reader for the config-contract check.

pstlint is stdlib-only (the CI lint ring installs nothing), but the
config-contract check must read ``helm/values.yaml``. This module parses
exactly the subset that file uses — nested mappings by indentation,
scalars (quoted/unquoted strings, ints, floats, bools, null), block
lists (``- `` items, scalar or mapping), and inline flow ``{...}`` /
``[...]`` — and *fails loudly* on anything it does not understand, so a
values.yaml grown past the subset surfaces as a lint error instead of a
silently wrong parse. Anchors, tags, multi-document streams, and block
scalars are out of scope on purpose.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class SimpleYamlError(ValueError):
    """values.yaml used syntax outside the supported subset."""


def _strip_comment(line: str) -> str:
    """Drop a trailing ``# ...`` comment (quote-aware)."""
    out: List[str] = []
    quote: Optional[str] = None
    for i, ch in enumerate(line):
        if quote is not None:
            if ch == quote:
                quote = None
            out.append(ch)
            continue
        if ch in "\"'":
            quote = ch
            out.append(ch)
            continue
        if ch == "#" and (i == 0 or line[i - 1] in " \t"):
            break
        out.append(ch)
    return "".join(out).rstrip()


def _lines(text: str) -> List[Tuple[int, str, int]]:
    """(indent, content, lineno) for each non-empty, non-comment line."""
    out: List[Tuple[int, str, int]] = []
    for lineno, raw in enumerate(text.splitlines(), start=1):
        if "\t" in raw[: len(raw) - len(raw.lstrip())]:
            raise SimpleYamlError("tab indentation at line %d" % lineno)
        stripped = raw.lstrip(" ")
        if not stripped or stripped.startswith("#"):
            continue
        content = _strip_comment(stripped)
        if not content:
            continue
        out.append((len(raw) - len(stripped), content, lineno))
    return out


def _scalar(text: str, lineno: int) -> Any:
    text = text.strip()
    if text.startswith("{") or text.startswith("["):
        return _flow(text, lineno)
    if len(text) >= 2 and text[0] in "\"'" and text[-1] == text[0]:
        return text[1:-1]
    low = text.lower()
    if low in ("null", "~", ""):
        return None
    if low == "true":
        return True
    if low == "false":
        return False
    if low in ("yes", "no", "on", "off"):
        # YAML 1.1 booleans Helm WOULD honor but this subset deliberately
        # rejects: silently returning the string would make the
        # config-contract default comparison wrong, violating the
        # fail-loudly contract. Quote the string or use true/false.
        raise SimpleYamlError(
            "YAML 1.1 boolean %r at line %d — use true/false (or quote "
            "the string)" % (text, lineno)
        )
    try:
        return int(text)
    except ValueError:
        pass
    try:
        return float(text)
    except ValueError:
        pass
    return text


def _split_flow(body: str, lineno: int) -> List[str]:
    """Split a flow body on top-level commas (depth- and quote-aware)."""
    parts: List[str] = []
    depth = 0
    quote: Optional[str] = None
    cur: List[str] = []
    for ch in body:
        if quote is not None:
            cur.append(ch)
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch in "{[":
            depth += 1
            cur.append(ch)
        elif ch in "}]":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if quote is not None or depth != 0:
        raise SimpleYamlError("unbalanced flow collection at line %d" % lineno)
    if "".join(cur).strip():
        parts.append("".join(cur))
    return parts


def _flow(text: str, lineno: int) -> Any:
    text = text.strip()
    if text.startswith("{"):
        if not text.endswith("}"):
            raise SimpleYamlError("unterminated flow mapping at line %d" % lineno)
        out: Dict[str, Any] = {}
        for part in _split_flow(text[1:-1], lineno):
            if ":" not in part:
                raise SimpleYamlError(
                    "flow mapping entry without ':' at line %d" % lineno
                )
            key, _, value = part.partition(":")
            out[_key(key, lineno)] = _scalar(value, lineno)
        return out
    if text.startswith("["):
        if not text.endswith("]"):
            raise SimpleYamlError("unterminated flow list at line %d" % lineno)
        return [_scalar(p, lineno) for p in _split_flow(text[1:-1], lineno)]
    raise SimpleYamlError("unsupported flow scalar at line %d" % lineno)


def _key(text: str, lineno: int) -> str:
    key = text.strip()
    if len(key) >= 2 and key[0] in "\"'" and key[-1] == key[0]:
        key = key[1:-1]
    if not key:
        raise SimpleYamlError("empty mapping key at line %d" % lineno)
    return key


def _split_key(content: str, lineno: int) -> Tuple[str, str]:
    """Split ``key: rest`` at the first colon outside quotes."""
    quote: Optional[str] = None
    for i, ch in enumerate(content):
        if quote is not None:
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
        elif ch == ":" and (i + 1 == len(content) or content[i + 1] in " \t"):
            return content[:i], content[i + 1:]
    raise SimpleYamlError("expected 'key: value' at line %d" % lineno)


class _Parser:
    def __init__(self, lines: List[Tuple[int, str, int]]) -> None:
        self.lines = lines
        self.pos = 0

    def peek(self) -> Optional[Tuple[int, str, int]]:
        return self.lines[self.pos] if self.pos < len(self.lines) else None

    def parse_block(self, indent: int) -> Any:
        head = self.peek()
        assert head is not None
        if head[1].startswith("- ") or head[1] == "-":
            return self.parse_list(indent)
        return self.parse_map(indent)

    def parse_map(self, indent: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        while True:
            cur = self.peek()
            if cur is None or cur[0] < indent:
                return out
            line_indent, content, lineno = cur
            if line_indent > indent:
                raise SimpleYamlError("unexpected indent at line %d" % lineno)
            if content.startswith("- "):
                raise SimpleYamlError(
                    "list item where mapping key expected at line %d" % lineno
                )
            key_text, rest = _split_key(content, lineno)
            key = _key(key_text, lineno)
            self.pos += 1
            if rest.strip():
                out[key] = _scalar(rest, lineno)
                continue
            nxt = self.peek()
            if nxt is None or nxt[0] <= indent:
                out[key] = None
                continue
            out[key] = self.parse_block(nxt[0])
        return out

    def parse_list(self, indent: int) -> List[Any]:
        out: List[Any] = []
        while True:
            cur = self.peek()
            if cur is None or cur[0] < indent:
                return out
            line_indent, content, lineno = cur
            if line_indent > indent or not (
                content.startswith("- ") or content == "-"
            ):
                raise SimpleYamlError(
                    "expected '- ' list item at line %d" % lineno
                )
            body = content[2:].strip() if content.startswith("- ") else ""
            if not body:
                self.pos += 1
                nxt = self.peek()
                if nxt is None or nxt[0] <= indent:
                    out.append(None)
                else:
                    out.append(self.parse_block(nxt[0]))
                continue
            if ":" in body and not body.startswith(("{", "[", '"', "'")):
                # '- key: value' opens a mapping item whose further keys
                # sit at indent+2 — rewrite the head line and reparse.
                self.lines[self.pos] = (line_indent + 2, body, lineno)
                out.append(self.parse_map(line_indent + 2))
            else:
                self.pos += 1
                out.append(_scalar(body, lineno))
        return out


def parse(text: str) -> Any:
    """Parse the YAML subset; raises :class:`SimpleYamlError` beyond it."""
    lines = _lines(text)
    if not lines:
        return {}
    parser = _Parser(lines)
    result = parser.parse_block(lines[0][0])
    leftover = parser.peek()
    if leftover is not None:
        raise SimpleYamlError(
            "trailing content at line %d (indentation outside the "
            "document root?)" % leftover[2]
        )
    return result


def resolve(doc: Any, path: str) -> Tuple[bool, Any]:
    """Resolve a dotted path like ``routerSpec.fleet.evictionRatio`` or
    ``servingEngineSpec.modelSpec[].engineConfig.maxModelLen`` (``[]``
    takes the first list element). Returns ``(found, value)``."""
    cur = doc
    for part in path.split("."):
        take_first = part.endswith("[]")
        key = part[:-2] if take_first else part
        if not isinstance(cur, dict) or key not in cur:
            return False, None
        cur = cur[key]
        if take_first:
            if not isinstance(cur, list) or not cur:
                return False, None
            cur = cur[0]
    return True, cur


def leaf_paths(doc: Any, prefix: str = "") -> List[str]:
    """Dotted paths of every leaf (non-mapping value) under ``doc``.
    Lists are leaves (helm list knobs are consumed whole)."""
    out: List[str] = []
    if isinstance(doc, dict):
        for key, value in doc.items():
            sub = "%s.%s" % (prefix, key) if prefix else str(key)
            if isinstance(value, dict) and value:
                out.extend(leaf_paths(value, sub))
            else:
                out.append(sub)
    return out
