"""pstlint core: source model, suppression grammar, finding plumbing.

Design constraints:

- **stdlib only.** The lint ring must run on a bare checkout (CI installs
  nothing for it) and the analyzer is imported by the test suite, so
  everything here is ``ast`` + ``tokenize``.
- **Suppressions carry a reason.** ``# pstlint: disable=<check>(<reason>)``
  — a reasonless disable is itself a finding (``bad-suppression``), and a
  disable that never suppresses anything is flagged too
  (``unused-suppression``) so stale escapes rot away instead of
  accumulating.
- **Annotations are comments.** ``# pstlint: owned-by=...`` /
  ``jit-family=...`` / ``holds=...`` attach machine-readable contracts to
  declarations without imports or decorators (the annotated modules must
  stay importable with the analyzer absent).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Checks that the framework itself emits (not registered check modules).
BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"
SYNTAX_ERROR = "syntax-error"

_DIRECTIVE_RE = re.compile(r"#\s*pstlint:\s*(?P<body>.*)$")
_DISABLE_RE = re.compile(
    r"disable(?P<scope>-file)?="
    r"(?P<check>[A-Za-z0-9_-]+)"
    r"(?:\((?P<reason>[^()]*(?:\([^()]*\)[^()]*)*)\))?"
)
# Annotation directives: key=value where value runs to end-of-comment
# (values may contain commas, colons and spaces; never a second '=').
_ANNOTATION_RE = re.compile(
    r"(?P<key>owned-by|jit-family|holds|task-owner)=(?P<value>[^=]+?)\s*$"
)


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    check: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: Optional[str] = None  # the suppression's reason, when suppressed

    def format(self) -> str:
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return "%s:%d:%d: [%s] %s%s" % (
            self.path, self.line, self.col, self.check, self.message, tag
        )

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    check: str
    line: int  # line the directive comment sits on
    reason: str
    file_wide: bool
    used: bool = False


class SourceFile:
    """One parsed module: AST + the pstlint comment directives in it."""

    def __init__(self, path: Path, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.syntax_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:
            self.syntax_error = e
        # line -> raw directive body (only comment lines bearing the tag).
        self.directives: Dict[int, str] = {}
        self.suppressions: List[Suppression] = []
        self.bad_directives: List[Tuple[int, str]] = []
        # line -> {key: value} for annotation directives.
        self.annotations: Dict[int, Dict[str, str]] = {}
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.text).readline)
            comments = [
                (t.start[0], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            comments = []
        for line, comment in comments:
            m = _DIRECTIVE_RE.search(comment)
            if not m:
                continue
            body = m.group("body").strip()
            self.directives[line] = body
            matched = False
            for dm in _DISABLE_RE.finditer(body):
                matched = True
                reason = (dm.group("reason") or "").strip()
                if not reason:
                    self.bad_directives.append((
                        line,
                        "suppression of %r carries no reason — use "
                        "disable=%s(<why this is safe>)"
                        % (dm.group("check"), dm.group("check")),
                    ))
                    continue
                self.suppressions.append(Suppression(
                    check=dm.group("check"),
                    line=line,
                    reason=reason,
                    file_wide=dm.group("scope") == "-file",
                ))
            for am in _ANNOTATION_RE.finditer(body):
                matched = True
                self.annotations.setdefault(line, {})[am.group("key")] = (
                    am.group("value").strip()
                )
            if not matched:
                self.bad_directives.append((
                    line, "unrecognized pstlint directive: %r" % body
                ))

    # -- annotation lookup -------------------------------------------------

    def annotation_at(self, line: int, key: str) -> Optional[str]:
        """Annotation value attached to ``line``: on the line itself or on
        a directive comment on the immediately preceding line."""
        for cand in (line, line - 1):
            ann = self.annotations.get(cand)
            if ann and key in ann:
                return ann[key]
        return None

    # -- suppression matching ----------------------------------------------

    def suppression_for(self, check: str, line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.check != check:
                continue
            if s.file_wide or s.line in (line, line - 1):
                return s
        return None


class Project:
    """The file set under analysis plus the repo root for path resolution
    (cross-file checks need to find e.g. ``engine/precompile.py`` and
    ``docs/observability.md`` relative to it)."""

    def __init__(self, files: Sequence[SourceFile], root: Path) -> None:
        self.files = list(files)
        self.root = root
        # Cross-file anchors loaded by resolve() that were NOT part of the
        # requested scan. Their suppressions/annotations apply to findings
        # attributed to them, but they are excluded from the framework
        # scans (syntax/bad-suppression/unused-suppression) — a subset
        # lint must not start reporting on files nobody asked about.
        self.auxiliary: Dict[str, SourceFile] = {}

    def find(self, *suffixes: str) -> List[SourceFile]:
        """Files whose relative path ends with any of ``suffixes`` (posix
        separators)."""
        out = []
        for f in self.files:
            rel = f.rel.replace("\\", "/")
            if any(rel.endswith(s) for s in suffixes):
                out.append(f)
        return out

    def resolve(self, suffix: str) -> Optional[SourceFile]:
        """The file ending in ``suffix``: from the scanned set if present,
        else loaded from disk under ``root``. Cross-file checks use this so
        a subset lint (e.g. ``pst-lint production_stack_tpu/router/``) sees
        the same registry/lattice anchors a full-tree lint does instead of
        reporting them missing."""
        hits = self.find(suffix)
        if hits:
            return hits[0]
        for rel, cached in self.auxiliary.items():
            if rel.replace("\\", "/").endswith(suffix):
                return cached
        basename = suffix.split("/")[-1]
        for cand in sorted(self.root.rglob(basename)):
            if any(part.startswith(".") for part in cand.parts):
                continue
            try:
                rel = str(cand.relative_to(self.root))
            except ValueError:  # pragma: no cover — symlink escape
                continue
            if rel.replace("\\", "/").endswith(suffix):
                src = SourceFile(cand, rel, cand.read_text(encoding="utf-8"))
                self.auxiliary[rel] = src
                return src
        return None

    def in_dir(self, *parts: str) -> List[SourceFile]:
        """Files whose relative path contains any of ``parts`` as a path
        segment (e.g. ``router`` matches ``production_stack_tpu/router/...``)."""
        out = []
        for f in self.files:
            segs = f.rel.replace("\\", "/").split("/")
            if any(p in segs for p in parts):
                out.append(f)
        return out


def iter_py_files(paths: Iterable[str]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            out.extend(sorted(
                f for f in path.rglob("*.py")
                if not any(part.startswith(".") for part in f.parts)
            ))
        elif path.suffix == ".py":
            out.append(path)
    # De-dup while preserving order (overlapping roots on the CLI).
    seen = set()
    uniq = []
    for f in out:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            uniq.append(f)
    return uniq


def load_project(paths: Sequence[str], root: Optional[Path] = None) -> Project:
    root = root or Path.cwd()
    files = []
    for f in iter_py_files(paths):
        try:
            rel = str(f.resolve().relative_to(root.resolve()))
        except ValueError:
            rel = str(f)
        files.append(SourceFile(f, rel, f.read_text(encoding="utf-8")))
    return Project(files, root)


def apply_suppressions(
    project: Project, findings: List[Finding], report_unused: bool = True
) -> List[Finding]:
    """Mark suppressed findings, then append the framework findings:
    syntax errors, reasonless suppressions, and (optionally) suppressions
    that never fired."""
    # Auxiliary (resolve()-loaded) files participate in suppression
    # matching — a finding attributed to an anchor honors the anchor's
    # own disable= comments — but scanned files win on rel collisions.
    by_rel = dict(project.auxiliary)
    by_rel.update({f.rel: f for f in project.files})
    for finding in findings:
        src = by_rel.get(finding.path)
        if src is None:
            continue
        sup = src.suppression_for(finding.check, finding.line)
        if sup is not None:
            sup.used = True
            finding.suppressed = True
            finding.reason = sup.reason
    out = list(findings)
    for src in project.files:
        if src.syntax_error is not None:
            out.append(Finding(
                SYNTAX_ERROR, src.rel, src.syntax_error.lineno or 1, 0,
                "file does not parse: %s" % src.syntax_error.msg,
            ))
        for line, msg in src.bad_directives:
            out.append(Finding(BAD_SUPPRESSION, src.rel, line, 0, msg))
        if report_unused:
            for sup in src.suppressions:
                if not sup.used:
                    out.append(Finding(
                        UNUSED_SUPPRESSION, src.rel, sup.line, 0,
                        "suppression of %r never matched a finding — "
                        "remove it (stale escapes hide future regressions)"
                        % sup.check,
                    ))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    return out


# ---------------------------------------------------------------------------
# Shared AST helpers used by several checks
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return None if base is None else base + "." + node.attr
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class FunctionStack(ast.NodeVisitor):
    """Visitor base that tracks the enclosing (async) function chain."""

    def __init__(self) -> None:
        self.func_stack: List[ast.AST] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # A lambda body runs wherever the closure is called (executor,
        # callback), not in the enclosing coroutine — same exclusion as a
        # nested sync def.
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    @property
    def current_function(self) -> Optional[ast.AST]:
        return self.func_stack[-1] if self.func_stack else None

    @property
    def in_async_def(self) -> bool:
        return isinstance(self.current_function, ast.AsyncFunctionDef)


def assignments_in(func: ast.AST) -> Dict[str, ast.AST]:
    """name -> RHS expression for simple assignments inside ``func``
    (including tuple unpacks, where every target name maps to the shared
    RHS). Last assignment wins — a deliberate, documented approximation:
    pstlint resolves one level of straight-line dataflow, not full SSA."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(func):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value
                elif isinstance(tgt, (ast.Tuple, ast.List)):
                    for el in tgt.elts:
                        if isinstance(el, ast.Name):
                            out[el.id] = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                out[node.target.id] = node.value
        elif isinstance(node, ast.withitem) and node.optional_vars is not None:
            if isinstance(node.optional_vars, ast.Name):
                out[node.optional_vars.id] = node.context_expr
    return out
