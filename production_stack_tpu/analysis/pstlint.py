"""pstlint CLI.

Usage::

    python -m production_stack_tpu.analysis.pstlint production_stack_tpu/ scripts/
    pst-lint --format json production_stack_tpu/
    pst-lint --format sarif production_stack_tpu/ > pstlint.sarif
    pst-lint --checks async-blocking,hop-contract production_stack_tpu/router/

Exit codes: 0 = clean (suppressed findings allowed), 1 = unsuppressed
findings, 2 = usage error. ``--format json`` emits a machine-readable
report (list of finding objects + summary) for CI annotation tooling;
``--format sarif`` emits SARIF 2.1.0 so CI can upload findings as PR
diff annotations (``github/codeql-action/upload-sarif``). Both formats
are covered by a schema-stability test (tests/test_pstlint.py) — the
key sets below are a consumed contract, not an implementation detail.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from .checks import ALL_CHECKS, CHECKS_BY_ID
from .core import Finding, apply_suppressions, iter_py_files, load_project

# SARIF 2.1.0 constants (the schema-stability test pins these).
_SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(findings: Sequence[Finding]) -> dict:
    """Render findings as one SARIF 2.1.0 run.

    Suppressed findings are included with a ``suppressions`` entry (kind
    ``inSource`` — the ``# pstlint: disable=...(reason)`` comment) so the
    upload shows them as reviewed, not hidden.
    """
    rules = sorted({f.check for f in findings} | {c.CHECK_ID for c in ALL_CHECKS})
    descriptions = {c.CHECK_ID: c.DESCRIPTION for c in ALL_CHECKS}
    results = []
    for f in findings:
        result: dict = {
            "ruleId": f.check,
            "level": "note" if f.suppressed else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": max(f.col + 1, 1),
                    },
                },
            }],
        }
        if f.suppressed:
            result["suppressions"] = [{
                "kind": "inSource",
                "justification": f.reason or "",
            }]
        results.append(result)
    return {
        "$schema": _SARIF_SCHEMA,
        "version": _SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "pstlint",
                    "informationUri": (
                        "https://github.com/production-stack-tpu/"
                        "production-stack-tpu/blob/main/docs/"
                        "static-analysis.md"
                    ),
                    "rules": [
                        {
                            "id": rule,
                            "shortDescription": {
                                "text": descriptions.get(rule, rule)
                            },
                        }
                        for rule in rules
                    ],
                },
            },
            "results": results,
        }],
    }


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pst-lint",
        description="Project-invariant static analyzer for "
        "production-stack-tpu (see docs/static-analysis.md).",
    )
    parser.add_argument("paths", nargs="*", help="files or directories to lint")
    parser.add_argument(
        "--checks",
        help="comma-separated subset of checks to run (default: all)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        dest="fmt",
    )
    parser.add_argument(
        "--root",
        help="repo root for docs/registry resolution (default: cwd)",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print findings silenced by disable= comments",
    )
    parser.add_argument(
        "--no-unused", action="store_true",
        help="do not flag suppressions that never fired (use when "
        "linting a subset of checks or files)",
    )
    parser.add_argument(
        "--list-checks", action="store_true", help="list checks and exit"
    )
    return parser


def run_checks(
    paths: Sequence[str],
    checks: Optional[Sequence[str]] = None,
    root: Optional[Path] = None,
    report_unused: bool = True,
) -> List[Finding]:
    """Programmatic entry point (the test suite uses this)."""
    project = load_project(paths, root=root)
    selected = ALL_CHECKS if checks is None else [
        CHECKS_BY_ID[c] for c in checks
    ]
    findings: List[Finding] = []
    for check in selected:
        findings.extend(check.run(project))
    # Unused-suppression detection is only sound when every check ran:
    # a hop-contract suppression is not stale just because only
    # async-blocking was selected.
    report_unused = report_unused and checks is None
    return apply_suppressions(project, findings, report_unused=report_unused)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.list_checks:
        for check in ALL_CHECKS:
            print("%-16s %s" % (check.CHECK_ID, check.DESCRIPTION))
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("pst-lint: error: no paths given", file=sys.stderr)
        return 2

    checks: Optional[List[str]] = None
    if args.checks:
        checks = [c.strip() for c in args.checks.split(",") if c.strip()]
        unknown = [c for c in checks if c not in CHECKS_BY_ID]
        if unknown:
            print(
                "pst-lint: error: unknown check(s): %s (see --list-checks)"
                % ", ".join(unknown),
                file=sys.stderr,
            )
            return 2

    # A misspelled or renamed path must be a loud error, not a vacuous
    # green run — exit 0 on an empty file set would silently switch the
    # whole invariant ring off.
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        print(
            "pst-lint: error: path(s) do not exist: %s" % ", ".join(missing),
            file=sys.stderr,
        )
        return 2
    if not iter_py_files(args.paths):
        print(
            "pst-lint: error: no Python files found under: %s"
            % ", ".join(args.paths),
            file=sys.stderr,
        )
        return 2

    root = Path(args.root) if args.root else None
    findings = run_checks(
        args.paths, checks=checks, root=root,
        report_unused=not args.no_unused,
    )
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.fmt == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "summary": {
                "active": len(active),
                "suppressed": len(suppressed),
            },
        }, indent=2))
    elif args.fmt == "sarif":
        print(json.dumps(to_sarif(findings), indent=2))
    else:
        shown = findings if args.show_suppressed else active
        for f in shown:
            print(f.format())
        print(
            "pst-lint: %d finding(s), %d suppressed"
            % (len(active), len(suppressed))
        )
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main())
