"""Paged attention over a block-table KV cache.

This is the op the reference delegates to vLLM's CUDA PagedAttention; here it
is TPU-native with two interchangeable implementations:

- ``gather``: pure-XLA. Gathers the sequence's KV pages into a contiguous
  ``[B, S, ...]`` view and runs masked attention. Compiles everywhere
  (including the 8-device virtual CPU mesh used in tests) and XLA fuses the
  mask/softmax chain; the gather materialization costs HBM bandwidth, which
  rules it out at long context (a 32k-table gather materializes the whole
  window per layer).
- ``pallas``: TPU flash kernels that stream only the live pages HBM→VMEM
  with double-buffered DMA
  (:mod:`production_stack_tpu.ops.paged_attention_pallas`).

Shapes:
  q            [B, T, H, hd]       T=1 for decode rows, T=chunk for prefill
  kv_pages     [L, nb, 2, bs, KH*hd] combined pages: row 0 = K, row 1 = V;
                                   each token row spans all kv heads in the
                                   lane dim (one DMA per page in the kernel;
                                   minor dims stay tiling-exact). The FULL
                                   stacked cache is passed with a layer
                                   index — a per-layer slice inside the
                                   model's layer scan would materialize a
                                   copy of the layer cache every step.
  block_tables [B, W] int32        page ids per sequence (W*bs >= kv_len)
  kv_lens      [B]   int32         valid KV length per sequence
  q_positions  [B, T] int32        absolute position of each query token
                                   (padding rows may hold any value; they are
                                   masked out downstream via last_idx/sampling)
  layer        int32 scalar        layer to attend against (may be traced)
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def window_eff(window) -> jax.Array:
    """Effective sliding window as an int32 scalar: the configured window,
    or a past-any-context sentinel when 0/negative (= unlimited). Shared by
    the gather path, both Pallas kernels, and the encode path so the
    window-bound convention (`key_pos > q_pos - window_eff`) lives in one
    place."""
    win = jnp.asarray(window, jnp.int32)
    return jnp.where(win > 0, win, jnp.int32(1 << 30))


def _use_pallas() -> bool:
    if os.environ.get("PST_DISABLE_PALLAS"):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def paged_attention(
    q: jax.Array,
    kv_pages: jax.Array,
    block_tables: jax.Array,
    kv_lens: jax.Array,
    q_positions: jax.Array,
    layer=0,
    *,
    scale: float,
    impl: str = "auto",
    window=0,
    softcap: float = 0.0,
) -> jax.Array:
    """Causal attention of ``q`` against paged KV. Returns [B, T, H, hd].

    ``window`` (int32 scalar, may be traced — e.g. derived from the layer
    index for Gemma-2's alternating local/global layers) limits each query
    to the last ``window`` positions; 0 = unlimited. ``softcap`` applies
    Gemma-style attention-logit soft-capping ``tanh(s/c)*c`` (static; 0 =
    off)."""
    if impl == "auto":
        impl = "pallas" if _use_pallas() else "gather"
    if impl == "pallas":
        from .paged_attention_pallas import pallas_paged_attention

        return pallas_paged_attention(
            q, kv_pages, block_tables, kv_lens, q_positions, layer,
            scale=scale, window=window, softcap=softcap,
        )
    return gather_paged_attention(
        q, kv_pages, block_tables, kv_lens, q_positions, layer, scale=scale,
        window=window, softcap=softcap,
    )


def gather_paged_attention(
    q: jax.Array,
    kv_pages: jax.Array,
    block_tables: jax.Array,
    kv_lens: jax.Array,
    q_positions: jax.Array,
    layer=0,
    *,
    scale: float,
    window=0,
    softcap: float = 0.0,
) -> jax.Array:
    B, T, H, hd = q.shape
    _, nb, _, bs, lanes = kv_pages.shape
    KH = lanes // hd
    W = block_tables.shape[1]
    S = W * bs
    G = H // KH

    # [W...] -> [B, S, KH, hd] per half. Out-of-range table entries are
    # clipped by XLA gather semantics; they are masked below anyway. (The
    # layer slice materializes here — acceptable for the test/CPU path.)
    pages = jax.lax.dynamic_index_in_dim(kv_pages, layer, 0, keepdims=False)
    kv = pages[block_tables]
    k = kv[:, :, 0].reshape(B, S, KH, hd)
    v = kv[:, :, 1].reshape(B, S, KH, hd)

    qg = q.reshape(B, T, KH, G, hd)
    # scores [B, KH, G, T, S]
    scores = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
    )
    scores = scores * scale
    if softcap:
        scores = jnp.tanh(scores / softcap) * softcap

    kv_pos = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    valid = kv_pos < kv_lens[:, None]  # [B, S]
    causal = kv_pos[:, None, :] <= q_positions[..., None]  # [B, T, S]
    # Sliding window: each query sees at most the last `window` positions
    # (0 = unlimited; `window` may be a traced scalar for per-layer windows).
    in_window = kv_pos[:, None, :] > q_positions[..., None] - window_eff(window)
    mask = (valid[:, None, :] & causal & in_window)[:, None, None]
    scores = jnp.where(mask, scores, _NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1)
    # 1-byte (fp8/int8) caches: the PV dot runs in the query dtype —
    # casting probs to the cache dtype would quantize the softmax weights
    # themselves (model-level numerics oracle regression).
    dt = q.dtype if jnp.dtype(v.dtype).itemsize == 1 else v.dtype
    out = jnp.einsum(
        "bkgts,bskd->btkgd", probs.astype(dt), v.astype(dt),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, T, H, hd).astype(q.dtype)
