"""Ring attention: context-parallel causal attention over the ``sp`` axis.

The building block for contexts larger than one device group's HBM
(SURVEY.md §2.4 "sequence/context parallel" — absent from the reference,
which caps at 32k + offload; the task's long-context requirement makes it
first-class here). Design is the standard ring schedule mapped onto the
scaling-book recipe — shard, ``ppermute``, let XLA place the collectives:

- The sequence is sharded over ``sp``: rank ``r`` holds query block ``r``
  and KV block ``r`` (``S_local = S / sp`` each). Peak memory per device is
  O(S/sp) — KV for a 128k context fits a 4-way sp group of chips that
  individually hold 32k.
- ``sp`` hops: each hop every rank runs FLASH attention of its (stationary)
  query block against the KV block currently resident, merges into running
  (m, l, acc) accumulators, then rotates the KV block to the next rank with
  ``jax.lax.ppermute`` — point-to-point neighbor traffic that rides ICI,
  overlapped by XLA with the attention compute of the next hop.
- Causality at BLOCK granularity: KV block ``b`` contributes to query block
  ``q`` only when ``b <= q`` (the per-element triangle applies inside the
  diagonal block). NOTE every rank still COMPUTES all ``sp`` hops and
  discards non-contributing ones via ``where`` — SPMD requires one uniform
  program, so FLOPs are the full square; wall-clock is bounded by the
  busiest rank either way (a zigzag/load-balanced block order that earns
  back the triangle is a known follow-up, not implemented here).

This module provides the jnp/shard_map implementation (compiles on any
backend, incl. the CPU test mesh); the per-hop inner attention is a
standard flash block that XLA fuses — a Pallas inner kernel can be swapped
in without touching the ring schedule.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import AXIS_SEQUENCE, AXIS_TENSOR

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _flash_block(q, k, v, mask, scale):
    """One (m, l, acc) flash contribution of KV block (k, v) for queries q.

    q: [B, Tq, H, hd]; k/v: [B, Tk, KH, hd]; mask: [B, Tq, Tk] bool.
    Returns (m, l, acc) with m/l [B, H, Tq] and acc [B, H, Tq, hd].
    """
    B, Tq, H, hd = q.shape
    KH = k.shape[2]
    G = H // KH
    qg = q.reshape(B, Tq, KH, G, hd)
    s = jnp.einsum(
        "btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32
    ) * scale  # [B, KH, G, Tq, Tk]
    s = jnp.where(mask[:, None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [B, KH, G, Tq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum(
        "bkgts,bskd->bkgtd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    H_ = KH * G
    return (
        m.reshape(B, H_, Tq),
        l.reshape(B, H_, Tq),
        acc.reshape(B, H_, Tq, hd),
    )


def _merge(state, update):
    """Numerically-stable merge of two flash partial states."""
    m0, l0, a0 = state
    m1, l1, a1 = update
    m = jnp.maximum(m0, m1)
    w0 = jnp.exp(m0 - m)
    w1 = jnp.exp(m1 - m)
    return m, l0 * w0 + l1 * w1, a0 * w0[..., None] + a1 * w1[..., None]


def ring_self_attention(
    q: jax.Array,  # [B, S, H, hd] — S sharded over sp by the caller's specs
    k: jax.Array,  # [B, S, KH, hd]
    v: jax.Array,  # [B, S, KH, hd]
    lengths: jax.Array,  # [B] valid length (padding masked)
    mesh: Mesh,
    *,
    scale: float | None = None,
    axis: str = AXIS_SEQUENCE,
) -> jax.Array:
    """Causal self-attention with the sequence sharded over ``axis``.

    Every device holds S/sp of Q and of KV; KV blocks rotate around the
    ring while query blocks stay put. Output is sharded like ``q``.
    """
    B, S, H, hd = q.shape
    KH = k.shape[2]
    sp = mesh.shape[axis]
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    if S % sp:
        raise ValueError(f"sequence length {S} not divisible by sp={sp}")
    S_local = S // sp
    perm = [(r, (r + 1) % sp) for r in range(sp)]
    # Heads additionally shard over tp when divisible: ring-sp composes
    # with tensor parallel with zero extra collectives (each tp rank rings
    # its own head shard).
    tp = mesh.shape.get(AXIS_TENSOR, 1)
    head_axis = AXIS_TENSOR if (tp > 1 and H % tp == 0 and KH % tp == 0) else None
    H_local = H // tp if head_axis else H

    def body(q_blk, k_blk, v_blk, lengths):
        r = jax.lax.axis_index(axis)
        pos_q = r * S_local + jnp.arange(S_local, dtype=jnp.int32)  # [Tq]

        m = jnp.full((B, H_local, S_local), _NEG_INF, jnp.float32)
        l = jnp.zeros((B, H_local, S_local), jnp.float32)
        acc = jnp.zeros((B, H_local, S_local, hd), jnp.float32)
        state = (m, l, acc)
        kv = (k_blk, v_blk)

        # Hop h: the KV block resident on rank r originated at rank r - h.
        for h in range(sp):
            src = (r - h) % sp
            pos_k = src * S_local + jnp.arange(S_local, dtype=jnp.int32)
            mask = (
                (pos_k[None, None, :] <= pos_q[None, :, None])
                & (pos_k[None, None, :] < lengths[:, None, None])
            )  # [B, Tq, Tk]: causal & within each row's valid length
            # Block-causal skip: a KV block strictly above the queries
            # contributes nothing; its (all -inf) flash update is computed
            # on otherwise-idle lanes and discarded, preserving one uniform
            # program across ranks (SPMD requirement).
            contributes = src <= r
            merged = _merge(state, _flash_block(q_blk, kv[0], kv[1], mask, scale))
            state = jax.tree.map(
                lambda new, old: jnp.where(contributes, new, old),
                merged,
                state,
            )
            if h + 1 < sp:
                kv = jax.lax.ppermute(kv, axis, perm)

        m, l, acc = state
        out = acc / jnp.maximum(l, 1e-20)[..., None]  # [B, H, Tq, hd]
        return out.transpose(0, 2, 1, 3).astype(q_blk.dtype)  # [B, Tq, H, hd]

    seq = P(None, axis, head_axis)
    out = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(seq, seq, seq, P()),
        out_specs=seq,
    )(q, k, v, lengths)
    return out
