"""Pallas TPU matmul over packed int4 weights with group-wise scales.

Why a kernel: the XLA formulation of int4 dequant (unpack nibbles →
stack/reshape → scale → dot) defeats operand fusion — XLA materializes the
dequantized bf16 weight matrix to HBM every step, which costs MORE
bandwidth than serving int8 and transiently allocates a full layer of bf16
weights (the OOM/latency cliff the 8B int4 smoke hit). int8 survives in
XLA because its dequant is a bare convert, which does fuse.

The kernel keeps the stream at the true 0.5 byte/weight: packed tiles DMA
from HBM once; the two nibble planes are derived in VMEM (arithmetic
shifts — no interleave/relayout, which Mosaic would hate); each group's
contribution is TWO MXU dots (even rows against the low plane, odd rows
against the high plane — the caller pre-splits x, so no reshuffle
anywhere), scaled per group POST-dot (a group's scale only varies along
the output axis, so it commutes with the contraction).

Layout contract (matches models/llama.py quantize_leaf_int4):
  x       [N, din]        activations (bf16/f32)
  packed  [din/2, dout]   int8, original row 2i in the low nibble of
                          packed row i, row 2i+1 in the high nibble
  scales  [G, dout]       f32, G = din/128 groups along the contraction
Returns [N, dout] f32.

Constraints: group size 128, din % 1024 == 0, dout % 128 == 0 — all real
checkpoint shapes (8B: 4096/14336/1024 contractions) qualify; tiny debug
shapes fall back to the XLA path in the caller.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

GROUP = 128
# Groups folded into one grid step: 8 groups = 512 packed rows per DMA
# (256 KB at dout-tile 512) — deep enough to amortize per-cell overhead,
# small enough to double-buffer comfortably in VMEM.
GROUPS_PER_TILE = 8
IN_TILE = GROUP * GROUPS_PER_TILE  # original rows per grid step


def _interpret() -> bool:
    return bool(os.environ.get("PST_FORCE_PALLAS_INTERPRET"))


def kernel_supports(din: int, dout: int, group: int) -> bool:
    return group == GROUP and din % IN_TILE == 0 and dout % 128 == 0


def use_int4_kernel(packed: jax.Array, scales: jax.Array) -> bool:
    """True when this (packed, scales) pair should go through the kernel:
    serving-scale shapes on a TPU backend (or forced interpret). Tiny/odd
    shapes and non-TPU backends use the XLA dequant fallback."""
    if packed.ndim != 2 or os.environ.get("PST_DISABLE_PALLAS"):
        return False
    din, dout = packed.shape[-2] * 2, packed.shape[-1]
    group = din // scales.shape[-2]
    if not kernel_supports(din, dout, group):
        return False
    if _interpret():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _kernel(xe_ref, xo_ref, p_ref, s_ref, o_ref, *, groups: int):
    k = pl.program_id(2)
    acc = jnp.zeros(o_ref.shape, jnp.float32)
    half = GROUP // 2  # packed rows per group
    p = p_ref[...]  # [groups*half, tj] int8
    # Mosaic has no i8 vector shifts (arith.shli on vector<i8> fails to
    # legalize) — widen to i32, extract nibbles there. lo sign-extends the
    # low 4 bits via a 28-bit round trip; hi is a plain arithmetic shift
    # (p is already sign-extended by the i8→i32 convert).
    p32 = p.astype(jnp.int32)
    lo = jnp.right_shift(jnp.left_shift(p32, 28), 28)
    hi = jnp.right_shift(p32, 4)
    xe = xe_ref[...]
    dt = xe.dtype
    # Packed row i holds original rows 2i/2i+1, both in group i // half —
    # ONE scale expansion (broadcast over the half rows of each group)
    # serves both planes, and each plane contracts in a single big MXU dot
    # (per-group dots were issue-latency-bound: 16 tiny [tn,64] dots per
    # cell cost ~20 µs of fixed overhead).
    s = s_ref[...].astype(dt)  # [groups, tj]
    s_exp = jnp.broadcast_to(
        s[:, None, :], (s.shape[0], half, s.shape[1])
    ).reshape(s.shape[0] * half, s.shape[1])
    # f32 activations ask for HIGHEST (exact) contraction — the op is
    # HBM-bound, so the extra MXU passes are free. bf16 must use the
    # native path (Mosaic rejects fp32 contract precision on bf16
    # operands: "Bad lhs type").
    prec = jax.lax.Precision.HIGHEST if dt == jnp.float32 else None
    ge = jax.lax.dot_general(
        xe, lo.astype(dt) * s_exp, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )
    go = jax.lax.dot_general(
        xo_ref[...], hi.astype(dt) * s_exp, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )
    acc = acc + ge + go

    @pl.when(k == 0)
    def _init():
        o_ref[...] = acc

    @pl.when(k > 0)
    def _accum():
        o_ref[...] += acc


@functools.partial(jax.jit, static_argnames=("out_tile",))
def int4_matmul(
    x: jax.Array,
    packed: jax.Array,
    scales: jax.Array,
    out_tile: int = 512,
) -> jax.Array:
    """``x @ dequant(packed, scales)`` in fp32, streaming 0.5 B/weight."""
    N, din = x.shape
    dout = packed.shape[1]
    assert packed.shape[0] * 2 == din, (packed.shape, din)
    assert scales.shape == (din // GROUP, dout), scales.shape
    # Split even/odd contraction rows once (cheap XLA strided slices of the
    # small activation) so the kernel never reshuffles anything.
    xe = x[:, 0::2]
    xo = x[:, 1::2]
    tj = out_tile
    while dout % tj:
        tj //= 2
    # Row tile: pad N up to a sublane-friendly size.
    tn = 256 if N > 256 else max(8, 1 << (N - 1).bit_length())
    pad = -N % tn
    if pad:
        xe = jnp.pad(xe, ((0, pad), (0, 0)))
        xo = jnp.pad(xo, ((0, pad), (0, 0)))
    ni = (N + pad) // tn
    nj = dout // tj
    nk = din // IN_TILE
    half_tile = IN_TILE // 2  # packed rows per grid step

    out = pl.pallas_call(
        functools.partial(_kernel, groups=GROUPS_PER_TILE),
        grid=(ni, nj, nk),
        in_specs=[
            pl.BlockSpec((tn, half_tile), lambda i, j, k: (i, k)),
            pl.BlockSpec((tn, half_tile), lambda i, j, k: (i, k)),
            pl.BlockSpec((half_tile, tj), lambda i, j, k: (k, j)),
            pl.BlockSpec((GROUPS_PER_TILE, tj), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((tn, tj), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((N + pad, dout), jnp.float32),
        interpret=_interpret(),
    )(xe, xo, packed, scales)
    return out[:N] if pad else out
