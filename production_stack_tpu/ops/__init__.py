from .attention import paged_attention  # noqa: F401
