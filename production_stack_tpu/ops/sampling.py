"""On-device token sampling: temperature / top-k / top-p / min-p + penalties.

Runs inside the engine's jitted step so logits never leave the device (only
the sampled token ids — ``[B]`` int32 — cross to host). Truncated to the top
``SAMPLE_K_CAP`` logits before filtering: exact for any vocab when the cap
covers it, and the standard serving approximation for 100k+ vocabs (mass
outside the top-256 is negligible post-temperature).

Greedy rows (temperature ≈ 0) take a pure argmax of the raw logits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

SAMPLE_K_CAP = 256
# Top-logprob entries returned per sampled token (OpenAI allows up to 20).
LOGPROBS_K = 20
# Packed row layout (see sample_tokens_packed): token, chosen logprob,
# LOGPROBS_K top logprobs, LOGPROBS_K top token ids.
PACKED_WIDTH = 2 + 2 * LOGPROBS_K
_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def sample_tokens(
    logits: jax.Array,  # [B, V] float32
    temps: jax.Array,  # [B]
    top_ps: jax.Array,  # [B]
    top_ks: jax.Array,  # [B] int32 (<=0: disabled)
    min_ps: jax.Array,  # [B]
    seeds: jax.Array,  # [B] uint32 (per-seq, per-step)
    greedy_only: bool = False,
) -> jax.Array:
    """``greedy_only`` is a trace-time constant set by the runner when every
    row in the batch is greedy: skips the top-k/softmax/gumbel machinery
    entirely (a top_k over a 128k vocab costs real milliseconds per decode
    scan step, and greedy batches — the common serving case — need only the
    argmax XLA fuses into the unembed matmul's epilogue)."""
    if greedy_only:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    B, V = logits.shape
    K = min(V, SAMPLE_K_CAP)
    greedy = temps <= 1e-5
    t = jnp.maximum(temps, 1e-5)[:, None]

    vals, idxs = jax.lax.top_k(logits, K)  # [B, K] descending
    scaled = vals / t
    probs = jax.nn.softmax(scaled, axis=-1)

    col = jnp.arange(K, dtype=jnp.int32)[None, :]
    kk = jnp.where(top_ks <= 0, K, jnp.minimum(top_ks, K))[:, None]
    keep = col < kk
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_ps[:, None]  # keep first token crossing top_p
    keep &= probs >= min_ps[:, None] * probs[:, :1]
    keep = keep.at[:, 0].set(True)

    def one(seed, row, mask):
        g = jax.random.gumbel(jax.random.PRNGKey(seed), (K,), jnp.float32)
        return jnp.argmax(jnp.where(mask, row + g, _NEG))

    choice = jax.vmap(one)(seeds, scaled, keep)  # [B]
    sampled = jnp.take_along_axis(idxs, choice[:, None], axis=1)[:, 0]
    return jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled).astype(jnp.int32)


def sample_tokens_packed(
    logits: jax.Array,  # [B, V] float32
    temps: jax.Array,
    top_ps: jax.Array,
    top_ks: jax.Array,
    min_ps: jax.Array,
    seeds: jax.Array,
    with_logprobs: bool = False,
    greedy_only: bool = False,
) -> jax.Array:
    """Sample into ONE packed f32 array — ``[token]`` per row, or with
    ``with_logprobs`` (a trace-time constant: the runner compiles separate
    no-logprobs/logprobs step variants, like its penalties gating)
    ``[token, chosen_logprob, top_lps(K), top_ids(K)]``.

    Packing matters on remote-attached chips: one array = one host fetch.
    Token ids ride as f32 — exact for any vocab < 2^24. Logprobs are raw
    ``log_softmax(logits)`` (pre-temperature, the OpenAI/vLLM convention);
    gating them keeps the full-vocab log_softmax + top-k out of the
    latency-critical decode path when nobody asked."""
    tokens = sample_tokens(
        logits, temps, top_ps, top_ks, min_ps, seeds, greedy_only=greedy_only
    )
    if not with_logprobs:
        return tokens[:, None].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)  # [B, V]
    chosen = jnp.take_along_axis(logp, tokens[:, None], axis=1)  # [B, 1]
    top_lps, top_ids = jax.lax.top_k(logp, LOGPROBS_K)
    return jnp.concatenate(
        [
            tokens[:, None].astype(jnp.float32),
            chosen,
            top_lps,
            top_ids.astype(jnp.float32),
        ],
        axis=1,
    )


def unpack_sampled(packed) -> tuple:
    """Host-side view of a packed row array (any leading dims):
    (tokens int, chosen_lp, top_lps [..., K], top_ids [..., K] int)."""
    import numpy as np

    tokens = packed[..., 0].astype(np.int64)
    chosen = packed[..., 1]
    top_lps = packed[..., 2 : 2 + LOGPROBS_K]
    top_ids = packed[..., 2 + LOGPROBS_K :].astype(np.int64)
    return tokens, chosen, top_lps, top_ids


def apply_logit_bias(
    logits: jax.Array,  # [B, V] float32
    bias_ids: jax.Array,  # [B, Nb] int32, pad = V (dropped)
    bias_vals: jax.Array,  # [B, Nb] float32
) -> jax.Array:
    """OpenAI ``logit_bias``: additive per-token offsets before sampling."""
    B = logits.shape[0]
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    return logits.at[rows, bias_ids].add(bias_vals, mode="drop")


def apply_allowed_mask(
    logits: jax.Array,  # [B, V] float32
    allowed_ids: jax.Array,  # [B, Na] int32, pad = V (dropped)
    allow_free: jax.Array,  # [B] bool — True: row is unconstrained
) -> jax.Array:
    """Guided decoding: restrict each constrained row to its allowed token
    set (everything else to -inf); unconstrained rows pass through."""
    B, V = logits.shape
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    mask = (
        jnp.zeros((B, V), jnp.bool_)
        .at[rows, allowed_ids]
        .set(True, mode="drop")
    )
    mask = mask | allow_free[:, None]
    return jnp.where(mask, logits, _NEG)


def apply_penalties_counts(
    logits: jax.Array,  # [B, V] float32
    prompt_seen: jax.Array,  # [B, V] bool
    out_counts: jax.Array,  # [B, V] float32 (output-token occurrence counts)
    presence: jax.Array,  # [B]
    frequency: jax.Array,  # [B]
    repetition: jax.Array,  # [B]
) -> jax.Array:
    """Penalty math over *dense* per-vocab state. This is the form a
    decode-burst scan can carry: ``out_counts`` updates on-device after
    every sampled token (``multi_step``'s scan carry in engine/runner.py),
    so penalty/repetition rows ride multi-step bursts instead of forcing
    the whole batch to n=1 single-step dispatches."""
    seen = prompt_seen | (out_counts > 0)
    rep = repetition[:, None]
    logits = jnp.where(
        seen, jnp.where(logits > 0, logits / rep, logits * rep), logits
    )
    logits = logits - frequency[:, None] * out_counts
    logits = logits - presence[:, None] * (out_counts > 0).astype(jnp.float32)
    return logits


def apply_penalties(
    logits: jax.Array,  # [B, V] float32
    prompt_tokens: jax.Array,  # [B, Pp] int32, pad = V (dropped)
    output_tokens: jax.Array,  # [B, Po] int32, pad = V (dropped)
    presence: jax.Array,  # [B]
    frequency: jax.Array,  # [B]
    repetition: jax.Array,  # [B]
) -> jax.Array:
    """vLLM-convention penalties: repetition over prompt+output occurrence;
    presence/frequency over output counts. Token-id-array form used by the
    single-step path; scatters into the dense state and delegates to
    :func:`apply_penalties_counts` so the two paths cannot drift."""
    B, V = logits.shape
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    out_counts = (
        jnp.zeros((B, V), jnp.float32)
        .at[rows, output_tokens]
        .add(1.0, mode="drop")
    )
    prompt_seen = (
        jnp.zeros((B, V), jnp.bool_)
        .at[rows, prompt_tokens]
        .set(True, mode="drop")
    )
    return apply_penalties_counts(
        logits, prompt_seen, out_counts, presence, frequency, repetition
    )
