"""Pallas TPU flash kernels over paged KV: decode and chunked prefill.

The hot ops of the serving loop (the role vLLM's CUDA PagedAttention +
flash-attn kernels play behind the reference stack). Both are HBM-bandwidth
bound at the reference's long-context protocol (20k-token histories, 32k
max_model_len — ``BASELINE.md``), so the kernel is organized around DMA
efficiency, not grid geometry:

- KV lives in one combined page array ``[nb, 2, bs, KH*hd]`` (a page holds
  its K rows then V rows, each token row spanning **all** kv heads in the
  lane dimension), so one async copy moves an entire page — 100s of KB per
  DMA instead of the 8 KB per-head fragments a ``[KH, nb, bs, hd]`` layout
  forces. The head fold keeps the minor dims at ``(bs, KH*hd)``: both
  tiling-exact, no sublane padding (a ``[..., KH, hd]`` tail would pad
  KH=8 → 16 sublanes and physically double the cache).
- The grid is tiny — ``(B,)`` for decode, ``(B, T/Tq)`` for prefill — and
  each cell walks its sequence's **live** pages with a double-buffered
  ``fori_loop`` (chunks of ``C`` pages), overlapping the next chunk's DMAs
  with the current chunk's flash accumulation. Pages past ``kv_len`` — and,
  for prefill, pages entirely above the tile's causal horizon — are never
  fetched at all (the round-2 kernel's ``pl.when`` skipped the *compute* but
  the BlockSpec pipeline still paid the *DMA*; that was the round-2 TTFT
  regression).
- Flash state (m/l/acc) is head-major in VMEM scratch so per-head slices are
  contiguous; grouped-query heads share each page read.

Scalar-prefetched block tables address the pages (``PrefetchScalarGridSpec``)
so page ids are in SMEM before the body runs.

The kernels take the FULL stacked cache ``[L, nb, 2, bs, KH*hd]`` plus a
(possibly traced) layer index rather than a per-layer slice: inside the
model's layer scan a slice would materialize the whole 100s-of-MB layer
cache as a copy per layer per step, while the ANY-space operand costs
nothing — the DMA engine reads only the pages the sequence actually needs.

Shapes:
  q           [B, T, H, hd]        T=1 decode, T=chunk prefill
  kv_pages    [L, nb, 2, bs, KH*hd] combined K(row 0)/V(row 1) pages
  tables      [B, W] int32         page ids (W*bs >= kv_len)
  kv_lens     [B] int32            valid KV length per sequence (0 = padding)
  q_positions [B, T] int32         absolute position per query token; the
                                   prefill kernel uses row 0 (chunks are
                                   consecutive positions — runner contract)
  layer       int32 scalar         layer to read (scalar-prefetched)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import window_eff

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _pv_dot(p, v):
    """probs @ V with fp32 accumulation, correct for quantized caches.

    With an fp8 cache, casting probs to e4m3 for the dot quantizes the
    softmax weights themselves to ~2 significant digits (caught by the
    model-level numerics oracle) — but converting the STREAMED V chunks up
    to bf16 costs a per-chunk relayout that measured 6x slower end to end.
    Instead: split-precision in fp8. The main dot uses e4m3-rounded probs;
    a second dot carries the 16x-scaled rounding residual (≤ p/16, so the
    scale re-centers it in e4m3's mantissa range). Effective probs
    precision ~2^-8 — bf16-equivalent — while V never leaves its 1-byte
    layout and the PV MXU cost (a small slice of a DMA-bound kernel)
    merely doubles."""
    if jnp.dtype(v.dtype).itemsize != 1:
        return jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    p8 = p.astype(v.dtype)
    resid = ((p - p8.astype(jnp.float32)) * 16.0).astype(v.dtype)
    main = jax.lax.dot_general(
        p8, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    fix = jax.lax.dot_general(
        resid, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return main + fix * 0.0625


def _interpret() -> bool:
    return bool(os.environ.get("PST_FORCE_PALLAS_INTERPRET"))


def _chunk_pages(bs: int, target_tokens: int) -> int:
    """Pages per DMA buffer slot (~target_tokens per chunk). Decode uses
    bigger chunks than prefill: its per-chunk fixed cost (fori iteration,
    semaphore waits, G-row flash updates on mostly-empty vregs) dominates
    at long context, while prefill's larger per-chunk compute amortizes it
    already — and prefill's VMEM budget also carries the big q tile."""
    return max(target_tokens // bs, 1)


def _page_dma_loop(
    *,
    b,  # batch index (program id)
    layer,  # int32 layer index into the stacked cache
    n_chunks,  # traced: chunks of C pages to stream (exclusive end)
    tables_ref,  # [B, W] SMEM
    kv_hbm,  # [L, nb, 2, bs, KH*hd] ANY
    buf,  # [2, C, 2, bs, KH*hd] VMEM scratch
    sems,  # [2, C] DMA semaphores
    chunk: int,
    table_width: int,
    compute_chunk,  # (page [C, 2, bs, KH*hd], chunk_index) -> None
    c_start=0,  # traced: first live chunk (sliding window skips below it)
):
    """Double-buffered page streaming shared by decode and prefill: chunk
    ``c+1``'s DMAs are in flight while ``compute_chunk`` folds chunk ``c``.
    Chunks below ``c_start`` (entirely outside a sliding window) are neither
    fetched nor folded."""
    C, W = chunk, table_width

    def dma(c, j, slot):
        # Page ids past the live range clamp to the table's last entry;
        # their columns are masked by the caller (only the ragged final
        # chunk fetches any).
        page = tables_ref[b, jnp.minimum(c * C + j, W - 1)]
        return pltpu.make_async_copy(
            kv_hbm.at[layer, page], buf.at[slot, j], sems.at[slot, j]
        )

    @pl.when(n_chunks > c_start)
    def _warmup():
        for j in range(C):
            dma(c_start, j, jax.lax.rem(c_start, 2)).start()

    def body(c, _):
        slot = jax.lax.rem(c, 2)
        nslot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _next():
            for j in range(C):
                dma(c + 1, j, nslot).start()

        for j in range(C):
            dma(c, j, slot).wait()
        compute_chunk(buf[slot], c)
        return 0

    jax.lax.fori_loop(c_start, n_chunks, body, 0)


def _chunked_flash(
    *,
    b, layer, n_chunks, tables_ref, kv_hbm, buf, sems,
    q_heads,  # list of KH arrays [R, hd] (native dtype)
    bounds,  # [R, 1] exclusive per-row attention bound (causality + kv_len)
    m_ref,  # [KH, R, 128] fp32 scratch (col 0 live)
    l_ref,  # [KH, R, 128]
    acc_ref,  # [KH, R, hd]
    scale: float,
    block_size: int,
    chunk: int,
    table_width: int,
    head_dim: int,
    lows=None,  # [R, 1] inclusive per-row lower bound (sliding window)
    softcap: float = 0.0,
    c_start=0,  # traced: first chunk any row's window reaches
):
    """Per-head flash accumulation over streamed KV chunks (the prefill
    shape: R = Tq*G rows per head keep the MXU busy per head). Matmuls run
    in the operands' native dtype with fp32 accumulation — MXU-native for
    the bf16 serving path, exact for the fp32 oracle tests."""
    hd = head_dim
    KH = acc_ref.shape[0]

    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute(page, c):
        S = chunk * block_size
        col = c * S + jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        for h in range(KH):
            kh = page[:, 0, :, h * hd : (h + 1) * hd].reshape(S, hd)
            vh = page[:, 1, :, h * hd : (h + 1) * hd].reshape(S, hd)
            s = jax.lax.dot_general(
                q_heads[h], kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [R, S] fp32
            if softcap:
                s = jnp.tanh(s / softcap) * softcap
            live = col < bounds
            if lows is not None:
                live = live & (col >= lows)
            s = jnp.where(live, s, _NEG_INF)
            m_prev = m_ref[h, :, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[h, :, :1] = alpha * l_ref[h, :, :1] + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_ref[h, :, :1] = m_new
            acc_ref[h] = acc_ref[h] * alpha + _pv_dot(p, vh)

    _page_dma_loop(
        b=b, layer=layer, n_chunks=n_chunks, tables_ref=tables_ref,
        kv_hbm=kv_hbm, buf=buf, sems=sems, chunk=chunk,
        table_width=table_width, compute_chunk=compute, c_start=c_start,
    )


def _decode_kernel(
    tables_ref, lens_ref, layer_ref, win_ref,  # scalar prefetch (SMEM)
    q_ref,  # [1, H, hd] VMEM
    kv_hbm,  # [L, nb, 2, bs, KH*hd] ANY
    o_ref,  # [1, H, hd] VMEM
    buf, sems, m_ref, l_ref, acc_ref,  # scratch (m/l [H,128], acc [H,hd])
    *,
    scale: float,
    block_size: int,
    chunk: int,
    table_width: int,
    group: int,
    head_dim: int,
    softcap: float = 0.0,
):
    """Dense folded-q decode: per-head [G, hd] x [hd, S] mat-vecs waste the
    MXU (G of 128 rows live) and burn VPU on per-head slices, so instead q
    is scattered block-diagonally into the page's lane layout —
    ``q_sparse[r]`` holds row r's head at lane block r//G, zeros elsewhere —
    and ONE [H, KH*hd] x [KH*hd, S] matmul per chunk yields every head's
    scores (cross-head lanes contribute exact zeros). The p@V product runs
    dense the same way; each row's own head block is extracted from
    [H, KH, hd] with the same mask. ~KH x more MACs, all on otherwise-idle
    MXU rows; the VPU flash update shrinks from KH G-row passes to one
    full-vreg [H, S] pass."""
    b = pl.program_id(0)
    G, hd = group, head_dim
    H = q_ref.shape[1]
    KH = H // G
    kv_len = lens_ref[b]
    n_chunks = (kv_len + chunk * block_size - 1) // (chunk * block_size)
    # Sliding window (0 = unlimited): the one query row sits at position
    # kv_len-1 and may see positions >= kv_len - window; whole chunks below
    # that are never fetched.
    lo = jnp.maximum(kv_len - window_eff(win_ref[0]), 0)
    c_start = lo // (chunk * block_size)

    q = q_ref[0]  # [H, hd] native dtype
    # Arithmetic 0/1 mask (born 3D): Mosaic cannot minor-dim-reshape or
    # relayout sub-32-bit (bool) vectors, so the block-diagonal selector is
    # built as floats and applied by multiplication.
    row_head = jax.lax.broadcasted_iota(jnp.int32, (H, KH, 1), 0) // G
    head_idx = jax.lax.broadcasted_iota(jnp.int32, (H, KH, 1), 1)
    blockdiag = (row_head == head_idx).astype(jnp.float32)  # [H, KH, 1]
    q_sparse = (
        q[:, None, :] * blockdiag.astype(q.dtype)
    ).reshape(H, KH * hd)

    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    def compute(page, c):
        S = chunk * block_size
        k = page[:, 0].reshape(S, KH * hd)
        v = page[:, 1].reshape(S, KH * hd)
        col = c * S + jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        s = jax.lax.dot_general(
            q_sparse, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [H, S] fp32
        if softcap:
            s = jnp.tanh(s / softcap) * softcap
        s = jnp.where((col >= lo) & (col < kv_len), s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        pv = _pv_dot(p, v).reshape(H, KH, hd)
        own = (pv * blockdiag).sum(axis=1)  # each row's own head block
        acc_ref[...] = acc_ref[...] * alpha + own

    _page_dma_loop(
        b=b, layer=layer_ref[0], n_chunks=n_chunks, tables_ref=tables_ref,
        kv_hbm=kv_hbm, buf=buf, sems=sems, chunk=chunk,
        table_width=table_width, compute_chunk=compute, c_start=c_start,
    )
    out = acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-20)  # [H, hd]
    o_ref[0] = out.astype(o_ref.dtype)


def _decode_write_kernel(
    tables_ref, lens_ref, layer_ref, win_ref, wf_ref,  # scalar prefetch
    q_ref,  # [1, H, hd] VMEM
    k_ref,  # [1, 1, KH*hd] VMEM — this step's K row for this sequence
    v_ref,  # [1, 1, KH*hd] VMEM
    kv_hbm,  # [L, nb, 2, bs, KH*hd] ANY (aliased with kv_out)
    o_ref,  # [1, H, hd] VMEM
    kv_out,  # [L, nb, 2, bs, KH*hd] ANY — the SAME buffer (in-place)
    buf, sems, wbuf, wsems, m_ref, l_ref, acc_ref,
    **kw,
):
    """Decode step with the KV write folded in: each grid cell pulls its
    write page into VMEM, splices the new K/V row in with a masked select
    (sub-row DMA into a tiled fp8 page is not expressible — HBM slices
    must be tiling-aligned), pushes the page back, waits, then runs the
    standard flash read loop — the row just written is the newest position
    and is read back in the final chunk. Folding removes the per-layer
    XLA scatter from the decode step (a fixed ~0.2 ms x layers of pure op
    overhead on a 10 GiB carried buffer); the page round trip is ~512 KB
    per sequence per layer, noise next to the KV stream."""
    b = pl.program_id(0)
    bs = kv_hbm.shape[3]
    nb = kv_hbm.shape[1]
    wf = wf_ref[b]
    ly = layer_ref[0]

    @pl.when(wf < nb * bs)
    def _write():
        blk = wf // bs
        pos = wf % bs
        pull = pltpu.make_async_copy(
            kv_out.at[ly, blk], wbuf, wsems.at[0]
        )
        pull.start()
        pull.wait()
        row = jax.lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        mask = row == pos
        page_k = jnp.where(
            mask, k_ref[0].astype(jnp.float32), wbuf[0].astype(jnp.float32)
        ).astype(wbuf.dtype)
        page_v = jnp.where(
            mask, v_ref[0].astype(jnp.float32), wbuf[1].astype(jnp.float32)
        ).astype(wbuf.dtype)
        wbuf[0] = page_k
        wbuf[1] = page_v
        push = pltpu.make_async_copy(
            wbuf, kv_out.at[ly, blk], wsems.at[1]
        )
        push.start()
        push.wait()

    _decode_kernel(
        tables_ref, lens_ref, layer_ref, win_ref,
        q_ref, kv_out, o_ref, buf, sems, m_ref, l_ref, acc_ref, **kw,
    )


def pallas_paged_attention_decode_write(
    q3: jax.Array,  # [B, H, hd]
    kv_pages: jax.Array,  # [L, nb, 2, bs, KH*hd] (donated by the caller)
    block_tables: jax.Array,  # [B, W]
    kv_lens: jax.Array,  # [B] valid length INCLUDING the row being written
    layer,  # int32 scalar
    k_new: jax.Array,  # [B, KH*hd]
    v_new: jax.Array,  # [B, KH*hd]
    write_flat: jax.Array,  # [B] flat slot blk*bs+pos; >= nb*bs drops
    *,
    scale: float,
    window=0,
    softcap: float = 0.0,
) -> "tuple[jax.Array, jax.Array]":
    """Fused write+attend decode step. Returns (out [B, H, hd], cache).
    The cache is updated IN PLACE (input/output aliased)."""
    B, H, hd, bs, lanes, C, kw, scratch, flash = _decode_geometry(
        q3, kv_pages, block_tables, scale=scale, softcap=softcap
    )
    nb = kv_pages.shape[1]
    tables = block_tables.astype(jnp.int32)
    lens = kv_lens.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    win_arr = jnp.asarray(window, jnp.int32).reshape(1)
    wf = write_flat.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda b, t, l, ly, w, f: (b, 0, 0)),
            # [B, 1, lanes] with a singleton sublane dim: a (1, lanes)
            # trailing block is only legal when the sublane block equals
            # the array dim.
            pl.BlockSpec((1, 1, lanes), lambda b, t, l, ly, w, f: (b, 0, 0)),
            pl.BlockSpec((1, 1, lanes), lambda b, t, l, ly, w, f: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, H, hd), lambda b, t, l, ly, w, f: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=scratch + [
            pltpu.VMEM((2, bs, lanes), kv_pages.dtype),  # write page
            pltpu.SemaphoreType.DMA((2,)),
        ] + flash,
    )
    kernel = functools.partial(_decode_write_kernel, **kw)
    out, cache = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, hd), q3.dtype),
            jax.ShapeDtypeStruct(kv_pages.shape, kv_pages.dtype),
        ],
        # Operand index 8 = kv_pages (after 5 scalar-prefetch args and
        # q/k/v); aliased onto output 1 so the 10 GiB cache updates in
        # place instead of copying.
        input_output_aliases={8: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=_interpret(),
    )(tables, lens, layer_arr, win_arr, wf,
      q3,
      k_new.astype(kv_pages.dtype)[:, None],
      v_new.astype(kv_pages.dtype)[:, None],
      kv_pages)
    return out, cache


def _prefill_kernel(
    tables_ref, lens_ref, starts_ref, layer_ref, win_ref,  # scalar prefetch
    q_ref,  # [1, Tq, H, hd] VMEM
    kv_hbm,  # [L, nb, 2, bs, KH*hd] ANY
    o_ref,  # [1, Tq, H, hd] VMEM
    buf, sems, m_ref, l_ref, acc_ref,  # scratch
    *,
    scale: float,
    block_size: int,
    chunk: int,
    table_width: int,
    group: int,
    head_dim: int,
    q_tile: int,
    softcap: float = 0.0,
):
    b = pl.program_id(0)
    tq = pl.program_id(1)
    G, Tq, KH = group, q_tile, acc_ref.shape[0]
    kv_len = lens_ref[b]
    start = starts_ref[b]

    # Rows t*G+g of each head cover absolute positions start + tq*Tq + t.
    # The tile's causal horizon is its last row's position; pages past
    # min(horizon+1, kv_len) are never fetched (≈ halves page traffic over a
    # full prefill, while warm tiles near the sequence end still stream every
    # live page — exactly the data they need).
    limit = jnp.minimum(kv_len, start + (tq + 1) * Tq)
    n_chunks = (limit + chunk * block_size - 1) // (chunk * block_size)

    rows = jax.lax.broadcasted_iota(jnp.int32, (Tq * G, 1), 0)
    q_pos = start + tq * Tq + rows // G  # [Tq*G, 1]
    bounds = jnp.minimum(q_pos + 1, kv_len)
    # Sliding window lower bounds; chunks below the tile's FIRST row's
    # window start are outside every row's window and are never fetched.
    win_eff = window_eff(win_ref[0])
    lows = jnp.maximum(q_pos + 1 - win_eff, 0)  # [Tq*G, 1]
    c_start = jnp.maximum(start + tq * Tq + 1 - win_eff, 0) // (
        chunk * block_size
    )

    qh = [
        q_ref[0, :, h * G : (h + 1) * G, :].reshape(Tq * G, head_dim)
        for h in range(KH)
    ]
    _chunked_flash(
        b=b,
        layer=layer_ref[0],
        n_chunks=n_chunks,
        tables_ref=tables_ref,
        kv_hbm=kv_hbm,
        buf=buf,
        sems=sems,
        q_heads=qh,
        bounds=bounds,
        m_ref=m_ref,
        l_ref=l_ref,
        acc_ref=acc_ref,
        scale=scale,
        block_size=block_size,
        chunk=chunk,
        table_width=table_width,
        head_dim=head_dim,
        lows=lows,
        softcap=softcap,
        c_start=c_start,
    )
    # Padding rows (kv_len == 0) accumulated nothing: l stays 0 and the
    # output is 0, matching the drop-slot contract.
    for h in range(KH):
        out = acc_ref[h] / jnp.maximum(l_ref[h, :, :1], 1e-20)  # [Tq*G, hd]
        o_ref[0, :, h * G : (h + 1) * G, :] = out.reshape(
            Tq, G, head_dim
        ).astype(o_ref.dtype)


def _scratch(C, bs, lanes, R, KH, hd, kv_dtype):
    return [
        pltpu.VMEM((2, C, 2, bs, lanes), kv_dtype),
        pltpu.SemaphoreType.DMA((2, C)),
        pltpu.VMEM((KH, R, 128), jnp.float32),
        pltpu.VMEM((KH, R, 128), jnp.float32),
        pltpu.VMEM((KH, R, hd), jnp.float32),
    ]


def _decode_geometry(q3, kv_pages, block_tables, *, scale, softcap):
    """Shared decode-call geometry: chunking, flash scratch, and the kernel
    kwargs — ONE source of truth for the plain and fused-write wrappers
    (a tuning change here reaches both)."""
    B, H, hd = q3.shape
    _, nb, _, bs, lanes = kv_pages.shape
    KH = lanes // hd
    W = block_tables.shape[1]
    G = H // KH
    C = _chunk_pages(bs, 1024)
    kwargs = dict(
        scale=scale, block_size=bs, chunk=C, table_width=W, group=G,
        head_dim=hd, softcap=softcap,
    )
    scratch = [
        pltpu.VMEM((2, C, 2, bs, lanes), kv_pages.dtype),
        pltpu.SemaphoreType.DMA((2, C)),
    ]
    flash_scratch = [
        pltpu.VMEM((H, 128), jnp.float32),
        pltpu.VMEM((H, 128), jnp.float32),
        pltpu.VMEM((H, hd), jnp.float32),
    ]
    return B, H, hd, bs, lanes, C, kwargs, scratch, flash_scratch


def _decode_call(q3, kv_pages, block_tables, kv_lens, layer, window,
                 *, scale, softcap):
    B, H, hd, bs, lanes, C, kw, scratch, flash = _decode_geometry(
        q3, kv_pages, block_tables, scale=scale, softcap=softcap
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda b, t, l, ly, w: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, H, hd), lambda b, t, l, ly, w: (b, 0, 0)),
        scratch_shapes=scratch + flash,
    )
    kernel = functools.partial(_decode_kernel, **kw)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, hd), q3.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, layer, window, q3, kv_pages)


def _prefill_call(q, kv_pages, block_tables, kv_lens, starts, layer, window,
                  *, scale, q_tile, softcap):
    B, T, H, hd = q.shape
    _, nb, _, bs, lanes = kv_pages.shape
    KH = lanes // hd
    W = block_tables.shape[1]
    G = H // KH
    C = _chunk_pages(bs, 512)
    n_tiles = T // q_tile

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B, n_tiles),
        in_specs=[
            pl.BlockSpec(
                (1, q_tile, H, hd), lambda b, t, tt, l, s, ly, w: (b, t, 0, 0)
            ),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, q_tile, H, hd), lambda b, t, tt, l, s, ly, w: (b, t, 0, 0)
        ),
        scratch_shapes=_scratch(C, bs, lanes, q_tile * G, KH, hd, kv_pages.dtype),
    )
    kernel = functools.partial(
        _prefill_kernel,
        scale=scale,
        block_size=bs,
        chunk=C,
        table_width=W,
        group=G,
        head_dim=hd,
        q_tile=q_tile,
        softcap=softcap,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, H, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            # The 256-row q tile + 512-token KV chunks exceed the default
            # 16 MiB scoped-vmem budget; the chip has far more.
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, starts, layer, window, q, kv_pages)


def pallas_paged_attention(
    q: jax.Array,  # [B, T, H, hd]
    kv_pages: jax.Array,  # [L, nb, 2, bs, KH*hd]
    block_tables: jax.Array,  # [B, W]
    kv_lens: jax.Array,  # [B]
    q_positions: jax.Array,  # [B, T] absolute positions (row 0 = chunk start)
    layer=0,  # int32 scalar (may be traced — e.g. the model's layer scan)
    *,
    scale: float,
    window=0,  # int32 scalar sliding window (may be traced; 0 = unlimited)
    softcap: float = 0.0,  # attention-logit soft cap (static; 0 = off)
) -> jax.Array:
    B, T, H, hd = q.shape
    tables = block_tables.astype(jnp.int32)
    lens = kv_lens.astype(jnp.int32)
    layer_arr = jnp.asarray(layer, jnp.int32).reshape(1)
    win_arr = jnp.asarray(window, jnp.int32).reshape(1)
    if T == 1:
        out = _decode_call(
            q[:, 0], kv_pages, tables, lens, layer_arr, win_arr,
            scale=scale, softcap=softcap,
        )
        return out[:, None]

    # Chunk positions are consecutive from row 0's position (the runner
    # builds prefill batches that way), so the kernel derives causality from
    # starts alone. Padding rows attend past their chunk; their outputs are
    # discarded downstream (last_idx / dropped writes).
    # 256-row q tiles: every tile re-streams the sequence's earlier KV, so
    # at long context halving the tile count halves attention HBM traffic.
    q_tile = min(T, 256)
    if T % q_tile:  # odd shapes: runner buckets are powers of two
        from .attention import gather_paged_attention

        return gather_paged_attention(
            q, kv_pages, block_tables, kv_lens, q_positions, layer,
            scale=scale, window=window, softcap=softcap,
        )
    starts = q_positions[:, 0].astype(jnp.int32)
    return _prefill_call(
        q, kv_pages, tables, lens, starts, layer_arr, win_arr, scale=scale,
        q_tile=q_tile, softcap=softcap,
    )
