"""Pallas TPU flash kernels over paged KV: decode and chunked prefill.

The hot ops of the serving loop (the role vLLM's CUDA PagedAttention +
flash-attn kernels play behind the reference stack). Both are
HBM-bandwidth-bound: the win over the gather fallback is that pages stream
HBM→VMEM per grid cell and are reduced online (flash accumulation) — neither
the gathered ``[B, S, ...]`` KV nor the full ``[T, S]`` score matrix ever
materializes in HBM. At the reference's long-context protocol (20k-token
histories, 32k max_model_len — ``BASELINE.md``) the gather path's
materializations are the difference between fitting and OOM.

Layout: KV pages are ``[KH, nb, bs, hd]`` (contiguous ``[bs, hd]`` tiles, the
TPU-tiling-legal arrangement). Page indices come from the block table via
scalar prefetch (``PrefetchScalarGridSpec``) so the pipeline can address HBM
pages ahead of the body.

- **Decode** (``T == 1``): grid ``(B, KH, W)``; each cell folds one page into
  fp32 flash accumulators ``[G, hd]``; the last step normalizes.
- **Chunked prefill** (``T > 1``): grid ``(B, Tt, KH, W)``. Queries are
  pre-folded to ``[B, KH, T*G, hd]`` rows (grouped-query heads share a page
  read); each cell folds one page into ``[Tq*G, hd]`` accumulators under the
  causal mask derived from the chunk's start position. Pages entirely above
  the tile's last query position are skipped — the causal triangle halves the
  page traffic, exactly the chunked-prefill capability the reference enables
  with ``--enable-chunked-prefill`` (`deployment-vllm-multi.yaml:135-141`).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret() -> bool:
    return bool(os.environ.get("PST_FORCE_PALLAS_INTERPRET"))


def _decode_kernel(
    # scalar prefetch
    tables_ref,  # [B, W] int32 (SMEM)
    lens_ref,  # [B] int32 (SMEM)
    # blocked operands
    q_ref,  # [1, 1, G, hd]
    k_ref,  # [1, 1, bs, hd]
    v_ref,  # [1, 1, bs, hd]
    o_ref,  # [1, 1, G, hd]
    # scratch
    m_ref,  # [G, 128] fp32 (col 0 live)
    l_ref,  # [G, 128] fp32 (col 0 live)
    acc_ref,  # [G, hd] fp32
    *,
    scale: float,
    block_size: int,
):
    b = pl.program_id(0)
    w = pl.program_id(2)
    n_w = pl.num_programs(2)

    @pl.when(w == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = lens_ref[b]

    @pl.when(w * block_size < kv_len)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, bs]
        kv_pos = w * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        s = jnp.where(kv_pos < kv_len, s, _NEG_INF)

        m_prev = m_ref[:, :1]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [G, bs]
        alpha = jnp.exp(m_prev - m_new)  # [G, 1]
        l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(w == n_w - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-20)
        ).astype(o_ref.dtype)


def _decode_call(q4, k_pages, v_pages, block_tables, kv_lens, *, scale):
    B, KH, G, hd = q4.shape
    _, nb, bs, _ = k_pages.shape
    W = block_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KH, W),
        in_specs=[
            pl.BlockSpec((1, 1, G, hd), lambda b, h, w, t, l: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bs, hd), lambda b, h, w, t, l: (h, t[b, w], 0, 0)),
            pl.BlockSpec((1, 1, bs, hd), lambda b, h, w, t, l: (h, t[b, w], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, h, w, t, l: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=scale, block_size=bs)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, G, hd), q4.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, q4, k_pages, v_pages)


def _prefill_kernel(
    # scalar prefetch
    tables_ref,  # [B, W] int32 (SMEM)
    lens_ref,  # [B] int32 (SMEM)
    starts_ref,  # [B] int32 (SMEM) — absolute position of chunk row 0
    # blocked operands
    q_ref,  # [1, 1, TqG, hd]
    k_ref,  # [1, 1, bs, hd]
    v_ref,  # [1, 1, bs, hd]
    o_ref,  # [1, 1, TqG, hd]
    # scratch
    m_ref,  # [TqG, 128] fp32 (col 0 live)
    l_ref,  # [TqG, 128] fp32 (col 0 live)
    acc_ref,  # [TqG, hd] fp32
    *,
    scale: float,
    block_size: int,
    q_tile: int,  # Tq (query tokens per tile)
    group: int,  # G (q heads per kv head; rows are t*G+g)
):
    b = pl.program_id(0)
    tq = pl.program_id(1)
    w = pl.program_id(3)
    n_w = pl.num_programs(3)

    @pl.when(w == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = lens_ref[b]
    start = starts_ref[b]
    # Query rows in this tile cover absolute positions
    # [start + tq*Tq, start + tq*Tq + Tq - 1]; pages past the last one are
    # entirely masked — skip them (causal triangle ≈ halves page traffic).
    tile_last_pos = start + (tq + 1) * q_tile - 1

    @pl.when((w * block_size <= tile_last_pos) & (w * block_size < kv_len))
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [TqG, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [TqG, bs]

        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)  # row = t*G+g
        q_pos = start + tq * q_tile + rows // group  # [TqG, bs]
        kv_pos = w * block_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where((kv_pos <= q_pos) & (kv_pos < kv_len), s, _NEG_INF)

        m_prev = m_ref[:, :1]  # [TqG, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(w == n_w - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-20)
        ).astype(o_ref.dtype)


def _prefill_call(qf, k_pages, v_pages, block_tables, kv_lens, starts,
                  *, scale, q_tile, group):
    B, KH, M, hd = qf.shape  # M = T*G rows
    _, nb, bs, _ = k_pages.shape
    W = block_tables.shape[1]
    tile_rows = q_tile * group
    n_tiles = M // tile_rows

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, n_tiles, KH, W),
        in_specs=[
            pl.BlockSpec(
                (1, 1, tile_rows, hd), lambda b, tq, h, w, t, l, s: (b, h, tq, 0)
            ),
            pl.BlockSpec(
                (1, 1, bs, hd), lambda b, tq, h, w, t, l, s: (h, t[b, w], 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, bs, hd), lambda b, tq, h, w, t, l, s: (h, t[b, w], 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, tile_rows, hd), lambda b, tq, h, w, t, l, s: (b, h, tq, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((tile_rows, 128), jnp.float32),
            pltpu.VMEM((tile_rows, 128), jnp.float32),
            pltpu.VMEM((tile_rows, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel,
        scale=scale,
        block_size=bs,
        q_tile=q_tile,
        group=group,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, M, hd), qf.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, starts, qf, k_pages, v_pages)


def _pick_q_tile(T: int, G: int) -> int:
    """Largest power-of-two tile with tile_rows = Tq*G in [8, 512]."""
    tq = 1
    while tq * 2 <= T and (tq * 2) * G <= 512:
        tq *= 2
    while tq * G < 8 and tq < T:  # too few sublanes: widen if possible
        tq *= 2
    return tq


def pallas_paged_attention(
    q: jax.Array,  # [B, T, H, hd]
    k_pages: jax.Array,  # [KH, nb, bs, hd]
    v_pages: jax.Array,
    block_tables: jax.Array,  # [B, W]
    kv_lens: jax.Array,  # [B]
    q_positions: jax.Array,  # [B, T] absolute positions (row 0 = chunk start)
    *,
    scale: float,
) -> jax.Array:
    B, T, H, hd = q.shape
    KH = k_pages.shape[0]
    G = H // KH
    if T == 1:
        q4 = q[:, 0].reshape(B, KH, G, hd)
        out = _decode_call(
            q4,
            k_pages,
            v_pages,
            block_tables.astype(jnp.int32),
            kv_lens.astype(jnp.int32),
            scale=scale,
        )
        return out.reshape(B, 1, H, hd)

    q_tile = _pick_q_tile(T, G)
    if T % q_tile:
        from .attention import gather_paged_attention  # odd shapes: fallback

        return gather_paged_attention(
            q, k_pages, v_pages, block_tables, kv_lens, q_positions, scale=scale
        )
    # Fold grouped heads into query rows: [B, T, KH, G, hd] -> [B, KH, T*G, hd]
    # (row t*G + g). Chunk positions are consecutive from row 0's position —
    # the runner builds prefill batches that way — so the kernel derives the
    # causal mask from starts alone. Padding rows attend past their chunk;
    # their outputs are discarded downstream (last_idx / dropped writes).
    qf = (
        q.reshape(B, T, KH, G, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, KH, T * G, hd)
    )
    starts = q_positions[:, 0].astype(jnp.int32)
    out = _prefill_call(
        qf,
        k_pages,
        v_pages,
        block_tables.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        starts,
        scale=scale,
        q_tile=q_tile,
        group=G,
    )
    return (
        out.reshape(B, KH, T, G, hd)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, T, H, hd)
    )
