"""Pallas TPU flash-decode kernel over paged KV.

The hot op of the serving loop (the role vLLM's CUDA PagedAttention kernel
plays behind the reference stack). Decode attention is HBM-bandwidth-bound:
the win over the gather fallback is that pages stream HBM→VMEM per grid cell
and are reduced online (flash accumulation) — the gathered KV never
materializes in HBM.

Layout: KV pages are ``[KH, nb, bs, hd]`` (contiguous ``[bs, hd]`` tiles, the
TPU-tiling-legal arrangement). Grid ``(B, KH, W)``; each cell loads one page
for one kv-head and folds it into fp32 flash accumulators held in VMEM
scratch. Page indices come from the block table via scalar prefetch
(``PrefetchScalarGridSpec``) so the pipeline can address HBM pages ahead of
the body. The last grid step normalizes and writes ``[G, hd]``.

Used for decode (``T == 1``); prefill chunks take the gather path where the
big matmuls already keep the MXU busy.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret() -> bool:
    return bool(os.environ.get("PST_FORCE_PALLAS_INTERPRET"))


def _decode_kernel(
    # scalar prefetch
    tables_ref,  # [B, W] int32 (SMEM)
    lens_ref,  # [B] int32 (SMEM)
    # blocked operands
    q_ref,  # [1, 1, G, hd]
    k_ref,  # [1, 1, bs, hd]
    v_ref,  # [1, 1, bs, hd]
    o_ref,  # [1, 1, G, hd]
    # scratch
    m_ref,  # [G, 128] fp32 (col 0 live)
    l_ref,  # [G, 128] fp32 (col 0 live)
    acc_ref,  # [G, hd] fp32
    *,
    scale: float,
    block_size: int,
):
    b = pl.program_id(0)
    w = pl.program_id(2)
    n_w = pl.num_programs(2)

    @pl.when(w == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    kv_len = lens_ref[b]

    @pl.when(w * block_size < kv_len)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, hd]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [G, bs]
        kv_pos = w * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        s = jnp.where(kv_pos < kv_len, s, _NEG_INF)

        m_prev = m_ref[:, :1]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # [G, bs]
        alpha = jnp.exp(m_prev - m_new)  # [G, 1]
        l_ref[:, :1] = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:, :1] = m_new
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(w == n_w - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-20)
        ).astype(o_ref.dtype)


def _decode_call(q4, k_pages, v_pages, block_tables, kv_lens, *, scale):
    B, KH, G, hd = q4.shape
    _, nb, bs, _ = k_pages.shape
    W = block_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KH, W),
        in_specs=[
            pl.BlockSpec((1, 1, G, hd), lambda b, h, w, t, l: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bs, hd), lambda b, h, w, t, l: (h, t[b, w], 0, 0)),
            pl.BlockSpec((1, 1, bs, hd), lambda b, h, w, t, l: (h, t[b, w], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, G, hd), lambda b, h, w, t, l: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, scale=scale, block_size=bs)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KH, G, hd), q4.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, q4, k_pages, v_pages)


def pallas_paged_attention(
    q: jax.Array,  # [B, T, H, hd] — T must be 1 (decode)
    k_pages: jax.Array,  # [KH, nb, bs, hd]
    v_pages: jax.Array,
    block_tables: jax.Array,  # [B, W]
    kv_lens: jax.Array,  # [B]
    q_positions: jax.Array,  # unused for decode (kv_lens carries causality)
    *,
    scale: float,
) -> jax.Array:
    B, T, H, hd = q.shape
    if T != 1:
        from .attention import gather_paged_attention

        return gather_paged_attention(
            q, k_pages, v_pages, block_tables, kv_lens, q_positions, scale=scale
        )
    KH = k_pages.shape[0]
    G = H // KH
    q4 = q[:, 0].reshape(B, KH, G, hd)
    out = _decode_call(
        q4,
        k_pages,
        v_pages,
        block_tables.astype(jnp.int32),
        kv_lens.astype(jnp.int32),
        scale=scale,
    )
    return out.reshape(B, 1, H, hd)
