"""Pallas TPU flash kernels over paged KV: decode and chunked prefill.

The hot ops of the serving loop (the role vLLM's CUDA PagedAttention +
flash-attn kernels play behind the reference stack). Both are HBM-bandwidth
bound at the reference's long-context protocol (20k-token histories, 32k
max_model_len — ``BASELINE.md``), so the kernel is organized around DMA
efficiency, not grid geometry:

- KV lives in one combined page array ``[nb, 2, bs, KH*hd]`` (a page holds
  its K rows then V rows, each token row spanning **all** kv heads in the
  lane dimension), so one async copy moves an entire page — 100s of KB per
  DMA instead of the 8 KB per-head fragments a ``[KH, nb, bs, hd]`` layout
  forces. The head fold keeps the minor dims at ``(bs, KH*hd)``: both
  tiling-exact, no sublane padding (a ``[..., KH, hd]`` tail would pad
  KH=8 → 16 sublanes and physically double the cache).
- The grid is tiny — ``(B,)`` for decode, ``(B, T/Tq)`` for prefill — and
  each cell walks its sequence's **live** pages with a double-buffered
  ``fori_loop`` (chunks of ``C`` pages), overlapping the next chunk's DMAs
  with the current chunk's flash accumulation. Pages past ``kv_len`` — and,
  for prefill, pages entirely above the tile's causal horizon — are never
  fetched at all (the round-2 kernel's ``pl.when`` skipped the *compute* but
  the BlockSpec pipeline still paid the *DMA*; that was the round-2 TTFT
  regression).
- Flash state (m/l/acc) is head-major in VMEM scratch so per-head slices are
  contiguous; grouped-query heads share each page read.

Scalar-prefetched block tables address the pages (``PrefetchScalarGridSpec``)
so page ids are in SMEM before the body runs.

Shapes (one layer):
  q           [B, T, H, hd]        T=1 decode, T=chunk prefill
  kv_pages    [nb, 2, bs, KH*hd]   combined K(row 0)/V(row 1) pages
  tables      [B, W] int32         page ids (W*bs >= kv_len)
  kv_lens     [B] int32            valid KV length per sequence (0 = padding)
  q_positions [B, T] int32         absolute position per query token; the
                                   prefill kernel uses row 0 (chunks are
                                   consecutive positions — runner contract)
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret() -> bool:
    return bool(os.environ.get("PST_FORCE_PALLAS_INTERPRET"))


def _chunk_pages(bs: int) -> int:
    """Pages per DMA buffer slot: target ~512 tokens per chunk."""
    return max(512 // bs, 1)


def _chunked_flash(
    *,
    b,  # batch index (program id)
    n_chunks,  # traced: chunks of C pages to stream
    tables_ref,  # [B, W] SMEM
    kv_hbm,  # [nb, 2, bs, KH*hd] ANY
    buf,  # [2, C, 2, bs, KH*hd] VMEM scratch
    sems,  # [2, C] DMA semaphores
    q_heads,  # list of KH fp32 arrays [R, hd]
    bounds,  # [R, 1] exclusive per-row attention bound (causality + kv_len)
    m_ref,  # [KH, R, 128] fp32 scratch (col 0 live)
    l_ref,  # [KH, R, 128]
    acc_ref,  # [KH, R, hd]
    scale: float,
    block_size: int,
    chunk: int,
    table_width: int,
    head_dim: int,
):
    """Stream ``n_chunks`` KV chunks with double-buffered DMA and fold each
    into the per-head flash accumulators. Shared by decode and prefill —
    decode is the R=G, bounds=kv_len special case."""
    C, W, hd = chunk, table_width, head_dim
    KH = acc_ref.shape[0]

    def dma(c, j, slot):
        # Page ids past the live range clamp to the table's last entry;
        # their columns are masked below (only the ragged final chunk
        # fetches any).
        page = tables_ref[b, jnp.minimum(c * C + j, W - 1)]
        return pltpu.make_async_copy(
            kv_hbm.at[page], buf.at[slot, j], sems.at[slot, j]
        )

    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(n_chunks > 0)
    def _warmup():
        for j in range(C):
            dma(0, j, 0).start()

    def body(c, _):
        slot = jax.lax.rem(c, 2)
        nslot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _next():
            for j in range(C):
                dma(c + 1, j, nslot).start()

        for j in range(C):
            dma(c, j, slot).wait()

        page = buf[slot]  # [C, 2, bs, KH*hd]
        S = C * block_size
        col = c * S + jax.lax.broadcasted_iota(jnp.int32, (1, S), 1)
        for h in range(KH):
            kh = page[:, 0, :, h * hd : (h + 1) * hd].reshape(S, hd)
            vh = page[:, 1, :, h * hd : (h + 1) * hd].reshape(S, hd)
            s = jax.lax.dot_general(
                q_heads[h], kh.astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [R, S]
            s = jnp.where(col < bounds, s, _NEG_INF)
            m_prev = m_ref[h, :, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[h, :, :1] = alpha * l_ref[h, :, :1] + jnp.sum(
                p, axis=-1, keepdims=True
            )
            m_ref[h, :, :1] = m_new
            acc_ref[h] = acc_ref[h] * alpha + jax.lax.dot_general(
                p, vh.astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        return 0

    jax.lax.fori_loop(0, n_chunks, body, 0)


def _decode_kernel(
    tables_ref, lens_ref,  # scalar prefetch (SMEM)
    q_ref,  # [1, H, hd] VMEM
    kv_hbm,  # [nb, 2, bs, KH*hd] ANY
    o_ref,  # [1, H, hd] VMEM
    buf, sems, m_ref, l_ref, acc_ref,  # scratch
    *,
    scale: float,
    block_size: int,
    chunk: int,
    table_width: int,
    group: int,
    head_dim: int,
):
    b = pl.program_id(0)
    G, KH = group, acc_ref.shape[0]
    kv_len = lens_ref[b]
    n_chunks = (kv_len + chunk * block_size - 1) // (chunk * block_size)

    q = q_ref[0].astype(jnp.float32)  # [H, hd]
    _chunked_flash(
        b=b,
        n_chunks=n_chunks,
        tables_ref=tables_ref,
        kv_hbm=kv_hbm,
        buf=buf,
        sems=sems,
        q_heads=[q[h * G : (h + 1) * G] for h in range(KH)],
        bounds=jnp.full((G, 1), kv_len, jnp.int32),
        m_ref=m_ref,
        l_ref=l_ref,
        acc_ref=acc_ref,
        scale=scale,
        block_size=block_size,
        chunk=chunk,
        table_width=table_width,
        head_dim=head_dim,
    )
    out = acc_ref[...] / jnp.maximum(l_ref[:, :, :1], 1e-20)  # [KH, G, hd]
    o_ref[0] = out.reshape(KH * G, head_dim).astype(o_ref.dtype)


def _prefill_kernel(
    tables_ref, lens_ref, starts_ref,  # scalar prefetch (SMEM)
    q_ref,  # [1, Tq, H, hd] VMEM
    kv_hbm,  # [nb, 2, bs, KH*hd] ANY
    o_ref,  # [1, Tq, H, hd] VMEM
    buf, sems, m_ref, l_ref, acc_ref,  # scratch
    *,
    scale: float,
    block_size: int,
    chunk: int,
    table_width: int,
    group: int,
    head_dim: int,
    q_tile: int,
):
    b = pl.program_id(0)
    tq = pl.program_id(1)
    G, Tq, KH = group, q_tile, acc_ref.shape[0]
    kv_len = lens_ref[b]
    start = starts_ref[b]

    # Rows t*G+g of each head cover absolute positions start + tq*Tq + t.
    # The tile's causal horizon is its last row's position; pages past
    # min(horizon+1, kv_len) are never fetched (≈ halves page traffic over a
    # full prefill, while warm tiles near the sequence end still stream every
    # live page — exactly the data they need).
    limit = jnp.minimum(kv_len, start + (tq + 1) * Tq)
    n_chunks = (limit + chunk * block_size - 1) // (chunk * block_size)

    rows = jax.lax.broadcasted_iota(jnp.int32, (Tq * G, 1), 0)
    q_pos = start + tq * Tq + rows // G  # [Tq*G, 1]
    bounds = jnp.minimum(q_pos + 1, kv_len)

    qh = [
        q_ref[0, :, h * G : (h + 1) * G, :]
        .reshape(Tq * G, head_dim)
        .astype(jnp.float32)
        for h in range(KH)
    ]
    _chunked_flash(
        b=b,
        n_chunks=n_chunks,
        tables_ref=tables_ref,
        kv_hbm=kv_hbm,
        buf=buf,
        sems=sems,
        q_heads=qh,
        bounds=bounds,
        m_ref=m_ref,
        l_ref=l_ref,
        acc_ref=acc_ref,
        scale=scale,
        block_size=block_size,
        chunk=chunk,
        table_width=table_width,
        head_dim=head_dim,
    )
    # Padding rows (kv_len == 0) accumulated nothing: l stays 0 and the
    # output is 0, matching the drop-slot contract.
    for h in range(KH):
        out = acc_ref[h] / jnp.maximum(l_ref[h, :, :1], 1e-20)  # [Tq*G, hd]
        o_ref[0, :, h * G : (h + 1) * G, :] = out.reshape(
            Tq, G, head_dim
        ).astype(o_ref.dtype)


def _scratch(C, bs, lanes, R, KH, hd, kv_dtype):
    return [
        pltpu.VMEM((2, C, 2, bs, lanes), kv_dtype),
        pltpu.SemaphoreType.DMA((2, C)),
        pltpu.VMEM((KH, R, 128), jnp.float32),
        pltpu.VMEM((KH, R, 128), jnp.float32),
        pltpu.VMEM((KH, R, hd), jnp.float32),
    ]


def _decode_call(q3, kv_pages, block_tables, kv_lens, *, scale):
    B, H, hd = q3.shape
    nb, _, bs, lanes = kv_pages.shape
    KH = lanes // hd
    W = block_tables.shape[1]
    G = H // KH
    C = _chunk_pages(bs)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, hd), lambda b, t, l: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, H, hd), lambda b, t, l: (b, 0, 0)),
        scratch_shapes=_scratch(C, bs, lanes, G, KH, hd, kv_pages.dtype),
    )
    kernel = functools.partial(
        _decode_kernel,
        scale=scale,
        block_size=bs,
        chunk=C,
        table_width=W,
        group=G,
        head_dim=hd,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, hd), q3.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, q3, kv_pages)


def _prefill_call(q, kv_pages, block_tables, kv_lens, starts, *, scale, q_tile):
    B, T, H, hd = q.shape
    nb, _, bs, lanes = kv_pages.shape
    KH = lanes // hd
    W = block_tables.shape[1]
    G = H // KH
    C = _chunk_pages(bs)
    n_tiles = T // q_tile

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, n_tiles),
        in_specs=[
            pl.BlockSpec((1, q_tile, H, hd), lambda b, t, tt, l, s: (b, t, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, q_tile, H, hd), lambda b, t, tt, l, s: (b, t, 0, 0)
        ),
        scratch_shapes=_scratch(C, bs, lanes, q_tile * G, KH, hd, kv_pages.dtype),
    )
    kernel = functools.partial(
        _prefill_kernel,
        scale=scale,
        block_size=bs,
        chunk=C,
        table_width=W,
        group=G,
        head_dim=hd,
        q_tile=q_tile,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, H, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=_interpret(),
    )(block_tables, kv_lens, starts, q, kv_pages)


def pallas_paged_attention(
    q: jax.Array,  # [B, T, H, hd]
    kv_pages: jax.Array,  # [nb, 2, bs, KH*hd]
    block_tables: jax.Array,  # [B, W]
    kv_lens: jax.Array,  # [B]
    q_positions: jax.Array,  # [B, T] absolute positions (row 0 = chunk start)
    *,
    scale: float,
) -> jax.Array:
    B, T, H, hd = q.shape
    tables = block_tables.astype(jnp.int32)
    lens = kv_lens.astype(jnp.int32)
    if T == 1:
        out = _decode_call(q[:, 0], kv_pages, tables, lens, scale=scale)
        return out[:, None]

    # Chunk positions are consecutive from row 0's position (the runner
    # builds prefill batches that way), so the kernel derives causality from
    # starts alone. Padding rows attend past their chunk; their outputs are
    # discarded downstream (last_idx / dropped writes).
    q_tile = min(T, 128)
    if T % q_tile:  # odd shapes: runner buckets are powers of two
        from .attention import gather_paged_attention

        return gather_paged_attention(
            q, kv_pages, block_tables, kv_lens, q_positions, scale=scale
        )
    starts = q_positions[:, 0].astype(jnp.int32)
    return _prefill_call(
        q, kv_pages, tables, lens, starts, scale=scale, q_tile=q_tile
    )
