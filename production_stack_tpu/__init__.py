"""production-stack-tpu: a TPU-native LLM serving-fleet framework.

Re-implements the capabilities of the vLLM Production Stack
(reference: /root/reference, an orchestration layer around vLLM) as a
standalone TPU-first system:

- ``production_stack_tpu.engine``  — a JAX/Pallas serving engine (paged KV
  cache, continuous batching, tensor/sequence parallelism over a device
  mesh) exposing an OpenAI-compatible HTTP surface.
- ``production_stack_tpu.router``  — an L7 request router (service
  discovery, routing policies, stats, metrics), the analogue of the
  reference's ``src/vllm_router``.
- ``production_stack_tpu.kvserver`` — remote KV block store + cache
  controller (the analogue of the reference's LMCache server/controller).
- ``helm/``, ``csrc/operator``     — deployment + control plane.
"""

__version__ = "0.2.0"
