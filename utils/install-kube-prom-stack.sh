#!/usr/bin/env bash
# kube-prometheus-stack with the repo's scrape/dashboard values.
# Reference analogue: the observability install steps in
# observability/README + tutorials (kube-prom-stack.yaml values).
set -euo pipefail
cd "$(dirname "$0")/.."

helm repo add prometheus-community https://prometheus-community.github.io/helm-charts >/dev/null
helm repo update >/dev/null
helm upgrade -i kube-prom prometheus-community/kube-prometheus-stack \
  --namespace monitoring --create-namespace \
  -f observability/kube-prom-stack.yaml \
  --wait --timeout 10m

# Grafana dashboards as ConfigMaps (sidecar-discovered).
kubectl -n monitoring create configmap pst-dashboards \
  --from-file=observability/pst-dashboard.json \
  --from-file=observability/kv-tiering-dashboard.json \
  --dry-run=client -o yaml | kubectl apply -f -
kubectl -n monitoring label configmap pst-dashboards grafana_dashboard=1 --overwrite

# Custom-metrics adapter (HPA/KEDA on vllm:num_requests_waiting).
kubectl apply -f observability/prom-adapter.yaml || \
  echo "WARN: prom-adapter apply failed (HPA on engine metrics unavailable)"
echo "observability stack installed (namespace: monitoring)"
