#!/usr/bin/env bash
# Install kind (Kubernetes-in-Docker) if missing. Reference: utils/install-kind.sh.
set -euo pipefail
if command -v kind >/dev/null 2>&1; then
  echo "kind already installed: $(kind version)"
  exit 0
fi
ARCH=$(uname -m); case "$ARCH" in x86_64) ARCH=amd64 ;; aarch64) ARCH=arm64 ;; esac
KIND_VERSION=${KIND_VERSION:-v0.23.0}
curl -fsSLo /tmp/kind "https://kind.sigs.k8s.io/dl/${KIND_VERSION}/kind-linux-${ARCH}"
sudo install -m 0755 /tmp/kind /usr/local/bin/kind
kind version
