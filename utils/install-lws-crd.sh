#!/usr/bin/env bash
# Install the LeaderWorkerSet (LWS) operator + CRDs — required by
# helm/templates/multihost-engine.yaml (the Ray-cluster replacement for
# multi-host TPU slices; SURVEY.md §2.4 "Pipeline parallel, multi-host").
set -euo pipefail
LWS_VERSION=${LWS_VERSION:-v0.5.1}
kubectl apply --server-side \
  -f "https://github.com/kubernetes-sigs/lws/releases/download/${LWS_VERSION}/manifests.yaml"
kubectl -n lws-system rollout status deploy/lws-controller-manager --timeout=180s
echo "LeaderWorkerSet ${LWS_VERSION} installed"
