#!/usr/bin/env bash
# Bare VM -> single-node minikube cluster ready for `helm install`.
# Reference analogue: utils/install-minikube-cluster.sh (GPU operator swapped
# for the TPU device-plugin DaemonSet on real TPU-VM nodes; kind is the
# lighter CI option — see install-kind-cluster.sh).
set -euo pipefail
cd "$(dirname "$0")"

./install-kubectl.sh
./install-helm.sh

if ! command -v minikube >/dev/null 2>&1; then
  ARCH=$(uname -m); case "$ARCH" in x86_64) ARCH=amd64 ;; aarch64) ARCH=arm64 ;; esac
  curl -fsSLo /tmp/minikube \
    "https://storage.googleapis.com/minikube/releases/latest/minikube-linux-${ARCH}"
  sudo install -m 0755 /tmp/minikube /usr/local/bin/minikube
fi

minikube status >/dev/null 2>&1 || minikube start --driver=docker --memory=8g --cpus=4
kubectl cluster-info

./install-lws-crd.sh || echo "WARN: LWS install failed (multihost template unavailable)"

# On a real TPU-VM node pool, expose google.com/tpu resources to kubelet.
# (No-op on laptops/CI — the fake engine image needs no TPU resource.)
if [[ "${INSTALL_TPU_PLUGIN:-0}" == "1" ]]; then
  kubectl apply -f https://raw.githubusercontent.com/GoogleCloudPlatform/ai-on-gke/main/tpu-provisioner/deploy/device-plugin.yaml || \
    echo "WARN: TPU device plugin apply failed"
fi

cat <<EOF

Cluster ready. Install the stack:

  helm install pst ./helm -f helm/examples/values-minimal.yaml

EOF
