#!/usr/bin/env bash
# Install Helm if missing. Reference analogue: utils/install-helm.sh.
set -euo pipefail
if command -v helm >/dev/null 2>&1; then
  echo "helm already installed: $(helm version --short)"
  exit 0
fi
curl -fsSL https://raw.githubusercontent.com/helm/helm/main/scripts/get-helm-3 | bash
helm version --short
