#!/usr/bin/env bash
# Bare VM -> kind cluster ready for `helm install` of the stack.
# Reference analogue: utils/install-kind-cluster.sh (minikube variant below).
#
#   ./utils/install-kind-cluster.sh            # cluster + LWS CRD
#   INSTALL_PROM=1 ./utils/install-kind-cluster.sh   # + kube-prom-stack
set -euo pipefail
cd "$(dirname "$0")"

./install-kubectl.sh
./install-helm.sh
./install-kind.sh

CLUSTER=${CLUSTER_NAME:-pst}
if ! kind get clusters 2>/dev/null | grep -qx "$CLUSTER"; then
  kind create cluster --name "$CLUSTER" --wait 120s
fi
kubectl cluster-info --context "kind-${CLUSTER}"

# LWS CRDs (multihost engine template) — best-effort on clusters that
# will never run multi-host slices.
./install-lws-crd.sh || echo "WARN: LWS install failed (multihost template unavailable)"

if [[ "${INSTALL_PROM:-0}" == "1" ]]; then
  ./install-kube-prom-stack.sh
fi

cat <<EOF

Cluster ready. Install the stack:

  helm install pst ./helm -f helm/examples/values-minimal.yaml

EOF
