#!/usr/bin/env bash
# Install kubectl if missing. Reference analogue: utils/install-kubectl.sh.
set -euo pipefail
if command -v kubectl >/dev/null 2>&1; then
  echo "kubectl already installed: $(kubectl version --client --output=yaml | head -2)"
  exit 0
fi
ARCH=$(uname -m); case "$ARCH" in x86_64) ARCH=amd64 ;; aarch64) ARCH=arm64 ;; esac
VER=$(curl -fsSL https://dl.k8s.io/release/stable.txt)
curl -fsSLo /tmp/kubectl "https://dl.k8s.io/release/${VER}/bin/linux/${ARCH}/kubectl"
sudo install -m 0755 /tmp/kubectl /usr/local/bin/kubectl
kubectl version --client
