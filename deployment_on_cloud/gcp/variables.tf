variable "project_id" {
  type        = string
  description = "GCP project to deploy into"
}

variable "region" {
  type        = string
  default     = "us-west4" # v5e availability
}

variable "cluster_name" {
  type    = string
  default = "pst"
}

variable "cpu_machine_type" {
  type    = string
  default = "e2-standard-8"
}

variable "cpu_node_count" {
  type    = number
  default = 2
}

# ct5lp-hightpu-4t = one v5e host VM with 4 chips (tp=4 engine per pod).
variable "tpu_machine_type" {
  type    = string
  default = "ct5lp-hightpu-4t"
}

variable "tpu_node_count" {
  type    = number
  default = 1
}

variable "tpu_min_nodes" {
  type    = number
  default = 0
}

variable "tpu_max_nodes" {
  type    = number
  default = 4
}

# Multi-host slice topology ("" = single-host pools). "4x4" provisions a
# v5e-16 slice — the BASELINE.md north-star pool — whose hosts the
# LeaderWorkerSet multihost template spans.
variable "tpu_topology" {
  type    = string
  default = ""
}
