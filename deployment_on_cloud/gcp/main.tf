# GKE cluster with a v5e TPU node pool for production-stack-tpu.
# Reference analogue: deployment_on_cloud/gcp (GPU GKE terraform), re-aimed
# at TPU node pools (`google.com/tpu` resources, ct5lp machine types).
#
# Usage:
#   cd deployment_on_cloud/gcp
#   terraform init
#   terraform apply -var project_id=my-proj -var region=us-west4
#   gcloud container clusters get-credentials pst --region us-west4
#   ../../utils/install-lws-crd.sh && helm install pst ../../helm \
#       -f ../../helm/examples/values-minimal.yaml

terraform {
  required_version = ">= 1.5"
  required_providers {
    google = {
      source  = "hashicorp/google"
      version = ">= 5.30"
    }
  }
}

provider "google" {
  project = var.project_id
  region  = var.region
}

resource "google_container_cluster" "pst" {
  name     = var.cluster_name
  location = var.region

  # One small CPU node pool for the router/operator/observability pods;
  # TPU pools attach below.
  remove_default_node_pool = true
  initial_node_count       = 1
  deletion_protection      = false

  release_channel {
    channel = "REGULAR"
  }
}

resource "google_container_node_pool" "cpu" {
  name     = "cpu-pool"
  cluster  = google_container_cluster.pst.id
  location = var.region

  node_count = var.cpu_node_count
  node_config {
    machine_type = var.cpu_machine_type
    oauth_scopes = ["https://www.googleapis.com/auth/cloud-platform"]
  }
}

# TPU v5e node pool. Machine type encodes the per-VM topology:
#   ct5lp-hightpu-1t  -> 1 chip/VM  (single-chip engines)
#   ct5lp-hightpu-4t  -> 4 chips/VM (tp=4 engines)
#   ct5lp-hightpu-8t  -> 8 chips/VM (tp=8 engines)
# Multi-host slices (v5e-16 and up: the BASELINE.md north-star pool) use
# placement_policy tpu_topology + the LWS multihost template
# (helm/templates/multihost-engine.yaml).
resource "google_container_node_pool" "tpu" {
  name     = "tpu-v5e-pool"
  cluster  = google_container_cluster.pst.id
  location = var.region

  initial_node_count = var.tpu_node_count
  node_config {
    machine_type = var.tpu_machine_type
    oauth_scopes = ["https://www.googleapis.com/auth/cloud-platform"]

    # Engine pods select this pool (helm values nodeSelectorTerms).
    labels = {
      "pst/pool" = "tpu-v5e"
    }
  }

  dynamic "placement_policy" {
    for_each = var.tpu_topology == "" ? [] : [var.tpu_topology]
    content {
      type         = "COMPACT"
      tpu_topology = placement_policy.value # e.g. "4x4" for v5e-16
    }
  }

  autoscaling {
    min_node_count = var.tpu_min_nodes
    max_node_count = var.tpu_max_nodes
  }
}

output "cluster_name" {
  value = google_container_cluster.pst.name
}

output "get_credentials" {
  value = "gcloud container clusters get-credentials ${google_container_cluster.pst.name} --region ${var.region} --project ${var.project_id}"
}
