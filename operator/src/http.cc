#include "http.hpp"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <ctime>
#include <sstream>
#include <stdexcept>

namespace pst {

Url Url::parse(const std::string& url) {
  Url out;
  std::string rest = url;
  const std::string scheme = "http://";
  if (rest.rfind(scheme, 0) == 0) rest = rest.substr(scheme.size());
  if (rest.rfind("https://", 0) == 0)
    throw std::runtime_error("https unsupported: route via a TLS proxy sidecar");
  auto slash = rest.find('/');
  std::string hostport = slash == std::string::npos ? rest : rest.substr(0, slash);
  out.path = slash == std::string::npos ? "/" : rest.substr(slash);
  auto colon = hostport.rfind(':');
  if (colon != std::string::npos) {
    out.host = hostport.substr(0, colon);
    out.port = std::stoi(hostport.substr(colon + 1));
  } else {
    out.host = hostport;
    out.port = 80;
  }
  return out;
}

namespace {

int connect_to(const std::string& host, int port, int timeout_sec) {
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res)
    throw std::runtime_error("DNS resolution failed for " + host);
  int fd = -1;
  for (auto* p = res; p; p = p->ai_next) {
    fd = socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    struct timeval tv{timeout_sec, 0};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) throw std::runtime_error("connect failed to " + host);
  return fd;
}

bool send_all(int fd, const std::string& data) {
  size_t sent = 0;
  while (sent < data.size()) {
    ssize_t n = send(fd, data.data() + sent, data.size() - sent, MSG_NOSIGNAL);
    if (n <= 0) return false;
    sent += static_cast<size_t>(n);
  }
  return true;
}

// Shared response-header parsing ("HTTP/1.1 200 OK" + Transfer-Encoding
// detection) for the buffered and streaming clients.
int parse_status_line(const std::string& headers) {
  auto sp = headers.find(' ');
  if (sp == std::string::npos) return 0;
  try {
    return std::stoi(headers.substr(sp + 1));
  } catch (const std::exception&) {
    return 0;
  }
}

bool is_chunked(const std::string& headers) {
  std::string lower;
  lower.reserve(headers.size());
  for (char c : headers) lower += static_cast<char>(tolower(c));
  return lower.find("transfer-encoding: chunked") != std::string::npos;
}

}  // namespace

int http_stream(const std::string& url,
                const std::function<bool(const std::string&)>& on_line,
                const std::atomic<int>* stop, int timeout_sec) {
  // Never throws: watch threads have no exception handler of their own —
  // a parse failure must degrade to "stream unavailable", not terminate.
  Url u;
  int fd;
  try {
    u = Url::parse(url);
    fd = connect_to(u.host, u.port, /*timeout_sec=*/2);
  } catch (const std::exception&) {
    return 0;
  }
  // Short receive timeout so the stop flag is polled between reads; the
  // overall stream lives until close/stop (K8s watch streams are long).
  struct timeval tv{1, 0};
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));

  std::ostringstream req;
  req << "GET " << u.path << " HTTP/1.1\r\n"
      << "Host: " << u.host << ":" << u.port << "\r\n"
      << "Connection: close\r\n"
      << "Accept: application/json\r\n\r\n";
  if (!send_all(fd, req.str())) {
    close(fd);
    return 0;
  }

  std::string raw;         // bytes before the header/body split
  std::string body;        // de-chunked body bytes not yet emitted as lines
  std::string chunk_buf;   // raw chunked-encoding bytes pending de-framing
  bool headers_done = false, chunked = false;
  int status = 0;
  time_t deadline = time(nullptr) + timeout_sec;
  char buf[16384];
  while (!(stop && stop->load(std::memory_order_relaxed)) &&
         time(nullptr) < deadline) {
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n == 0) break;  // server closed
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) continue;  // poll stop
      break;
    }
    deadline = time(nullptr) + timeout_sec;  // progress resets the idle clock
    if (!headers_done) {
      raw.append(buf, static_cast<size_t>(n));
      auto he = raw.find("\r\n\r\n");
      if (he == std::string::npos) continue;
      std::string headers = raw.substr(0, he);
      status = parse_status_line(headers);
      chunked = is_chunked(headers);
      headers_done = true;
      chunk_buf = raw.substr(he + 4);
      raw.clear();
    } else {
      chunk_buf.append(buf, static_cast<size_t>(n));
    }
    if (!headers_done) continue;
    if (status < 200 || status >= 300) break;
    if (chunked) {  // incremental de-chunk: emit complete chunks into body
      size_t pos = 0;
      while (true) {
        auto le = chunk_buf.find("\r\n", pos);
        if (le == std::string::npos) break;
        size_t chunk_len;
        try {
          chunk_len = std::stoul(chunk_buf.substr(pos, le - pos), nullptr, 16);
        } catch (const std::exception&) {
          close(fd);
          return status;  // malformed framing: give up on this stream
        }
        if (chunk_len == 0) {
          close(fd);
          return status;
        }
        if (chunk_buf.size() < le + 2 + chunk_len + 2) break;  // incomplete
        body.append(chunk_buf, le + 2, chunk_len);
        pos = le + 2 + chunk_len + 2;
      }
      chunk_buf.erase(0, pos);
    } else {
      body += chunk_buf;
      chunk_buf.clear();
    }
    size_t nl;
    while ((nl = body.find('\n')) != std::string::npos) {
      std::string line = body.substr(0, nl);
      body.erase(0, nl + 1);
      if (!line.empty() && !on_line(line)) {
        close(fd);
        return status;
      }
    }
  }
  close(fd);
  return status;
}

HttpResponse http_request(const std::string& method, const std::string& url,
                          const std::string& body,
                          const std::string& content_type, int timeout_sec) {
  Url u = Url::parse(url);
  int fd = connect_to(u.host, u.port, timeout_sec);

  std::ostringstream req;
  req << method << " " << u.path << " HTTP/1.1\r\n"
      << "Host: " << u.host << ":" << u.port << "\r\n"
      << "Connection: close\r\n"
      << "Accept: application/json\r\n";
  if (!body.empty() || method == "POST" || method == "PUT" || method == "PATCH") {
    req << "Content-Type: " << content_type << "\r\n"
        << "Content-Length: " << body.size() << "\r\n";
  }
  req << "\r\n" << body;

  HttpResponse resp;
  if (!send_all(fd, req.str())) {
    close(fd);
    throw std::runtime_error("send failed to " + u.host);
  }

  std::string raw;
  char buf[16384];
  ssize_t n;
  while ((n = recv(fd, buf, sizeof(buf), 0)) > 0) raw.append(buf, static_cast<size_t>(n));
  close(fd);
  if (raw.empty()) throw std::runtime_error("empty response from " + u.host);

  auto header_end = raw.find("\r\n\r\n");
  if (header_end == std::string::npos)
    throw std::runtime_error("malformed HTTP response");
  std::string headers = raw.substr(0, header_end);
  std::string payload = raw.substr(header_end + 4);

  resp.status = parse_status_line(headers);

  // De-chunk if needed (Connection: close means we already have every byte).
  if (is_chunked(headers)) {
    std::string out;
    size_t pos = 0;
    while (pos < payload.size()) {
      auto line_end = payload.find("\r\n", pos);
      if (line_end == std::string::npos) break;
      size_t chunk_len;
      try {
        chunk_len = std::stoul(payload.substr(pos, line_end - pos), nullptr, 16);
      } catch (const std::exception&) {
        break;  // malformed framing: keep what we have
      }
      if (chunk_len == 0) break;
      out.append(payload, line_end + 2, chunk_len);
      pos = line_end + 2 + chunk_len + 2;  // skip chunk + trailing CRLF
    }
    resp.body = std::move(out);
  } else {
    resp.body = std::move(payload);
  }
  return resp;
}

}  // namespace pst
