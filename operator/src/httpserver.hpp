// Minimal threaded HTTP/1.1 server (POSIX sockets) for the picker service.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <functional>
#include <sstream>
#include <string>
#include <thread>

namespace pst {

struct HttpServerRequest {
  std::string method;
  std::string path;
  std::string body;
};

struct HttpServerResponse {
  int status = 200;
  std::string content_type = "application/json";
  std::string body;
};

using Handler = std::function<HttpServerResponse(const HttpServerRequest&)>;

class HttpServer {
 public:
  explicit HttpServer(Handler handler) : handler_(std::move(handler)) {}

  // Binds and returns the actual port (0 = ephemeral).
  int listen(int port) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      return -1;
    ::listen(fd_, 128);
    socklen_t len = sizeof(addr);
    getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    return ntohs(addr.sin_port);
  }

  void serve_forever() {
    while (!stop_.load()) {
      int client = accept(fd_, nullptr, nullptr);
      if (client < 0) continue;
      std::thread([this, client] { handle(client); }).detach();
    }
  }

  void stop() {
    stop_.store(true);
    if (fd_ >= 0) {
      shutdown(fd_, SHUT_RDWR);
      close(fd_);
    }
  }

 private:
  void handle(int client) {
    struct timeval tv{10, 0};
    setsockopt(client, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    std::string raw;
    char buf[8192];
    size_t content_length = 0;
    size_t header_end = std::string::npos;
    while (true) {
      ssize_t n = recv(client, buf, sizeof(buf), 0);
      if (n <= 0) break;
      raw.append(buf, static_cast<size_t>(n));
      if (header_end == std::string::npos) {
        header_end = raw.find("\r\n\r\n");
        if (header_end != std::string::npos) {
          auto cl = raw.find("Content-Length:");
          if (cl == std::string::npos) cl = raw.find("content-length:");
          if (cl != std::string::npos && cl < header_end)
            content_length = std::stoul(raw.substr(cl + 15));
        }
      }
      if (header_end != std::string::npos &&
          raw.size() >= header_end + 4 + content_length)
        break;
    }
    if (header_end == std::string::npos) {
      close(client);
      return;
    }
    HttpServerRequest req;
    {
      std::istringstream line(raw.substr(0, raw.find("\r\n")));
      line >> req.method >> req.path;
    }
    req.body = raw.substr(header_end + 4);

    HttpServerResponse resp = handler_(req);
    std::ostringstream out;
    out << "HTTP/1.1 " << resp.status << " OK\r\n"
        << "Content-Type: " << resp.content_type << "\r\n"
        << "Content-Length: " << resp.body.size() << "\r\n"
        << "Connection: close\r\n\r\n"
        << resp.body;
    const std::string data = out.str();
    size_t sent = 0;
    while (sent < data.size()) {
      ssize_t n = send(client, data.data() + sent, data.size() - sent, MSG_NOSIGNAL);
      if (n <= 0) break;
      sent += static_cast<size_t>(n);
    }
    close(client);
  }

  Handler handler_;
  int fd_ = -1;
  std::atomic<bool> stop_{false};
};

}  // namespace pst
