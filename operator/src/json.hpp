// Minimal JSON value type + parser/serializer for the operator.
//
// The reference operator leans on controller-runtime's typed Go structs
// (operator/api/v1alpha1/*_types.go); this C++ controller works with dynamic
// JSON the way the K8s API actually speaks it — no codegen, no deepcopy
// (zz_generated.deepcopy.go has no analogue here by design).
//
// Self-contained (no external deps: the image has no libcurl/openssl dev).
#pragma once

#include <cmath>
#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace pst {

class Json;
using JsonArray = std::vector<Json>;
using JsonObject = std::map<std::string, Json>;

class Json {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Json() : type_(Type::Null) {}
  Json(std::nullptr_t) : type_(Type::Null) {}
  Json(bool b) : type_(Type::Bool), bool_(b) {}
  Json(int v) : type_(Type::Number), num_(v) {}
  Json(long v) : type_(Type::Number), num_(static_cast<double>(v)) {}
  Json(double v) : type_(Type::Number), num_(v) {}
  Json(const char* s) : type_(Type::String), str_(s) {}
  Json(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Json(JsonArray a) : type_(Type::Array), arr_(std::move(a)) {}
  Json(JsonObject o) : type_(Type::Object), obj_(std::move(o)) {}

  static Json object() { return Json(JsonObject{}); }
  static Json array() { return Json(JsonArray{}); }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }
  bool is_string() const { return type_ == Type::String; }
  bool is_number() const { return type_ == Type::Number; }
  bool is_bool() const { return type_ == Type::Bool; }

  bool as_bool(bool dflt = false) const {
    return type_ == Type::Bool ? bool_ : dflt;
  }
  double as_number(double dflt = 0) const {
    return type_ == Type::Number ? num_ : dflt;
  }
  long as_int(long dflt = 0) const {
    return type_ == Type::Number ? static_cast<long>(num_) : dflt;
  }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  std::string as_string_or(const std::string& dflt) const {
    return type_ == Type::String ? str_ : dflt;
  }

  JsonArray& items() {
    ensure(Type::Array);
    return arr_;
  }
  const JsonArray& items() const {
    static const JsonArray empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  JsonObject& fields() {
    ensure(Type::Object);
    return obj_;
  }
  const JsonObject& fields() const {
    static const JsonObject empty;
    return type_ == Type::Object ? obj_ : empty;
  }

  // Object access. operator[] creates (for building); at() is const lookup
  // returning a Null sentinel for missing keys (for safe deep reads).
  Json& operator[](const std::string& key) {
    ensure(Type::Object);
    return obj_[key];
  }
  const Json& at(const std::string& key) const {
    static const Json null_json;
    if (type_ != Type::Object) return null_json;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_json : it->second;
  }
  // Deep path lookup: at({"spec", "replicas"}).
  const Json& at(std::initializer_list<std::string> path) const {
    const Json* cur = this;
    for (const auto& key : path) cur = &cur->at(key);
    return *cur;
  }
  bool has(const std::string& key) const {
    return type_ == Type::Object && obj_.count(key) > 0;
  }

  void push_back(Json v) {
    ensure(Type::Array);
    arr_.push_back(std::move(v));
  }

  bool operator==(const Json& o) const {
    if (type_ != o.type_) return false;
    switch (type_) {
      case Type::Null: return true;
      case Type::Bool: return bool_ == o.bool_;
      case Type::Number: return num_ == o.num_;
      case Type::String: return str_ == o.str_;
      case Type::Array: return arr_ == o.arr_;
      case Type::Object: return obj_ == o.obj_;
    }
    return false;
  }
  bool operator!=(const Json& o) const { return !(*this == o); }

  std::string dump() const {
    std::ostringstream out;
    write(out);
    return out.str();
  }

  static Json parse(const std::string& text) {
    size_t pos = 0;
    Json v = parse_value(text, pos);
    skip_ws(text, pos);
    if (pos != text.size()) throw std::runtime_error("trailing JSON content");
    return v;
  }

 private:
  void ensure(Type t) {
    if (type_ == Type::Null) {
      type_ = t;  // building convenience: null -> container on first use
      return;
    }
    if (type_ != t) throw std::runtime_error("JSON type mismatch");
  }

  void write(std::ostringstream& out) const {
    switch (type_) {
      case Type::Null: out << "null"; break;
      case Type::Bool: out << (bool_ ? "true" : "false"); break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == static_cast<long long>(num_)) {
          out << static_cast<long long>(num_);
        } else {
          out << num_;
        }
        break;
      }
      case Type::String: write_string(out, str_); break;
      case Type::Array: {
        out << '[';
        for (size_t i = 0; i < arr_.size(); ++i) {
          if (i) out << ',';
          arr_[i].write(out);
        }
        out << ']';
        break;
      }
      case Type::Object: {
        out << '{';
        bool first = true;
        for (const auto& [k, v] : obj_) {
          if (!first) out << ',';
          first = false;
          write_string(out, k);
          out << ':';
          v.write(out);
        }
        out << '}';
        break;
      }
    }
  }

  static void write_string(std::ostringstream& out, const std::string& s) {
    out << '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': out << "\\\""; break;
        case '\\': out << "\\\\"; break;
        case '\n': out << "\\n"; break;
        case '\r': out << "\\r"; break;
        case '\t': out << "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out << buf;
          } else {
            out << c;
          }
      }
    }
    out << '"';
  }

  static void skip_ws(const std::string& s, size_t& pos) {
    while (pos < s.size() &&
           (s[pos] == ' ' || s[pos] == '\t' || s[pos] == '\n' || s[pos] == '\r'))
      ++pos;
  }

  static Json parse_value(const std::string& s, size_t& pos) {
    skip_ws(s, pos);
    if (pos >= s.size()) throw std::runtime_error("unexpected end of JSON");
    char c = s[pos];
    if (c == '{') return parse_object(s, pos);
    if (c == '[') return parse_array(s, pos);
    if (c == '"') return Json(parse_string(s, pos));
    if (c == 't' || c == 'f') return parse_bool(s, pos);
    if (c == 'n') {
      expect(s, pos, "null");
      return Json();
    }
    return parse_number(s, pos);
  }

  static void expect(const std::string& s, size_t& pos, const char* lit) {
    for (const char* p = lit; *p; ++p, ++pos) {
      if (pos >= s.size() || s[pos] != *p)
        throw std::runtime_error(std::string("expected ") + lit);
    }
  }

  static Json parse_bool(const std::string& s, size_t& pos) {
    if (s[pos] == 't') {
      expect(s, pos, "true");
      return Json(true);
    }
    expect(s, pos, "false");
    return Json(false);
  }

  static Json parse_number(const std::string& s, size_t& pos) {
    size_t start = pos;
    if (pos < s.size() && (s[pos] == '-' || s[pos] == '+')) ++pos;
    while (pos < s.size() &&
           (isdigit(static_cast<unsigned char>(s[pos])) || s[pos] == '.' ||
            s[pos] == 'e' || s[pos] == 'E' || s[pos] == '-' || s[pos] == '+'))
      ++pos;
    if (pos == start) throw std::runtime_error("invalid JSON number");
    return Json(std::stod(s.substr(start, pos - start)));
  }

  static std::string parse_string(const std::string& s, size_t& pos) {
    if (s[pos] != '"') throw std::runtime_error("expected string");
    ++pos;
    std::string out;
    while (pos < s.size() && s[pos] != '"') {
      char c = s[pos++];
      if (c == '\\') {
        if (pos >= s.size()) throw std::runtime_error("bad escape");
        char e = s[pos++];
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (pos + 4 > s.size()) throw std::runtime_error("bad \\u escape");
            unsigned code = std::stoul(s.substr(pos, 4), nullptr, 16);
            pos += 4;
            // UTF-8 encode (surrogate pairs for completeness).
            if (code >= 0xD800 && code <= 0xDBFF && pos + 6 <= s.size() &&
                s[pos] == '\\' && s[pos + 1] == 'u') {
              unsigned low = std::stoul(s.substr(pos + 2, 4), nullptr, 16);
              pos += 6;
              code = 0x10000 + ((code - 0xD800) << 10) + (low - 0xDC00);
            }
            if (code < 0x80) {
              out += static_cast<char>(code);
            } else if (code < 0x800) {
              out += static_cast<char>(0xC0 | (code >> 6));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else if (code < 0x10000) {
              out += static_cast<char>(0xE0 | (code >> 12));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            } else {
              out += static_cast<char>(0xF0 | (code >> 18));
              out += static_cast<char>(0x80 | ((code >> 12) & 0x3F));
              out += static_cast<char>(0x80 | ((code >> 6) & 0x3F));
              out += static_cast<char>(0x80 | (code & 0x3F));
            }
            break;
          }
          default: throw std::runtime_error("bad escape char");
        }
      } else {
        out += c;
      }
    }
    if (pos >= s.size()) throw std::runtime_error("unterminated string");
    ++pos;  // closing quote
    return out;
  }

  static Json parse_array(const std::string& s, size_t& pos) {
    ++pos;  // [
    Json arr = Json::array();
    skip_ws(s, pos);
    if (pos < s.size() && s[pos] == ']') {
      ++pos;
      return arr;
    }
    while (true) {
      arr.push_back(parse_value(s, pos));
      skip_ws(s, pos);
      if (pos >= s.size()) throw std::runtime_error("unterminated array");
      if (s[pos] == ',') {
        ++pos;
        continue;
      }
      if (s[pos] == ']') {
        ++pos;
        return arr;
      }
      throw std::runtime_error("expected , or ] in array");
    }
  }

  static Json parse_object(const std::string& s, size_t& pos) {
    ++pos;  // {
    Json obj = Json::object();
    skip_ws(s, pos);
    if (pos < s.size() && s[pos] == '}') {
      ++pos;
      return obj;
    }
    while (true) {
      skip_ws(s, pos);
      std::string key = parse_string(s, pos);
      skip_ws(s, pos);
      if (pos >= s.size() || s[pos] != ':')
        throw std::runtime_error("expected : in object");
      ++pos;
      obj[key] = parse_value(s, pos);
      skip_ws(s, pos);
      if (pos >= s.size()) throw std::runtime_error("unterminated object");
      if (s[pos] == ',') {
        ++pos;
        continue;
      }
      if (s[pos] == '}') {
        ++pos;
        return obj;
      }
      throw std::runtime_error("expected , or } in object");
    }
  }

  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  JsonArray arr_;
  JsonObject obj_;
};

}  // namespace pst
