// Thin dynamic Kubernetes REST client (list/get/create/replace/delete +
// status patch) — the role controller-runtime's Client plays for the
// reference operator (operator/cmd/main.go:58-231), minus caches/codegen.
#pragma once

#include <optional>
#include <string>

#include "http.hpp"
#include "json.hpp"

namespace pst {

class K8sClient {
 public:
  K8sClient(std::string base_url, std::string ns)
      : base_(std::move(base_url)), ns_(std::move(ns)) {}

  const std::string& ns() const { return ns_; }

  // api_prefix: "/api/v1" (core) or "/apis/<group>/<version>".
  Json list(const std::string& api_prefix, const std::string& plural,
            const std::string& label_selector = "") const;
  std::optional<Json> get(const std::string& api_prefix,
                          const std::string& plural,
                          const std::string& name) const;
  Json create(const std::string& api_prefix, const std::string& plural,
              const Json& obj) const;
  Json replace(const std::string& api_prefix, const std::string& plural,
               const std::string& name, const Json& obj) const;
  bool destroy(const std::string& api_prefix, const std::string& plural,
               const std::string& name) const;
  // Merge-patch against the /status subresource.
  bool patch_status(const std::string& api_prefix, const std::string& plural,
                    const std::string& name, const Json& status) const;

  // Long-poll watch stream (?watch=true): on_event receives each event line
  // (a JSON object {"type": "ADDED|MODIFIED|DELETED", "object": {...}});
  // return false from it to stop. Blocks until server close/stop/idle
  // timeout; returns the HTTP status (0 = transport error).
  int watch(const std::string& api_prefix, const std::string& plural,
            const std::function<bool(const std::string&)>& on_event,
            const std::atomic<int>* stop, int idle_timeout_sec = 60) const;

 private:
  std::string url(const std::string& api_prefix, const std::string& plural,
                  const std::string& name = "",
                  const std::string& query = "") const;
  std::string base_;
  std::string ns_;
};

// API path constants.
inline const char* kCoreV1 = "/api/v1";
inline const char* kAppsV1 = "/apis/apps/v1";
inline const char* kPstV1 = "/apis/pst.production-stack.io/v1alpha1";

}  // namespace pst
