// Reconcilers for the four CRDs (reference: operator/internal/controller/*).
//
// TPURuntime   — engine fleet:   Service + PVC + Deployment from CR spec
//                (vllmruntime_controller.go:57-186 analogue, TPU resources)
// TPURouter    — router:         Deployment + Service
//                (vllmrouter_controller.go:61-195 analogue)
// CacheServer  — remote KV store Deployment + Service
//                (cacheserver_controller.go:54-289 analogue)
// LoraAdapter  — dynamic LoRA:   placement over ready engine pods + engine
//                HTTP load/unload (loraadapter_controller.go:73-232 analogue)
#pragma once

#include <string>
#include <vector>

#include "k8s.hpp"
#include "json.hpp"

namespace pst {

struct ReconcileResult {
  bool changed = false;
  std::string phase;
  std::string message;
};

// Stable content hash of a CR spec; stored as an annotation on owned objects
// so drift detection is a string compare (deploymentNeedsUpdate analogue).
std::string spec_hash(const Json& spec);

Json build_engine_deployment(const Json& cr, const std::string& ns);
Json build_engine_service(const Json& cr, const std::string& ns);
Json build_engine_pvc(const Json& cr, const std::string& ns);
Json build_router_deployment(const Json& cr, const std::string& ns);
Json build_router_service(const Json& cr, const std::string& ns);
Json build_cache_server_deployment(const Json& cr, const std::string& ns);
Json build_cache_server_service(const Json& cr, const std::string& ns);

ReconcileResult reconcile_tpu_runtime(const K8sClient& k8s, const Json& cr);
ReconcileResult reconcile_tpu_router(const K8sClient& k8s, const Json& cr);
ReconcileResult reconcile_cache_server(const K8sClient& k8s, const Json& cr);
ReconcileResult reconcile_lora_adapter(const K8sClient& k8s, const Json& cr);

}  // namespace pst
