#include "k8s.hpp"

#include <stdexcept>

namespace pst {

std::string K8sClient::url(const std::string& api_prefix,
                           const std::string& plural, const std::string& name,
                           const std::string& query) const {
  std::string out = base_ + api_prefix + "/namespaces/" + ns_ + "/" + plural;
  if (!name.empty()) out += "/" + name;
  if (!query.empty()) out += "?" + query;
  return out;
}

Json K8sClient::list(const std::string& api_prefix, const std::string& plural,
                     const std::string& label_selector) const {
  std::string query;
  if (!label_selector.empty()) query = "labelSelector=" + label_selector;
  auto resp = http_request("GET", url(api_prefix, plural, "", query));
  if (!resp.ok())
    throw std::runtime_error("list " + plural + " failed: " +
                             std::to_string(resp.status));
  return Json::parse(resp.body);
}

std::optional<Json> K8sClient::get(const std::string& api_prefix,
                                   const std::string& plural,
                                   const std::string& name) const {
  auto resp = http_request("GET", url(api_prefix, plural, name));
  if (resp.status == 404) return std::nullopt;
  if (!resp.ok())
    throw std::runtime_error("get " + plural + "/" + name + " failed: " +
                             std::to_string(resp.status));
  return Json::parse(resp.body);
}

Json K8sClient::create(const std::string& api_prefix, const std::string& plural,
                       const Json& obj) const {
  auto resp = http_request("POST", url(api_prefix, plural), obj.dump());
  if (!resp.ok())
    throw std::runtime_error("create " + plural + " failed: " +
                             std::to_string(resp.status) + " " + resp.body);
  return Json::parse(resp.body);
}

Json K8sClient::replace(const std::string& api_prefix,
                        const std::string& plural, const std::string& name,
                        const Json& obj) const {
  auto resp = http_request("PUT", url(api_prefix, plural, name), obj.dump());
  if (!resp.ok())
    throw std::runtime_error("replace " + plural + "/" + name + " failed: " +
                             std::to_string(resp.status) + " " + resp.body);
  return Json::parse(resp.body);
}

bool K8sClient::destroy(const std::string& api_prefix,
                        const std::string& plural,
                        const std::string& name) const {
  auto resp = http_request("DELETE", url(api_prefix, plural, name));
  return resp.ok() || resp.status == 404;
}

int K8sClient::watch(const std::string& api_prefix, const std::string& plural,
                     const std::function<bool(const std::string&)>& on_event,
                     const std::atomic<int>* stop,
                     int idle_timeout_sec) const {
  return http_stream(url(api_prefix, plural, "", "watch=true"), on_event,
                     stop, idle_timeout_sec);
}

bool K8sClient::patch_status(const std::string& api_prefix,
                             const std::string& plural, const std::string& name,
                             const Json& status) const {
  Json patch = Json::object();
  patch["status"] = status;
  auto resp = http_request("PATCH", url(api_prefix, plural, name + "/status"),
                           patch.dump(), "application/merge-patch+json");
  if (resp.status == 404) {  // API server without the status subresource
    resp = http_request("PATCH", url(api_prefix, plural, name), patch.dump(),
                        "application/merge-patch+json");
  }
  return resp.ok();
}

}  // namespace pst
