// Blocking HTTP/1.1 client over POSIX sockets (no libcurl/TLS in the image).
//
// In-cluster, the controller reaches the API server through a TLS-terminating
// localhost proxy (`kubectl proxy` sidecar — see operator/README.md), so the
// client itself speaks plain HTTP. The same client drives engine-pod HTTP
// (LoRA load/unload, /v1/models), mirroring the reference reconciler's calls
// (loraadapter_controller.go:582-611).
#pragma once

#include <csignal>
#include <atomic>
#include <functional>
#include <string>

namespace pst {

struct HttpResponse {
  int status = 0;
  std::string body;
  bool ok() const { return status >= 200 && status < 300; }
};

struct Url {
  std::string host;
  int port = 80;
  std::string path;  // includes query
  static Url parse(const std::string& url);
};

// method: GET/POST/PUT/PATCH/DELETE. content_type applies when body nonempty.
HttpResponse http_request(const std::string& method, const std::string& url,
                          const std::string& body = "",
                          const std::string& content_type = "application/json",
                          int timeout_sec = 10);

// Streaming GET: de-chunks the response incrementally and invokes on_line for
// every newline-terminated line of the body (the K8s watch wire format:
// one JSON event object per line). Returns when the server closes the
// stream, a socket timeout elapses with *stop set, or on_line returns false.
// Returns the HTTP status (0 on transport error before headers).
int http_stream(const std::string& url,
                const std::function<bool(const std::string&)>& on_line,
                const std::atomic<int>* stop, int timeout_sec = 30);

}  // namespace pst
