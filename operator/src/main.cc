// pst-operator: controller manager for the production-stack-tpu CRDs.
//
// Reference equivalent: operator/cmd/main.go:58-231 (controller-runtime
// manager with leader election + 4 reconcilers). This manager is a C++
// poll-reconcile loop: every --interval it lists each CRD and drives the
// cluster to the declared state; leader election uses a coordination.k8s.io
// Lease so only one replica reconciles.
//
// The API server is reached over plain HTTP (--api-server); in-cluster this
// is a kubectl-proxy/TLS-terminating sidecar on localhost (no TLS libs in
// the runtime image — see operator/README.md).

#include <atomic>
#include <condition_variable>
#include <csignal>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>
#include <unistd.h>

#include "httpserver.hpp"
#include "k8s.hpp"
#include "reconcilers.hpp"

namespace {

// std::atomic<int>: written by the signal handler AND read by the
// watch/metrics threads — sig_atomic_t is only signal-safe, not
// thread-safe (TSAN flags the pair). Lock-free atomic int is both.
std::atomic<int> g_stop{0};
void handle_signal(int) { g_stop.store(1, std::memory_order_relaxed); }

struct Options {
  std::string api_server = "http://127.0.0.1:8001";
  std::string ns = "default";
  int interval_sec = 10;
  int metrics_port = 0;  // 0 = disabled (reference --metrics-bind-address)
  bool once = false;  // single pass (tests / CI)
  bool watch = true;  // event-driven reconcile (interval is the fallback)
  bool leader_election = false;
  std::string identity;
};

// Reconcile counters exported at /metrics (the controller-runtime metrics
// server analogue, reference main.go:59-88 --metrics-bind-address).
struct Metrics {
  std::atomic<long> passes{0};
  std::atomic<long> reconciles{0};
  std::atomic<long> errors{0};
};
Metrics g_metrics;

Options parse_options(int argc, char** argv) {
  Options o;
  char host[256] = {0};
  gethostname(host, sizeof(host) - 1);
  o.identity = host;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (a == "--api-server") o.api_server = next();
    else if (a == "--namespace") o.ns = next();
    else if (a == "--interval") o.interval_sec = std::stoi(next());
    else if (a == "--metrics-port") o.metrics_port = std::stoi(next());
    else if (a == "--once") o.once = true;
    else if (a == "--no-watch") o.watch = false;
    else if (a == "--leader-elect") o.leader_election = true;
    else if (a == "--identity") o.identity = next();
    else if (a == "--help") {
      printf("pst-operator --api-server URL --namespace NS [--interval S]"
             " [--metrics-port P] [--once] [--no-watch] [--leader-elect]"
             " [--identity ID]\n");
      exit(0);
    }
  }
  return o;
}

// Lease-based leader election (coordination.k8s.io/v1), reference
// main.go LeaderElection analogue. Returns true if we hold the lease.
bool try_acquire_lease(const pst::K8sClient& k8s, const Options& o) {
  const char* api = "/apis/coordination.k8s.io/v1";
  const std::string name = "pst-operator-leader";
  const int lease_seconds = o.interval_sec * 3;
  time_t now = time(nullptr);
  char now_buf[40];
  struct tm tm_utc;
  gmtime_r(&now, &tm_utc);
  strftime(now_buf, sizeof(now_buf), "%Y-%m-%dT%H:%M:%S.000000Z", &tm_utc);

  auto existing = k8s.get(api, "leases", name);
  pst::Json lease = pst::Json::object();
  lease["apiVersion"] = "coordination.k8s.io/v1";
  lease["kind"] = "Lease";
  lease["metadata"]["name"] = name;
  lease["metadata"]["namespace"] = k8s.ns();
  lease["spec"]["holderIdentity"] = o.identity;
  lease["spec"]["leaseDurationSeconds"] = lease_seconds;
  lease["spec"]["renewTime"] = std::string(now_buf);

  try {
    if (!existing) {
      k8s.create(api, "leases", lease);
      return true;
    }
    const std::string holder =
        existing->at({"spec", "holderIdentity"}).as_string();
    const std::string renew = existing->at({"spec", "renewTime"}).as_string();
    bool expired = true;
    if (!renew.empty()) {
      struct tm tm_renew {};
      if (strptime(renew.c_str(), "%Y-%m-%dT%H:%M:%S", &tm_renew)) {
        expired = difftime(now, timegm(&tm_renew)) > lease_seconds;
      }
    }
    if (holder == o.identity || holder.empty() || expired) {
      lease["metadata"]["resourceVersion"] =
          existing->at({"metadata", "resourceVersion"}).as_string();
      k8s.replace(api, "leases", name, lease);
      return true;
    }
    return false;
  } catch (const std::exception& e) {
    fprintf(stderr, "[operator] lease error (reconciling anyway): %s\n",
            e.what());
    return true;  // fail open: a stuck lease must not halt the fleet
  }
}

void reconcile_all(const pst::K8sClient& k8s) {
  struct Kind {
    const char* plural;
    pst::ReconcileResult (*fn)(const pst::K8sClient&, const pst::Json&);
  };
  static const Kind kinds[] = {
      {"tpuruntimes", pst::reconcile_tpu_runtime},
      {"tpurouters", pst::reconcile_tpu_router},
      {"cacheservers", pst::reconcile_cache_server},
      {"loraadapters", pst::reconcile_lora_adapter},
  };
  for (const auto& kind : kinds) {
    pst::Json list;
    try {
      list = k8s.list(pst::kPstV1, kind.plural);
    } catch (const std::exception& e) {
      // CRD may not be installed; that's fine (reference skips likewise).
      continue;
    }
    for (const auto& cr : list.at("items").items()) {
      const std::string name = cr.at({"metadata", "name"}).as_string();
      try {
        auto result = kind.fn(k8s, cr);
        g_metrics.reconciles++;
        if (result.changed)
          printf("[operator] %s/%s reconciled -> %s\n", kind.plural,
                 name.c_str(), result.phase.c_str());
      } catch (const std::exception& e) {
        g_metrics.errors++;
        fprintf(stderr, "[operator] %s/%s reconcile failed: %s\n", kind.plural,
                name.c_str(), e.what());
      }
    }
  }
  g_metrics.passes++;
}

// Event-driven convergence (the reference's controller-runtime informers,
// operator/cmd/main.go:58-231): one watch stream per CRD kind plus the
// engine-pod watch (pods trigger LoraAdapter re-placement the way
// findLoraAdaptersForPod does, loraadapter_controller.go:278). Any event
// marks the loop dirty; the interval pass remains as a safety net and as
// graceful degradation when the API server rejects ?watch=true.
class WatchHub {
 public:
  WatchHub(const pst::K8sClient& k8s) : k8s_(k8s) {}

  void start() {
    static const std::pair<const char*, const char*> streams[] = {
        {pst::kPstV1, "tpuruntimes"},
        {pst::kPstV1, "tpurouters"},
        {pst::kPstV1, "cacheservers"},
        {pst::kPstV1, "loraadapters"},
        {pst::kCoreV1, "pods"},
    };
    for (const auto& s : streams) {
      threads_.emplace_back([this, api = s.first, plural = s.second] {
        bool warned = false;
        const bool own_kind = std::string(api) == pst::kPstV1;
        while (!g_stop) {
          int status = k8s_.watch(
              api, plural,
              [this, own_kind, plural](const std::string& line) {
                if (relevant(own_kind, plural, line)) notify();
                return !g_stop;
              },
              &g_stop);
          if (g_stop) break;
          if (status == 404 || status == 400) {
            // API server without watch support: interval fallback only.
            if (!warned) {
              fprintf(stderr, "[operator] watch %s unsupported (%d); "
                      "falling back to interval polling\n", plural, status);
              warned = true;
            }
            for (int i = 0; i < 300 && !g_stop; ++i)
              std::this_thread::sleep_for(std::chrono::milliseconds(100));
          } else {
            // Stream closed / transport error: brief backoff, re-watch.
            for (int i = 0; i < 10 && !g_stop; ++i)
              std::this_thread::sleep_for(std::chrono::milliseconds(100));
          }
        }
      });
    }
  }

  // Wait until an event arrives or timeout; clears the dirty flag. Waits in
  // short slices: the signal handler only flips g_stop (it cannot safely
  // notify a condition variable), so shutdown must be polled.
  void wait_dirty(int timeout_sec) {
    std::unique_lock<std::mutex> lock(mu_);
    for (int waited_ms = 0; waited_ms < timeout_sec * 1000 && !g_stop;
         waited_ms += 200) {
      if (cv_.wait_for(lock, std::chrono::milliseconds(200),
                       [this] { return dirty_; }))
        break;
    }
    dirty_ = false;
  }

  void join() {
    for (auto& t : threads_) t.join();
  }

 private:
  // Event filter: the reconcilers end every pass with a status patch, which
  // on a real API server emits a MODIFIED event on the object just
  // reconciled. Waking on those would make the operator reconcile in a
  // permanent ~150ms hot loop. `metadata.generation` only increments on
  // spec changes, so for our own CRDs: ADDED/DELETED always wake,
  // MODIFIED wakes only on a generation change or a pending
  // deletionTimestamp (finalizer flow). Pod events always wake — the
  // operator never writes pods, so they are externally caused.
  bool relevant(bool own_kind, const std::string& plural,
                const std::string& line) {
    if (!own_kind) return true;
    try {
      pst::Json ev = pst::Json::parse(line);
      const std::string type = ev.at("type").as_string();
      const pst::Json& meta = ev.at({"object", "metadata"});
      const std::string key = plural + "/" + meta.at("name").as_string();
      const long gen = meta.at("generation").as_int(-1);
      std::lock_guard<std::mutex> lock(gen_mu_);
      if (type == "DELETED") {
        generations_.erase(key);
        return true;
      }
      if (!meta.at("deletionTimestamp").as_string_or("").empty()) return true;
      auto it = generations_.find(key);
      const bool changed = it == generations_.end() || it->second != gen;
      generations_[key] = gen;
      return changed;
    } catch (const std::exception&) {
      return true;  // unparseable event: fail open, reconcile
    }
  }

  void notify() {
    std::lock_guard<std::mutex> lock(mu_);
    dirty_ = true;
    cv_.notify_one();
  }

  const pst::K8sClient& k8s_;
  std::vector<std::thread> threads_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool dirty_ = false;
  std::mutex gen_mu_;
  std::map<std::string, long> generations_;
};

}  // namespace

int main(int argc, char** argv) {
  Options o = parse_options(argc, argv);
  signal(SIGINT, handle_signal);
  signal(SIGTERM, handle_signal);
  pst::K8sClient k8s(o.api_server, o.ns);
  printf("[operator] managing namespace %s via %s (interval %ds, watch=%s)\n",
         o.ns.c_str(), o.api_server.c_str(), o.interval_sec,
         o.watch ? "on" : "off");
  fflush(stdout);

  WatchHub hub(k8s);
  const bool watching = o.watch && !o.once;
  if (watching) hub.start();

  // Prometheus metrics + health endpoint (controller-runtime metrics-server
  // analogue). Served on its own thread; 0 disables.
  std::unique_ptr<pst::HttpServer> metrics_srv;
  if (o.metrics_port > 0 && !o.once) {
    metrics_srv = std::make_unique<pst::HttpServer>(
        [](const pst::HttpServerRequest& req) {
          pst::HttpServerResponse resp;
          if (req.path == "/healthz") {
            resp.body = "{\"status\":\"ok\"}";
            return resp;
          }
          char buf[512];
          snprintf(buf, sizeof(buf),
                   "# TYPE pst_operator_reconcile_passes_total counter\n"
                   "pst_operator_reconcile_passes_total %ld\n"
                   "# TYPE pst_operator_reconciles_total counter\n"
                   "pst_operator_reconciles_total %ld\n"
                   "# TYPE pst_operator_reconcile_errors_total counter\n"
                   "pst_operator_reconcile_errors_total %ld\n",
                   g_metrics.passes.load(), g_metrics.reconciles.load(),
                   g_metrics.errors.load());
          resp.content_type = "text/plain";
          resp.body = buf;
          return resp;
        });
    int port = metrics_srv->listen(o.metrics_port);
    if (port > 0) {
      std::thread([srv = metrics_srv.get()] { srv->serve_forever(); })
          .detach();
      printf("[operator] metrics on :%d\n", port);
    } else {
      // The chart points the liveness probe here: running WITHOUT the
      // listener would be a permanent CrashLoopBackOff of an otherwise
      // fine operator. Fail fast instead — probe semantics then match
      // process health.
      fprintf(stderr, "[operator] fatal: cannot bind metrics port %d\n",
              o.metrics_port);
      return 1;
    }
  }

  do {
    if (!o.leader_election || try_acquire_lease(k8s, o)) {
      reconcile_all(k8s);
    }
    fflush(stdout);
    if (o.once) break;
    if (watching) {
      hub.wait_dirty(o.interval_sec);
      // Coalesce event bursts (a Deployment create fans out several watch
      // events) into one reconcile pass.
      std::this_thread::sleep_for(std::chrono::milliseconds(150));
    } else {
      for (int i = 0; i < o.interval_sec * 10 && !g_stop; ++i)
        std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
  } while (!g_stop);
  printf("[operator] shutting down\n");
  if (metrics_srv) {
    metrics_srv->stop();
    // Handler threads are detached: destroying the server under one is a
    // use-after-free. Intentionally leak it — the process is exiting.
    metrics_srv.release();
  }
  if (watching) hub.join();
  return 0;
}
