// pst-operator: controller manager for the production-stack-tpu CRDs.
//
// Reference equivalent: operator/cmd/main.go:58-231 (controller-runtime
// manager with leader election + 4 reconcilers). This manager is a C++
// poll-reconcile loop: every --interval it lists each CRD and drives the
// cluster to the declared state; leader election uses a coordination.k8s.io
// Lease so only one replica reconciles.
//
// The API server is reached over plain HTTP (--api-server); in-cluster this
// is a kubectl-proxy/TLS-terminating sidecar on localhost (no TLS libs in
// the runtime image — see operator/README.md).

#include <csignal>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <thread>
#include <unistd.h>

#include "k8s.hpp"
#include "reconcilers.hpp"

namespace {

volatile sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }

struct Options {
  std::string api_server = "http://127.0.0.1:8001";
  std::string ns = "default";
  int interval_sec = 10;
  bool once = false;  // single pass (tests / CI)
  bool leader_election = false;
  std::string identity;
};

Options parse_options(int argc, char** argv) {
  Options o;
  char host[256] = {0};
  gethostname(host, sizeof(host) - 1);
  o.identity = host;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string {
      return i + 1 < argc ? argv[++i] : "";
    };
    if (a == "--api-server") o.api_server = next();
    else if (a == "--namespace") o.ns = next();
    else if (a == "--interval") o.interval_sec = std::stoi(next());
    else if (a == "--once") o.once = true;
    else if (a == "--leader-elect") o.leader_election = true;
    else if (a == "--identity") o.identity = next();
    else if (a == "--help") {
      printf("pst-operator --api-server URL --namespace NS [--interval S]"
             " [--once] [--leader-elect] [--identity ID]\n");
      exit(0);
    }
  }
  return o;
}

// Lease-based leader election (coordination.k8s.io/v1), reference
// main.go LeaderElection analogue. Returns true if we hold the lease.
bool try_acquire_lease(const pst::K8sClient& k8s, const Options& o) {
  const char* api = "/apis/coordination.k8s.io/v1";
  const std::string name = "pst-operator-leader";
  const int lease_seconds = o.interval_sec * 3;
  time_t now = time(nullptr);
  char now_buf[40];
  struct tm tm_utc;
  gmtime_r(&now, &tm_utc);
  strftime(now_buf, sizeof(now_buf), "%Y-%m-%dT%H:%M:%S.000000Z", &tm_utc);

  auto existing = k8s.get(api, "leases", name);
  pst::Json lease = pst::Json::object();
  lease["apiVersion"] = "coordination.k8s.io/v1";
  lease["kind"] = "Lease";
  lease["metadata"]["name"] = name;
  lease["metadata"]["namespace"] = k8s.ns();
  lease["spec"]["holderIdentity"] = o.identity;
  lease["spec"]["leaseDurationSeconds"] = lease_seconds;
  lease["spec"]["renewTime"] = std::string(now_buf);

  try {
    if (!existing) {
      k8s.create(api, "leases", lease);
      return true;
    }
    const std::string holder =
        existing->at({"spec", "holderIdentity"}).as_string();
    const std::string renew = existing->at({"spec", "renewTime"}).as_string();
    bool expired = true;
    if (!renew.empty()) {
      struct tm tm_renew {};
      if (strptime(renew.c_str(), "%Y-%m-%dT%H:%M:%S", &tm_renew)) {
        expired = difftime(now, timegm(&tm_renew)) > lease_seconds;
      }
    }
    if (holder == o.identity || holder.empty() || expired) {
      lease["metadata"]["resourceVersion"] =
          existing->at({"metadata", "resourceVersion"}).as_string();
      k8s.replace(api, "leases", name, lease);
      return true;
    }
    return false;
  } catch (const std::exception& e) {
    fprintf(stderr, "[operator] lease error (reconciling anyway): %s\n",
            e.what());
    return true;  // fail open: a stuck lease must not halt the fleet
  }
}

void reconcile_all(const pst::K8sClient& k8s) {
  struct Kind {
    const char* plural;
    pst::ReconcileResult (*fn)(const pst::K8sClient&, const pst::Json&);
  };
  static const Kind kinds[] = {
      {"tpuruntimes", pst::reconcile_tpu_runtime},
      {"tpurouters", pst::reconcile_tpu_router},
      {"cacheservers", pst::reconcile_cache_server},
      {"loraadapters", pst::reconcile_lora_adapter},
  };
  for (const auto& kind : kinds) {
    pst::Json list;
    try {
      list = k8s.list(pst::kPstV1, kind.plural);
    } catch (const std::exception& e) {
      // CRD may not be installed; that's fine (reference skips likewise).
      continue;
    }
    for (const auto& cr : list.at("items").items()) {
      const std::string name = cr.at({"metadata", "name"}).as_string();
      try {
        auto result = kind.fn(k8s, cr);
        if (result.changed)
          printf("[operator] %s/%s reconciled -> %s\n", kind.plural,
                 name.c_str(), result.phase.c_str());
      } catch (const std::exception& e) {
        fprintf(stderr, "[operator] %s/%s reconcile failed: %s\n", kind.plural,
                name.c_str(), e.what());
      }
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  Options o = parse_options(argc, argv);
  signal(SIGINT, handle_signal);
  signal(SIGTERM, handle_signal);
  pst::K8sClient k8s(o.api_server, o.ns);
  printf("[operator] managing namespace %s via %s (interval %ds)\n",
         o.ns.c_str(), o.api_server.c_str(), o.interval_sec);
  fflush(stdout);

  do {
    if (!o.leader_election || try_acquire_lease(k8s, o)) {
      reconcile_all(k8s);
    }
    fflush(stdout);
    if (o.once) break;
    for (int i = 0; i < o.interval_sec * 10 && !g_stop; ++i)
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
  } while (!g_stop);
  printf("[operator] shutting down\n");
  return 0;
}
