// pst-picker: C++ endpoint-picker service for gateway integration.
//
// Reference parity: the Go Gateway-API inference-extension pickers
// (src/gateway_inference_extension/{roundrobin,prefix_aware,kv_aware}_picker.go).
// Instead of linking into a Go plugin framework, the same picking policies
// run behind a tiny HTTP API any gateway/ext-proc hook can call:
//
//   POST /pick {"policy"?: "...", "model": "...", "prompt": "...",
//               "pods": [{"name": "...", "address": "..."}]}
//     -> {"pod": "<name>", "matched_tokens": N}
//   GET /healthz
//
// Policies:
//   roundrobin  — atomic counter over name-sorted pods
//                 (roundrobin_picker.go:40-57)
//   prefixaware — 128-char-chunk xxh64 trie, longest prefix match with
//                 random tie-break, insert-on-pick
//                 (prefix_aware_picker.go:52-129; same chunking as the
//                 router's hashtrie so both layers agree)
//   kvaware     — cache-controller /lookup with threshold + roundrobin
//                 fallback (kv_aware_picker.go:48-88)

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <set>
#include <string>
#include <vector>

#include "http.hpp"
#include "httpserver.hpp"
#include "json.hpp"
#include "xxhash64.hpp"

namespace {

using pst::Json;

constexpr size_t kChunkChars = 128;

struct TrieNode {
  std::map<uint64_t, std::unique_ptr<TrieNode>> children;
  std::set<std::string> endpoints;
};

class PrefixTrie {
 public:
  // Node budget mirrors the router's HashTrie (max_nodes with pruning) so a
  // long-running picker can't grow without bound; on overflow the oldest
  // root subtree is dropped (approximate LRU via insertion order).
  static constexpr size_t kMaxNodes = 262144;

  void insert(const std::string& text, const std::string& endpoint) {
    std::lock_guard<std::mutex> guard(mu_);
    TrieNode* node = &root_;
    for (size_t i = 0; i < text.size(); i += kChunkChars) {
      uint64_t h = pst::xxh64(text.substr(i, kChunkChars));
      node->endpoints.insert(endpoint);
      auto& child = node->children[h];
      if (!child) {
        if (node_count_ >= kMaxNodes) prune_locked();
        child = std::make_unique<TrieNode>();
        ++node_count_;
        if (node == &root_) root_order_.push_back(h);
      }
      node = child.get();
    }
    node->endpoints.insert(endpoint);
  }

  // Returns (matched chars, endpoints at deepest matched node ∩ available).
  std::pair<size_t, std::set<std::string>> match(
      const std::string& text, const std::set<std::string>& available) {
    std::lock_guard<std::mutex> guard(mu_);
    TrieNode* node = &root_;
    size_t matched = 0;
    std::set<std::string> best;
    for (size_t i = 0; i < text.size(); i += kChunkChars) {
      uint64_t h = pst::xxh64(text.substr(i, kChunkChars));
      auto it = node->children.find(h);
      if (it == node->children.end()) break;
      std::set<std::string> eps;
      for (const auto& e : it->second->endpoints)
        if (available.count(e)) eps.insert(e);
      if (eps.empty()) break;
      node = it->second.get();
      matched = std::min(i + kChunkChars, text.size());
      best = std::move(eps);
    }
    return {matched, best};
  }

 private:
  static size_t count_nodes(const TrieNode& node) {
    size_t n = 1;
    for (const auto& [_, child] : node.children) n += count_nodes(*child);
    return n;
  }

  void prune_locked() {
    while (!root_order_.empty()) {
      uint64_t h = root_order_.front();
      root_order_.erase(root_order_.begin());
      auto it = root_.children.find(h);
      if (it == root_.children.end()) continue;
      node_count_ -= count_nodes(*it->second);
      root_.children.erase(it);
      return;
    }
    root_.children.clear();  // degenerate single-subtree case
    node_count_ = 0;
  }

  std::mutex mu_;
  TrieNode root_;
  size_t node_count_ = 0;
  std::vector<uint64_t> root_order_;
};

struct Pod {
  std::string name;
  std::string address;
};

std::vector<Pod> parse_pods(const Json& req) {
  std::vector<Pod> pods;
  for (const auto& p : req.at("pods").items())
    pods.push_back({p.at("name").as_string(), p.at("address").as_string()});
  std::sort(pods.begin(), pods.end(),
            [](const Pod& a, const Pod& b) { return a.name < b.name; });
  return pods;
}

class PickerService {
 public:
  PickerService(std::string default_policy, std::string controller_url,
                long threshold)
      : default_policy_(std::move(default_policy)),
        controller_url_(std::move(controller_url)),
        threshold_(threshold) {}

  pst::HttpServerResponse handle(const pst::HttpServerRequest& req) {
    if (req.path == "/healthz")
      return {200, "application/json", "{\"status\":\"ok\"}"};
    if (req.method != "POST" || req.path != "/pick")
      return {404, "application/json", "{\"error\":\"not found\"}"};
    try {
      Json body = Json::parse(req.body);
      auto pods = parse_pods(body);
      if (pods.empty())
        return {400, "application/json", "{\"error\":\"no pods\"}"};
      const std::string policy =
          body.at("policy").as_string_or(default_policy_);
      const std::string prompt = body.at("prompt").as_string();
      long matched = 0;
      std::string chosen;
      if (policy == "prefixaware") {
        chosen = pick_prefix(prompt, pods, &matched);
      } else if (policy == "kvaware") {
        chosen = pick_kvaware(body.at("model").as_string(), prompt, pods,
                              &matched);
      } else {
        chosen = pick_roundrobin(pods);
      }
      Json resp = Json::object();
      resp["pod"] = chosen;
      resp["matched_tokens"] = matched;
      return {200, "application/json", resp.dump()};
    } catch (const std::exception& e) {
      Json err = Json::object();
      err["error"] = e.what();
      return {500, "application/json", err.dump()};
    }
  }

 private:
  std::string pick_roundrobin(const std::vector<Pod>& pods) {
    return pods[counter_.fetch_add(1) % pods.size()].name;
  }

  std::string pick_prefix(const std::string& prompt,
                          const std::vector<Pod>& pods, long* matched) {
    std::set<std::string> available;
    for (const auto& p : pods) available.insert(p.name);
    auto [chars, eps] = trie_.match(prompt, available);
    *matched = static_cast<long>(chars);
    std::string chosen;
    if (!eps.empty()) {
      // Random tie-break among deepest-match holders (Go picker behavior).
      std::vector<std::string> v(eps.begin(), eps.end());
      std::uniform_int_distribution<size_t> dist(0, v.size() - 1);
      std::lock_guard<std::mutex> guard(rng_mu_);
      chosen = v[dist(rng_)];
    } else {
      chosen = pick_roundrobin(pods);
    }
    trie_.insert(prompt, chosen);
    return chosen;
  }

  std::string pick_kvaware(const std::string& model, const std::string& prompt,
                           const std::vector<Pod>& pods, long* matched) {
    // Chunk-hash the prompt the way the engines register chunks (byte-level
    // token ids == utf-8 bytes+1 for the byte tokenizer; for HF-tokenized
    // fleets the router path is authoritative — this picker queries with
    // the same /lookup contract: kv_aware_picker.go:92-115).
    try {
      Json lookup = Json::object();
      lookup["model"] = model;
      Json hashes = Json::array();
      // Controller speaks token-chunk hashes; gateway has text only, so ask
      // the controller's text-lookup convenience if present.
      lookup["text"] = prompt;
      auto resp = pst::http_request("POST", controller_url_ + "/lookup",
                                    lookup.dump(), "application/json", 2);
      if (resp.ok()) {
        Json result = Json::parse(resp.body);
        std::string best;
        long best_tokens = 0;
        for (const auto& [url, tokens] : result.at("matches").fields()) {
          if (tokens.as_int() > best_tokens) {
            best_tokens = tokens.as_int();
            best = url;
          }
        }
        for (const auto& p : pods) {
          if (p.address == best || p.name == best) {
            if (best_tokens >= threshold_) {
              *matched = best_tokens;
              return p.name;
            }
          }
        }
      }
    } catch (...) {
    }
    return pick_roundrobin(pods);
  }

  std::string default_policy_;
  std::string controller_url_;
  long threshold_;
  std::atomic<uint64_t> counter_{0};
  PrefixTrie trie_;
  std::mutex rng_mu_;
  std::mt19937 rng_{std::random_device{}()};
};

}  // namespace

int main(int argc, char** argv) {
  int port = 9002;
  std::string policy = "prefixaware";
  std::string controller_url = "http://127.0.0.1:9000";
  long threshold = 2000;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--port") port = std::stoi(next());
    else if (a == "--policy") policy = next();
    else if (a == "--controller-url") controller_url = next();
    else if (a == "--threshold") threshold = std::stol(next());
  }
  PickerService service(policy, controller_url, threshold);
  pst::HttpServer server(
      [&](const pst::HttpServerRequest& r) { return service.handle(r); });
  int bound = server.listen(port);
  if (bound < 0) {
    fprintf(stderr, "[picker] bind failed on port %d\n", port);
    return 1;
  }
  printf("[picker] policy=%s listening on :%d\n", policy.c_str(), bound);
  fflush(stdout);
  server.serve_forever();
  return 0;
}
