#include "reconcilers.hpp"

#include <algorithm>
#include <climits>
#include <cstdio>
#include <ctime>

namespace pst {

namespace {

constexpr const char* kHashAnnotation = "pst.production-stack.io/spec-hash";

Json owner_ref(const Json& cr) {
  Json ref = Json::object();
  ref["apiVersion"] = cr.at("apiVersion").as_string_or(
      "pst.production-stack.io/v1alpha1");
  ref["kind"] = cr.at("kind").as_string();
  ref["name"] = cr.at({"metadata", "name"}).as_string();
  ref["uid"] = cr.at({"metadata", "uid"}).as_string_or("");
  ref["controller"] = true;
  ref["blockOwnerDeletion"] = true;
  Json arr = Json::array();
  arr.push_back(ref);
  return arr;
}

Json meta_for(const Json& cr, const std::string& name, const std::string& ns,
              const std::string& component) {
  Json m = Json::object();
  m["name"] = name;
  m["namespace"] = ns;
  Json labels = Json::object();
  labels["app.kubernetes.io/part-of"] = "production-stack-tpu";
  labels["app.kubernetes.io/component"] = component;
  labels["app"] = name;
  labels["environment"] = "production-stack-tpu";
  if (component == "engine")
    labels["model"] = cr.at({"metadata", "name"}).as_string();
  m["labels"] = labels;
  Json ann = Json::object();
  ann[kHashAnnotation] = spec_hash(cr.at("spec"));
  m["annotations"] = ann;
  m["ownerReferences"] = owner_ref(cr);
  return m;
}

void push_arg(Json& args, const std::string& flag, const std::string& value) {
  args.push_back(flag);
  args.push_back(value);
}

void push_arg_num(Json& args, const std::string& flag, long value) {
  push_arg(args, flag, std::to_string(value));
}

std::string now_rfc3339() {
  char buf[32];
  time_t t = time(nullptr);
  struct tm tm_utc;
  gmtime_r(&t, &tm_utc);
  strftime(buf, sizeof(buf), "%Y-%m-%dT%H:%M:%SZ", &tm_utc);
  return buf;
}

// Generic "ensure object matches CR spec" upsert keyed on the spec-hash
// annotation (drift detection without semantic diffing).
bool upsert(const K8sClient& k8s, const std::string& api,
            const std::string& plural, const Json& desired) {
  const std::string name = desired.at({"metadata", "name"}).as_string();
  auto existing = k8s.get(api, plural, name);
  if (!existing) {
    k8s.create(api, plural, desired);
    return true;
  }
  const std::string want =
      desired.at({"metadata", "annotations"}).at(kHashAnnotation).as_string();
  const std::string have = existing->at({"metadata", "annotations"})
                               .at(kHashAnnotation)
                               .as_string();
  if (want != have) {
    Json replacement = desired;
    // Carry resourceVersion for optimistic concurrency on PUT.
    const std::string rv =
        existing->at({"metadata", "resourceVersion"}).as_string();
    if (!rv.empty()) replacement["metadata"]["resourceVersion"] = rv;
    k8s.replace(api, plural, name, replacement);
    return true;
  }
  return false;
}

}  // namespace

std::string spec_hash(const Json& spec) {
  // FNV-1a over the canonical dump (std::map keys are sorted → stable).
  const std::string s = spec.dump();
  uint64_t h = 1469598103934665603ull;
  for (unsigned char c : s) {
    h ^= c;
    h *= 1099511628211ull;
  }
  char buf[20];
  snprintf(buf, sizeof(buf), "%016llx", static_cast<unsigned long long>(h));
  return buf;
}

// ---------------------------------------------------------------------------
// TPURuntime
// ---------------------------------------------------------------------------

Json build_engine_deployment(const Json& cr, const std::string& ns) {
  const Json& spec = cr.at("spec");
  const std::string cr_name = cr.at({"metadata", "name"}).as_string();
  const std::string name = cr_name + "-engine";

  Json args = Json::array();
  push_arg(args, "--model", spec.at("model").as_string_or("tiny-llama-debug"));
  if (spec.has("servedModelName"))
    push_arg(args, "--served-model-name", spec.at("servedModelName").as_string());
  push_arg(args, "--host", "0.0.0.0");
  push_arg_num(args, "--port", 8000);
  const Json& ec = spec.at("engineConfig");
  push_arg_num(args, "--max-model-len", ec.at("maxModelLen").as_int(4096));
  push_arg_num(args, "--max-num-seqs", ec.at("maxNumSeqs").as_int(64));
  push_arg_num(args, "--max-num-batched-tokens",
               ec.at("maxNumBatchedTokens").as_int(2048));
  push_arg_num(args, "--tensor-parallel-size",
               ec.at("tensorParallelSize").as_int(1));
  push_arg_num(args, "--block-size", ec.at("blockSize").as_int(32));
  push_arg(args, "--attn-impl", ec.at("attnImpl").as_string_or("auto"));
  // Weight-only quantization (vllm serve --quantization analogue).
  if (ec.has("quantization") &&
      !ec.at("quantization").as_string_or("").empty())
    push_arg(args, "--quantization", ec.at("quantization").as_string_or(""));
  if (ec.has("numDecodeSteps") && ec.at("numDecodeSteps").as_int(0) > 0)
    push_arg_num(args, "--num-decode-steps", ec.at("numDecodeSteps").as_int());
  if (ec.has("adaptiveDecodeSteps") &&
      ec.at("adaptiveDecodeSteps").as_int(0) > 0)
    push_arg_num(args, "--adaptive-decode-steps",
                 ec.at("adaptiveDecodeSteps").as_int());
  if (ec.has("hbmUtilization")) {
    char buf[16];
    snprintf(buf, sizeof(buf), "%.3f", ec.at("hbmUtilization").as_number(0.9));
    push_arg(args, "--gpu-memory-utilization", buf);
  }
  if (ec.has("enablePrefixCaching") && !ec.at("enablePrefixCaching").as_bool(true))
    args.push_back("--no-enable-prefix-caching");
  const Json& kv = spec.at("kvCache");
  if (kv.at("cpuOffloadBlocks").as_int(0) > 0)
    push_arg_num(args, "--cpu-offload-blocks", kv.at("cpuOffloadBlocks").as_int());
  if (kv.has("remoteKvUrl") && !kv.at("remoteKvUrl").as_string().empty())
    push_arg(args, "--remote-kv-url", kv.at("remoteKvUrl").as_string());
  if (kv.has("kvRole") && kv.at("kvRole").as_string_or("none") != "none")
    push_arg(args, "--kv-role", kv.at("kvRole").as_string());
  if (spec.has("cacheControllerUrl"))
    push_arg(args, "--cache-controller-url",
             spec.at("cacheControllerUrl").as_string());
  for (const auto& extra : ec.at("extraArgs").items()) args.push_back(extra);

  Json container = Json::object();
  container["name"] = "engine";
  container["image"] = spec.at("image").as_string_or(
      "ghcr.io/production-stack-tpu/engine:0.1.0");
  Json cmd = Json::array();
  cmd.push_back("pst-engine");
  container["command"] = cmd;
  container["args"] = args;
  Json port = Json::object();
  port["containerPort"] = 8000;
  port["name"] = "http";
  Json ports = Json::array();
  ports.push_back(port);
  container["ports"] = ports;

  Json resources = Json::object();
  Json requests = Json::object();
  requests["cpu"] = spec.at({"resources", "cpu"}).as_string_or("4");
  requests["memory"] = spec.at({"resources", "memory"}).as_string_or("16Gi");
  Json limits = Json::object();
  const long chips = spec.at({"tpu", "chips"}).as_int(0);
  if (chips > 0) {
    requests["google.com/tpu"] = std::to_string(chips);
    limits["google.com/tpu"] = std::to_string(chips);
  }
  resources["requests"] = requests;
  if (chips > 0) resources["limits"] = limits;
  container["resources"] = resources;

  Json probe = Json::object();
  Json http_get = Json::object();
  http_get["path"] = "/health";
  http_get["port"] = 8000;
  probe["httpGet"] = http_get;
  probe["periodSeconds"] = 10;
  probe["failureThreshold"] = 120;
  container["startupProbe"] = probe;
  Json live = probe;
  live["failureThreshold"] = 6;
  container["livenessProbe"] = live;

  Json pod_spec = Json::object();
  if (chips > 0) {
    Json node_selector = Json::object();
    node_selector["cloud.google.com/gke-tpu-accelerator"] =
        spec.at({"tpu", "accelerator"}).as_string_or("tpu-v5-lite-podslice");
    node_selector["cloud.google.com/gke-tpu-topology"] =
        spec.at({"tpu", "topology"}).as_string_or("2x4");
    pod_spec["nodeSelector"] = node_selector;
    Json tol = Json::object();
    tol["key"] = "google.com/tpu";
    tol["operator"] = "Exists";
    tol["effect"] = "NoSchedule";
    Json tols = Json::array();
    tols.push_back(tol);
    pod_spec["tolerations"] = tols;
  }
  if (spec.at({"storage", "enabled"}).as_bool(false)) {
    Json vm = Json::object();
    vm["name"] = "model-storage";
    vm["mountPath"] = "/data";
    Json vms = Json::array();
    vms.push_back(vm);
    container["volumeMounts"] = vms;
    Json vol = Json::object();
    vol["name"] = "model-storage";
    Json pvc_src = Json::object();
    pvc_src["claimName"] = cr_name + "-pvc";
    vol["persistentVolumeClaim"] = pvc_src;
    Json vols = Json::array();
    vols.push_back(vol);
    pod_spec["volumes"] = vols;
    Json env = Json::object();
    env["name"] = "HF_HOME";
    env["value"] = "/data";
    Json envs = Json::array();
    envs.push_back(env);
    container["env"] = envs;
  }
  Json containers = Json::array();
  containers.push_back(container);
  pod_spec["containers"] = containers;

  Json pod_meta = Json::object();
  Json pod_labels = Json::object();
  pod_labels["app"] = name;
  pod_labels["model"] = cr_name;
  pod_labels["environment"] = "production-stack-tpu";
  pod_meta["labels"] = pod_labels;

  Json tmpl = Json::object();
  tmpl["metadata"] = pod_meta;
  tmpl["spec"] = pod_spec;

  Json selector = Json::object();
  Json match = Json::object();
  match["app"] = name;
  selector["matchLabels"] = match;

  Json dspec = Json::object();
  long replicas = spec.at("replicas").as_int(1);
  if (spec.has("autoscale")) {
    // The actuator owns the replica count: a spec change (hash mismatch →
    // full replace) must carry the last ACTUATED scale forward, not reset
    // the fleet to spec.replicas mid-surge.
    replicas = cr.at({"status", "desiredReplicas"}).as_int(replicas);
  }
  dspec["replicas"] = replicas;
  dspec["selector"] = selector;
  dspec["template"] = tmpl;

  Json dep = Json::object();
  dep["apiVersion"] = "apps/v1";
  dep["kind"] = "Deployment";
  dep["metadata"] = meta_for(cr, name, ns, "engine");
  dep["spec"] = dspec;
  return dep;
}

Json build_engine_service(const Json& cr, const std::string& ns) {
  const std::string cr_name = cr.at({"metadata", "name"}).as_string();
  const std::string name = cr_name + "-engine";
  Json svc = Json::object();
  svc["apiVersion"] = "v1";
  svc["kind"] = "Service";
  svc["metadata"] = meta_for(cr, name, ns, "engine");
  Json sel = Json::object();
  sel["app"] = name;
  Json port = Json::object();
  port["port"] = 8000;
  port["targetPort"] = 8000;
  port["name"] = "http";
  Json ports = Json::array();
  ports.push_back(port);
  Json sspec = Json::object();
  sspec["selector"] = sel;
  sspec["ports"] = ports;
  svc["spec"] = sspec;
  return svc;
}

Json build_engine_pvc(const Json& cr, const std::string& ns) {
  const Json& st = cr.at({"spec", "storage"});
  Json pvc = Json::object();
  pvc["apiVersion"] = "v1";
  pvc["kind"] = "PersistentVolumeClaim";
  pvc["metadata"] =
      meta_for(cr, cr.at({"metadata", "name"}).as_string() + "-pvc", ns, "engine");
  Json pspec = Json::object();
  Json modes = Json::array();
  modes.push_back(st.at("accessMode").as_string_or("ReadWriteOnce"));
  pspec["accessModes"] = modes;
  if (st.has("storageClass") && !st.at("storageClass").as_string().empty())
    pspec["storageClassName"] = st.at("storageClass").as_string();
  Json req = Json::object();
  Json storage = Json::object();
  storage["storage"] = st.at("size").as_string_or("100Gi");
  req["requests"] = storage;
  pspec["resources"] = req;
  pvc["spec"] = pspec;
  return pvc;
}

// ---------------------------------------------------------------------------
// Autoscale actuator (docs/autoscaling.md "Reconcile semantics")
// ---------------------------------------------------------------------------

namespace {

struct EnginePod {
  std::string name;
  std::string base;  // http://ip:port
};

std::vector<EnginePod> ready_engine_pods(const K8sClient& k8s,
                                         const std::string& base_model) {
  std::vector<EnginePod> pods;
  Json list = k8s.list(kCoreV1, "pods", "model%3D" + base_model);
  for (const auto& pod : list.at("items").items()) {
    const std::string ip = pod.at({"status", "podIP"}).as_string();
    const std::string phase = pod.at({"status", "phase"}).as_string();
    if (ip.empty() || phase != "Running") continue;
    // Engine port from the pod's declared containerPort (default 8000).
    long port = 8000;
    const auto& containers = pod.at({"spec", "containers"}).items();
    if (!containers.empty()) {
      const auto& ports = containers[0].at("ports").items();
      if (!ports.empty()) port = ports[0].at("containerPort").as_int(8000);
    }
    pods.push_back({pod.at({"metadata", "name"}).as_string(),
                    "http://" + ip + ":" + std::to_string(port)});
  }
  std::sort(pods.begin(), pods.end(),
            [](const EnginePod& a, const EnginePod& b) { return a.name < b.name; });
  return pods;
}

// Consumer contract with the router's GET /autoscale/signal
// (production_stack_tpu/router/services/capacity.py compute_signal).
// tests/test_flight_cost.py regex-extracts this list and asserts every
// field exists in the Python producer's output, so a producer rename
// breaks the build's tests, not a running fleet. A signal response
// missing any of these is version skew and is discarded — the operator
// never actuates on partial evidence.
constexpr const char* kSignalFields[] = {
    "ts",
    "replica_hint",
    "queue_depth",
    "in_flight_total",
    "engines_ready",
    "page_burning",
    "saturation",
    "evidence_replicas",
};

bool signal_valid(const Json& sig) {
  for (const char* field : kSignalFields)
    if (!sig.has(field)) return false;
  return true;
}

// One router replica's worth of evidence, max-merged across replicas.
// Each replica's signal is already gossip-merged over the fleet (burn =
// max, queue = sum across router peers), so replicas converge on the SAME
// values within one sync interval — max here is anti-skew defense for the
// convergence window, not an aggregation step; summing would double-count.
struct SignalView {
  long hint = -1;  // -1 = no reachable router produced a valid signal
  long queue_depth = 0;
  long in_flight = 0;
  long routers = 0;  // replicas that answered with a valid signal
};

struct RouterReplica {
  std::string pod;
  std::string base;  // http://ip:port
};

std::vector<RouterReplica> router_replicas(const K8sClient& k8s) {
  // Router pods carry only {app: <name>-router}; the component label lives
  // on the Deployment/Service metadata. Walk component=router Services to
  // their selector, then to Running pods.
  std::vector<RouterReplica> out;
  Json svcs = k8s.list(kCoreV1, "services",
                       "app.kubernetes.io%2Fcomponent%3Drouter");
  for (const auto& svc : svcs.at("items").items()) {
    const std::string app = svc.at({"spec", "selector", "app"}).as_string();
    if (app.empty()) continue;
    long port = 8000;
    const auto& ports = svc.at({"spec", "ports"}).items();
    if (!ports.empty()) port = ports[0].at("targetPort").as_int(8000);
    Json pods = k8s.list(kCoreV1, "pods", "app%3D" + app);
    for (const auto& pod : pods.at("items").items()) {
      const std::string ip = pod.at({"status", "podIP"}).as_string();
      if (ip.empty()) continue;
      if (pod.at({"status", "phase"}).as_string() != "Running") continue;
      out.push_back({pod.at({"metadata", "name"}).as_string(),
                     "http://" + ip + ":" + std::to_string(port)});
    }
  }
  std::sort(out.begin(), out.end(),
            [](const RouterReplica& a, const RouterReplica& b) {
              return a.pod < b.pod;
            });
  return out;
}

SignalView poll_signal(const std::vector<RouterReplica>& routers) {
  SignalView v;
  for (const auto& r : routers) {
    try {
      auto resp = http_request("GET", r.base + "/autoscale/signal", "", "", 5);
      if (!resp.ok()) continue;
      Json sig = Json::parse(resp.body);
      if (!signal_valid(sig)) continue;
      v.routers++;
      v.hint = std::max(v.hint, sig.at("replica_hint").as_int(0));
      v.queue_depth = std::max(v.queue_depth, sig.at("queue_depth").as_int(0));
      v.in_flight = std::max(v.in_flight, sig.at("in_flight_total").as_int(0));
    } catch (...) {
      // Unreachable/unparseable replica: its evidence simply doesn't count.
    }
  }
  return v;
}

// Crash-looping / never-ready engine pods are FENCED: they count against
// the Deployment's desired replicas (they hold a slot) but are excluded
// from victim selection and freeze scale-up — otherwise one bad image
// turns "ready < hint" into maxReplicas copies of the same crash loop.
std::vector<std::string> fenced_engine_pods(const K8sClient& k8s,
                                            const std::string& base_model) {
  std::vector<std::string> fenced;
  Json list = k8s.list(kCoreV1, "pods", "model%3D" + base_model);
  for (const auto& pod : list.at("items").items()) {
    bool bad = false;
    for (const auto& cs : pod.at({"status", "containerStatuses"}).items()) {
      const std::string reason =
          cs.at({"state", "waiting", "reason"}).as_string();
      if (reason == "CrashLoopBackOff" || reason == "ImagePullBackOff" ||
          reason == "ErrImagePull" || cs.at("restartCount").as_int(0) >= 3)
        bad = true;
    }
    if (bad) fenced.push_back(pod.at({"metadata", "name"}).as_string());
  }
  std::sort(fenced.begin(), fenced.end());
  return fenced;
}

// Victim = the engine the router fleet scores lowest (least routed
// in-flight per /debug/fleet). Falls back to the last pod by name when no
// router can answer — deterministic either way.
const EnginePod* pick_victim(const std::vector<RouterReplica>& routers,
                             const std::vector<EnginePod>& ready) {
  if (ready.empty()) return nullptr;
  for (const auto& r : routers) {
    try {
      auto resp = http_request("GET", r.base + "/debug/fleet", "", "", 5);
      if (!resp.ok()) continue;
      Json fleet = Json::parse(resp.body);
      const Json& engines = fleet.at("engines");
      long best = LONG_MAX;
      const EnginePod* victim = nullptr;
      for (const auto& pod : ready) {
        const long in_flight =
            engines.at(pod.base).at("in_flight_total").as_int(0);
        // <= so name-order ties break toward the LAST pod: matches the
        // no-router fallback, so flapping router reachability cannot flap
        // the victim choice between passes.
        if (in_flight <= best) {
          best = in_flight;
          victim = &pod;
        }
      }
      if (victim != nullptr) return victim;
    } catch (...) {
    }
  }
  return &ready.back();
}

void set_deployment_replicas(const K8sClient& k8s, const std::string& name,
                             long replicas) {
  auto dep = k8s.get(kAppsV1, "deployments", name);
  if (!dep) return;
  Json updated = *dep;
  updated["spec"]["replicas"] = replicas;
  k8s.replace(kAppsV1, "deployments", name, updated);
}

// POST an engine-admin action (drain/sleep/wake_up) THROUGH a router so
// service discovery marks the endpoint unroutable/routable in the same
// breath (request_service.py route_drain_request / route_sleep_wakeup) —
// falling back to the engine directly when no router is reachable (the
// probes reconcile discovery on the next cycle).
bool engine_admin_post(const std::vector<RouterReplica>& routers,
                       const std::string& engine_base,
                       const std::string& action, const std::string& params,
                       int timeout_s) {
  for (const auto& r : routers) {
    try {
      auto resp = http_request(
          "POST", r.base + "/" + action + "?url=" + engine_base + params, "",
          "", timeout_s);
      if (resp.ok()) return true;
    } catch (...) {
    }
  }
  try {
    return http_request("POST", engine_base + "/" + action +
                        (params.empty() ? "" : "?" + params.substr(1)),
                        "", "", timeout_s)
        .ok();
  } catch (...) {
    return false;
  }
}

// The full actuator for one autoscale-enabled TPURuntime. Returns status
// fields (desiredReplicas, idleStreak, lastScaleEpoch, fencedPods,
// sleeping, lastAutoscaleAction, replicaHint, routersPolled) — hysteresis
// state RIDES THE CR STATUS so `--once` passes (tests/CI) and operator
// restarts resume mid-cooldown instead of forgetting it.
Json autoscale_tpu_runtime(const K8sClient& k8s, const Json& cr) {
  const Json& as = cr.at({"spec", "autoscale"});
  const std::string cr_name = cr.at({"metadata", "name"}).as_string();
  const std::string dep_name = cr_name + "-engine";

  const long min_r = std::max(as.at("minReplicas").as_int(1), 0L);
  const long max_r = std::max(as.at("maxReplicas").as_int(8), min_r);
  const long stabilization_s = as.at("scaleDownStabilizationS").as_int(300);
  const long drain_deadline_s = as.at("drainDeadlineS").as_int(120);
  const long idle_verdicts = std::max(as.at("idleVerdicts").as_int(3), 1L);
  const bool scale_to_zero = as.at("scaleToZero").as_bool(false);
  // Scale-to-zero keeps ONE engine — slept, compile cache warm on disk —
  // so the floor never reaches an empty Deployment even when minReplicas=0.
  const long floor_r = std::max(min_r, 1L);

  const Json& st = cr.at("status");
  long idle_streak = st.at("idleStreak").as_int(0);
  long last_scale = st.at("lastScaleEpoch").as_int(0);
  bool sleeping = st.at("sleeping").as_bool(false);

  long current = floor_r;
  if (auto dep = k8s.get(kAppsV1, "deployments", dep_name))
    current = std::max(dep->at({"spec", "replicas"}).as_int(floor_r), 1L);

  const auto routers = router_replicas(k8s);
  const SignalView sig = poll_signal(routers);
  const auto fenced = fenced_engine_pods(k8s, cr_name);

  Json status = Json::object();
  Json fenced_json = Json::array();
  for (const auto& name : fenced) fenced_json.push_back(Json(name));
  status["fencedPods"] = fenced_json;
  status["routersPolled"] = sig.routers;
  status["replicaHint"] = sig.hint;

  if (sig.routers == 0) {
    // Zero evidence — hold position. An unreachable router fleet must
    // never read as "idle fleet": actuating blind is how autoscalers
    // delete the replicas that were busy serving.
    status["desiredReplicas"] = current;
    status["idleStreak"] = idle_streak;
    status["lastScaleEpoch"] = last_scale;
    status["sleeping"] = sleeping;
    status["lastAutoscaleAction"] = "hold_no_signal";
    return status;
  }

  long desired = std::min(std::max(sig.hint, floor_r), max_r);
  const long now = time(nullptr);
  std::string action = "none";

  // Idle verdict: nothing queued and the hint does not ask for more than we
  // run. Genuine surplus (hint < current) counts even with streams still in
  // flight — the blocking drain is what protects them; an exact-fit hint
  // (hint == current) counts only when the fleet is fully quiet, so the
  // streak can arm scale-to-zero at the floor but a momentary load dip
  // never pre-arms a scale-down. N consecutive verdicts arm the shrink
  // paths; any pressure resets the streak (anti-flap hysteresis).
  const bool idle =
      sig.queue_depth == 0 && sig.hint <= current &&
      (sig.hint < current || sig.in_flight == 0);
  idle_streak = idle ? idle_streak + 1 : 0;

  if (desired > current) {
    if (!fenced.empty()) {
      // Failure-aware: fenced pods already hold replica slots; piling more
      // replicas onto a crash loop is fuel, not capacity.
      action = "hold_fenced";
      desired = current;
    } else {
      set_deployment_replicas(k8s, dep_name, desired);
      if (sleeping) {
        // Surge while parked at zero: wake the slept standby FIRST — it
        // serves from its warm compile cache while the new pods come up.
        auto ready = ready_engine_pods(k8s, cr_name);
        if (!ready.empty())
          engine_admin_post(routers, ready.front().base, "wake_up", "", 10);
        sleeping = false;
      }
      last_scale = now;
      idle_streak = 0;
      current = desired;
      action = "scale_up";
    }
  } else if (desired < current) {
    if (idle_streak < idle_verdicts) {
      action = "hold_streak";
    } else if (now - last_scale < stabilization_s) {
      action = "hold_cooldown";
    } else if (!fenced.empty()) {
      // A fenced pod is the obvious victim: it serves nothing, so no
      // drain — shrink the Deployment and delete the broken pod.
      set_deployment_replicas(k8s, dep_name, current - 1);
      k8s.destroy(kCoreV1, "pods", fenced.front());
      last_scale = now;
      idle_streak = 0;
      action = "scale_down_fenced";
      current -= 1;
    } else {
      auto ready = ready_engine_pods(k8s, cr_name);
      const EnginePod* victim = pick_victim(routers, ready);
      if (victim == nullptr) {
        action = "hold_no_victim";
      } else {
        // Graceful ordering: drain THROUGH the router (discovery marks
        // the endpoint unroutable before the engine sees the POST), block
        // until in-flight work finishes or the drain deadline passes,
        // and only then shrink the Deployment and delete the pod —
        // SIGKILL never lands on a streaming response.
        engine_admin_post(
            routers, victim->base, "drain",
            "&wait=1&timeout=" + std::to_string(drain_deadline_s),
            static_cast<int>(drain_deadline_s) + 10);
        set_deployment_replicas(k8s, dep_name, current - 1);
        // Deleting the drained pod explicitly (instead of letting the
        // ReplicaSet pick) is what makes the drain meaningful; on a real
        // API server the pod-deletion-cost annotation would remove the
        // remaining race with the ReplicaSet controller.
        k8s.destroy(kCoreV1, "pods", victim->name);
        last_scale = now;
        idle_streak = 0;
        action = "scale_down";
        current -= 1;
      }
    }
  }

  // Pre-warmed scale-to-zero (docs/autoscaling.md "Scale to zero"): parked
  // at the floor with a fully idle fleet, the last engine sleeps — KV
  // freed, compile cache warm on disk. The FIRST admission-queue arrival
  // wakes it through the router (request_service wake-on-arrival); the
  // operator also wakes on queue evidence as the slower backstop.
  if (scale_to_zero && current == floor_r && action == "none") {
    // Sleeping is stricter than shrinking: no drain protects a slept
    // engine, so the fleet must be FULLY quiet, not merely surplus.
    if (!sleeping && idle && sig.in_flight == 0 &&
        idle_streak >= idle_verdicts) {
      auto ready = ready_engine_pods(k8s, cr_name);
      if (!ready.empty() &&
          engine_admin_post(routers, ready.front().base, "sleep", "&level=1",
                            10)) {
        sleeping = true;
        action = "sleep";
      }
    } else if (sleeping &&
               (sig.queue_depth > 0 || sig.in_flight > 0 ||
                sig.hint > current)) {
      auto ready = ready_engine_pods(k8s, cr_name);
      if (!ready.empty())
        engine_admin_post(routers, ready.front().base, "wake_up", "", 10);
      sleeping = false;
      action = "wake";
    }
  }

  status["desiredReplicas"] = desired;
  status["idleStreak"] = idle_streak;
  status["lastScaleEpoch"] = last_scale;
  status["sleeping"] = sleeping;
  status["lastAutoscaleAction"] = action;
  return status;
}

}  // namespace

ReconcileResult reconcile_tpu_runtime(const K8sClient& k8s, const Json& cr) {
  ReconcileResult result;
  const std::string ns = k8s.ns();
  bool changed = false;
  changed |= upsert(k8s, kCoreV1, "services", build_engine_service(cr, ns));
  if (cr.at({"spec", "storage", "enabled"}).as_bool(false)) {
    const std::string pvc_name =
        cr.at({"metadata", "name"}).as_string() + "-pvc";
    if (!k8s.get(kCoreV1, "persistentvolumeclaims", pvc_name))
      k8s.create(kCoreV1, "persistentvolumeclaims", build_engine_pvc(cr, ns));
  }
  changed |= upsert(k8s, kAppsV1, "deployments", build_engine_deployment(cr, ns));

  // Autoscale actuation runs AFTER the structural upserts so a fresh CR's
  // first pass creates the Deployment the actuator then scales.
  Json status = Json::object();
  if (cr.at("spec").has("autoscale")) {
    try {
      status = autoscale_tpu_runtime(k8s, cr);
      const std::string action =
          status.at("lastAutoscaleAction").as_string_or("none");
      if (action.rfind("scale", 0) == 0 || action == "sleep" ||
          action == "wake")
        changed = true;
    } catch (const std::exception& e) {
      fprintf(stderr, "[operator] tpuruntimes/%s: autoscale pass failed: %s\n",
              cr.at({"metadata", "name"}).as_string().c_str(), e.what());
    }
  }

  // Status: ready replicas from the owned Deployment.
  const std::string dep_name =
      cr.at({"metadata", "name"}).as_string() + "-engine";
  long ready = 0;
  if (auto dep = k8s.get(kAppsV1, "deployments", dep_name))
    ready = dep->at({"status", "readyReplicas"}).as_int(0);
  status["readyReplicas"] = ready;
  status["phase"] = status.at("sleeping").as_bool(false)
                        ? "Sleeping"
                        : (ready > 0 ? "Ready" : "Pending");
  status["lastReconciled"] = now_rfc3339();
  k8s.patch_status(kPstV1, "tpuruntimes",
                   cr.at({"metadata", "name"}).as_string(), status);
  result.changed = changed;
  result.phase = status.at("phase").as_string();
  return result;
}

// ---------------------------------------------------------------------------
// TPURouter
// ---------------------------------------------------------------------------

Json build_router_deployment(const Json& cr, const std::string& ns) {
  const Json& spec = cr.at("spec");
  const std::string name = cr.at({"metadata", "name"}).as_string() + "-router";

  Json args = Json::array();
  push_arg(args, "--host", "0.0.0.0");
  push_arg_num(args, "--port", spec.at("port").as_int(8000));
  push_arg(args, "--service-discovery",
           spec.at("serviceDiscovery").as_string_or("k8s"));
  if (spec.at("serviceDiscovery").as_string_or("k8s") == "k8s") {
    push_arg(args, "--k8s-namespace", ns);
    push_arg(args, "--k8s-label-selector",
             spec.at("k8sLabelSelector")
                 .as_string_or("environment=production-stack-tpu"));
  }
  push_arg(args, "--routing-logic",
           spec.at("routingLogic").as_string_or("roundrobin"));
  if (spec.has("sessionKey"))
    push_arg(args, "--session-key", spec.at("sessionKey").as_string());
  if (spec.has("cacheControllerUrl"))
    push_arg(args, "--cache-controller-url",
             spec.at("cacheControllerUrl").as_string());
  for (const auto& extra : spec.at("extraArgs").items()) args.push_back(extra);

  Json container = Json::object();
  container["name"] = "router";
  container["image"] = spec.at("image").as_string_or(
      "ghcr.io/production-stack-tpu/router:0.1.0");
  Json cmd = Json::array();
  cmd.push_back("pst-router");
  container["command"] = cmd;
  container["args"] = args;
  Json port = Json::object();
  port["containerPort"] = spec.at("port").as_int(8000);
  Json ports = Json::array();
  ports.push_back(port);
  container["ports"] = ports;

  Json containers = Json::array();
  containers.push_back(container);
  Json pod_spec = Json::object();
  pod_spec["containers"] = containers;
  if (spec.has("serviceAccountName"))
    pod_spec["serviceAccountName"] = spec.at("serviceAccountName").as_string();

  Json pod_labels = Json::object();
  pod_labels["app"] = name;
  Json pod_meta = Json::object();
  pod_meta["labels"] = pod_labels;
  Json tmpl = Json::object();
  tmpl["metadata"] = pod_meta;
  tmpl["spec"] = pod_spec;

  Json match = Json::object();
  match["app"] = name;
  Json selector = Json::object();
  selector["matchLabels"] = match;

  Json dspec = Json::object();
  dspec["replicas"] = spec.at("replicas").as_int(1);
  dspec["selector"] = selector;
  dspec["template"] = tmpl;

  Json dep = Json::object();
  dep["apiVersion"] = "apps/v1";
  dep["kind"] = "Deployment";
  dep["metadata"] = meta_for(cr, name, ns, "router");
  dep["spec"] = dspec;
  return dep;
}

Json build_router_service(const Json& cr, const std::string& ns) {
  const std::string name = cr.at({"metadata", "name"}).as_string() + "-router";
  Json svc = Json::object();
  svc["apiVersion"] = "v1";
  svc["kind"] = "Service";
  svc["metadata"] = meta_for(cr, name, ns, "router");
  Json sel = Json::object();
  sel["app"] = name;
  Json port = Json::object();
  port["port"] = cr.at({"spec", "servicePort"}).as_int(80);
  port["targetPort"] = cr.at({"spec", "port"}).as_int(8000);
  Json ports = Json::array();
  ports.push_back(port);
  Json sspec = Json::object();
  sspec["selector"] = sel;
  sspec["ports"] = ports;
  sspec["type"] = cr.at({"spec", "serviceType"}).as_string_or("ClusterIP");
  svc["spec"] = sspec;
  return svc;
}

ReconcileResult reconcile_tpu_router(const K8sClient& k8s, const Json& cr) {
  ReconcileResult result;
  const std::string ns = k8s.ns();
  bool changed = false;
  changed |= upsert(k8s, kCoreV1, "services", build_router_service(cr, ns));
  changed |= upsert(k8s, kAppsV1, "deployments", build_router_deployment(cr, ns));

  const std::string dep_name =
      cr.at({"metadata", "name"}).as_string() + "-router";
  long ready = 0;
  if (auto dep = k8s.get(kAppsV1, "deployments", dep_name))
    ready = dep->at({"status", "readyReplicas"}).as_int(0);
  // activeRuntimes: reference counts VLLMRuntimes (vllmrouter_controller.go:390).
  long runtimes = 0;
  try {
    runtimes = static_cast<long>(
        k8s.list(kPstV1, "tpuruntimes").at("items").items().size());
  } catch (...) {
  }
  Json status = Json::object();
  status["readyReplicas"] = ready;
  status["activeRuntimes"] = runtimes;
  status["phase"] = ready > 0 ? "Ready" : "Pending";
  status["lastReconciled"] = now_rfc3339();
  k8s.patch_status(kPstV1, "tpurouters",
                   cr.at({"metadata", "name"}).as_string(), status);
  result.changed = changed;
  result.phase = status.at("phase").as_string();
  return result;
}

// ---------------------------------------------------------------------------
// CacheServer
// ---------------------------------------------------------------------------

Json build_cache_server_deployment(const Json& cr, const std::string& ns) {
  const Json& spec = cr.at("spec");
  const std::string name =
      cr.at({"metadata", "name"}).as_string() + "-cache-server";
  Json args = Json::array();
  push_arg(args, "--host", "0.0.0.0");
  push_arg_num(args, "--port", spec.at("port").as_int(8100));
  push_arg_num(args, "--max-bytes",
               spec.at("maxBytes").as_int(8l << 30));
  Json container = Json::object();
  container["name"] = "cache-server";
  container["image"] = spec.at("image").as_string_or(
      "ghcr.io/production-stack-tpu/engine:0.1.0");
  Json cmd = Json::array();
  cmd.push_back("pst-kv-server");
  container["command"] = cmd;
  container["args"] = args;
  Json containers = Json::array();
  containers.push_back(container);
  Json pod_spec = Json::object();
  pod_spec["containers"] = containers;
  Json pod_labels = Json::object();
  pod_labels["app"] = name;
  Json pod_meta = Json::object();
  pod_meta["labels"] = pod_labels;
  Json tmpl = Json::object();
  tmpl["metadata"] = pod_meta;
  tmpl["spec"] = pod_spec;
  Json match = Json::object();
  match["app"] = name;
  Json selector = Json::object();
  selector["matchLabels"] = match;
  Json dspec = Json::object();
  dspec["replicas"] = spec.at("replicas").as_int(1);
  dspec["selector"] = selector;
  dspec["template"] = tmpl;
  Json dep = Json::object();
  dep["apiVersion"] = "apps/v1";
  dep["kind"] = "Deployment";
  dep["metadata"] = meta_for(cr, name, ns, "cache-server");
  dep["spec"] = dspec;
  return dep;
}

Json build_cache_server_service(const Json& cr, const std::string& ns) {
  const std::string name =
      cr.at({"metadata", "name"}).as_string() + "-cache-server";
  Json svc = Json::object();
  svc["apiVersion"] = "v1";
  svc["kind"] = "Service";
  svc["metadata"] = meta_for(cr, name, ns, "cache-server");
  Json sel = Json::object();
  sel["app"] = name;
  Json port = Json::object();
  port["port"] = cr.at({"spec", "port"}).as_int(8100);
  port["targetPort"] = cr.at({"spec", "port"}).as_int(8100);
  Json ports = Json::array();
  ports.push_back(port);
  Json sspec = Json::object();
  sspec["selector"] = sel;
  sspec["ports"] = ports;
  svc["spec"] = sspec;
  return svc;
}

ReconcileResult reconcile_cache_server(const K8sClient& k8s, const Json& cr) {
  ReconcileResult result;
  const std::string ns = k8s.ns();
  bool changed = false;
  changed |= upsert(k8s, kCoreV1, "services", build_cache_server_service(cr, ns));
  changed |=
      upsert(k8s, kAppsV1, "deployments", build_cache_server_deployment(cr, ns));
  Json status = Json::object();
  status["phase"] = "Ready";
  status["lastReconciled"] = now_rfc3339();
  k8s.patch_status(kPstV1, "cacheservers",
                   cr.at({"metadata", "name"}).as_string(), status);
  result.changed = changed;
  result.phase = "Ready";
  return result;
}

// ---------------------------------------------------------------------------
// LoraAdapter
// ---------------------------------------------------------------------------

namespace {

bool adapter_loaded(const std::string& base, const std::string& adapter) {
  try {
    auto resp = http_request("GET", base + "/v1/models", "", "", 5);
    if (!resp.ok()) return false;
    Json models = Json::parse(resp.body);
    for (const auto& m : models.at("data").items())
      if (m.at("id").as_string() == adapter) return true;
  } catch (...) {
  }
  return false;
}

bool post_adapter(const std::string& base, const std::string& endpoint,
                  const std::string& adapter, const std::string& path) {
  Json body = Json::object();
  body["lora_name"] = adapter;
  if (!path.empty()) body["lora_path"] = path;
  try {
    auto resp = http_request("POST", base + endpoint, body.dump(),
                             "application/json", 10);
    return resp.ok();
  } catch (...) {
    return false;
  }
}

}  // namespace

namespace {

const char* kLoraFinalizer = "pst.production-stack.io/lora-unload";

bool has_lora_finalizer(const Json& cr) {
  for (const auto& f : cr.at({"metadata", "finalizers"}).items())
    if (f.as_string() == kLoraFinalizer) return true;
  return false;
}

}  // namespace

ReconcileResult reconcile_lora_adapter(const K8sClient& k8s, const Json& cr) {
  // Placement algorithms follow the reference semantics
  // (loraadapter_controller.go:394 getOptimalPlacement):
  //   default   — load on every ready pod
  //   ordered   — first N pods by name
  //   equalized — N pods chosen round-robin by a stable hash offset, so
  //               multiple adapters spread across the fleet
  ReconcileResult result;
  const Json& spec = cr.at("spec");
  const std::string cr_name = cr.at({"metadata", "name"}).as_string();
  const std::string adapter = spec.at("adapterName").as_string_or(cr_name);
  const std::string path = spec.at("adapterPath").as_string_or("");
  const std::string base_model = spec.at("baseModel").as_string();

  // Finalizer-based deletion (reference handleDeletion,
  // loraadapter_controller.go:868): a deleted CR first unloads the adapter
  // from every pod that still serves it, then releases the finalizer so the
  // API server can drop the object. Without this a delete between passes
  // would strand adapters on pods forever.
  const bool deleting =
      !cr.at({"metadata", "deletionTimestamp"}).as_string_or("").empty();
  if (deleting) {
    // Unload is posted to EVERY matching pod unconditionally: probing
    // adapter_loaded() first would let a transiently-unreachable pod read
    // as "not loaded", release the finalizer, and strand the adapter on
    // that pod forever. Unloading an absent adapter is a no-op server-side;
    // an unreachable pod fails the POST and holds the finalizer for the
    // next reconcile.
    auto pods = ready_engine_pods(k8s, base_model);
    bool all_unloaded = true;
    for (const auto& pod : pods) {
      all_unloaded &=
          post_adapter(pod.base, "/v1/unload_lora_adapter", adapter, "");
    }
    if (all_unloaded && has_lora_finalizer(cr)) {
      Json updated = cr;
      Json remaining = Json::array();
      for (const auto& f : cr.at({"metadata", "finalizers"}).items())
        if (f.as_string() != kLoraFinalizer) remaining.push_back(f);
      updated["metadata"]["finalizers"] = remaining;
      k8s.replace(kPstV1, "loraadapters", cr_name, updated);
    }
    result.changed = true;
    result.phase = "Deleting";
    return result;
  }
  if (!has_lora_finalizer(cr)) {
    Json updated = cr;
    Json finalizers = Json::array();
    for (const auto& f : cr.at({"metadata", "finalizers"}).items())
      finalizers.push_back(f);
    finalizers.push_back(Json(std::string(kLoraFinalizer)));
    updated["metadata"]["finalizers"] = finalizers;
    try {
      k8s.replace(kPstV1, "loraadapters", cr_name, updated);
    } catch (const std::exception& e) {
      fprintf(stderr, "[operator] loraadapters/%s: finalizer add failed: %s\n",
              cr_name.c_str(), e.what());
    }
  }
  const std::string algo =
      spec.at({"placement", "algorithm"}).as_string_or("default");
  long want = spec.at({"placement", "replicas"}).as_int(0);

  auto pods = ready_engine_pods(k8s, base_model);
  std::vector<EnginePod> desired;
  if (algo == "default" || want <= 0 ||
      want >= static_cast<long>(pods.size())) {
    desired = pods;
  } else if (algo == "ordered") {
    desired.assign(pods.begin(), pods.begin() + want);
  } else {  // equalized
    size_t offset = 0;
    for (unsigned char c : adapter) offset = offset * 31 + c;
    for (long i = 0; i < want; ++i)
      desired.push_back(pods[(offset + static_cast<size_t>(i)) % pods.size()]);
  }

  Json loaded = Json::array();
  bool changed = false;
  for (const auto& pod : pods) {
    const bool should_have =
        std::any_of(desired.begin(), desired.end(),
                    [&](const EnginePod& p) { return p.name == pod.name; });
    const bool has = adapter_loaded(pod.base, adapter);
    if (should_have && !has) {
      changed |= post_adapter(pod.base, "/v1/load_lora_adapter", adapter, path);
    } else if (!should_have && has) {
      changed |= post_adapter(pod.base, "/v1/unload_lora_adapter", adapter, "");
    }
    if (should_have) loaded.push_back(pod.name);
  }

  Json status = Json::object();
  status["loadedPods"] = loaded;
  status["phase"] = loaded.items().empty() ? "Pending" : "Ready";
  status["lastReconciled"] = now_rfc3339();
  k8s.patch_status(kPstV1, "loraadapters",
                   cr.at({"metadata", "name"}).as_string(), status);
  result.changed = changed;
  result.phase = status.at("phase").as_string();
  return result;
}

}  // namespace pst
