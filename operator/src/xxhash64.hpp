// XXH64 (public algorithm, from its specification) — the same hash the
// router's prefix trie and the reference's Go picker use
// (prefix_aware_picker.go / prefix/hashtrie.py), so a C++ picker and the
// Python router agree on chunk identity.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

namespace pst {

inline uint64_t rotl64(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

inline uint64_t xxh64(const void* data, size_t len, uint64_t seed = 0) {
  constexpr uint64_t P1 = 11400714785074694791ull;
  constexpr uint64_t P2 = 14029467366897019727ull;
  constexpr uint64_t P3 = 1609587929392839161ull;
  constexpr uint64_t P4 = 9650029242287828579ull;
  constexpr uint64_t P5 = 2870177450012600261ull;

  const uint8_t* p = static_cast<const uint8_t*>(data);
  const uint8_t* end = p + len;
  uint64_t h;

  auto read64 = [](const uint8_t* q) {
    uint64_t v;
    memcpy(&v, q, 8);
    return v;  // little-endian host assumed (x86/ARM)
  };
  auto read32 = [](const uint8_t* q) {
    uint32_t v;
    memcpy(&v, q, 4);
    return static_cast<uint64_t>(v);
  };
  auto round = [&](uint64_t acc, uint64_t input) {
    acc += input * P2;
    acc = rotl64(acc, 31);
    return acc * P1;
  };

  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    do {
      v1 = round(v1, read64(p)); p += 8;
      v2 = round(v2, read64(p)); p += 8;
      v3 = round(v3, read64(p)); p += 8;
      v4 = round(v4, read64(p)); p += 8;
    } while (p + 32 <= end);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    auto merge = [&](uint64_t acc, uint64_t v) {
      acc ^= round(0, v);
      return acc * P1 + P4;
    };
    h = merge(h, v1); h = merge(h, v2); h = merge(h, v3); h = merge(h, v4);
  } else {
    h = seed + P5;
  }
  h += static_cast<uint64_t>(len);
  while (p + 8 <= end) {
    h ^= round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    ++p;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

inline uint64_t xxh64(const std::string& s, uint64_t seed = 0) {
  return xxh64(s.data(), s.size(), seed);
}

}  // namespace pst
