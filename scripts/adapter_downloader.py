"""LoRA adapter download sidecar.

Reference analogue: `docker/Dockerfile.sidecar` + the downloader service the
LoraAdapter reconciler drives (`loraadapter_controller.go:394` placement →
pod-local adapter files). Runs next to the engine container sharing the
adapter volume; the operator (or a human) POSTs a download request and the
engine then loads the files with `/v1/load_lora_adapter`.

API:
  POST /download {"name": "my-adapter", "source": "<uri>"}
      hf://org/repo          HuggingFace snapshot (needs egress + HF_TOKEN)
      http(s)://...          single-file or .tar.gz archive fetch
      file:///path           copy from an already-mounted path
  GET  /adapters             list downloaded adapter dirs
  GET  /healthz
"""

from __future__ import annotations

import asyncio
import os
import shutil
import tarfile
import tempfile

from aiohttp import ClientSession, web

ADAPTER_DIR = os.environ.get("ADAPTER_DIR", "/adapters")
PORT = int(os.environ.get("PORT", "8010"))


async def _fetch_http(url: str, dest_dir: str) -> None:
    fname = url.rstrip("/").rsplit("/", 1)[-1] or "adapter.bin"
    os.makedirs(dest_dir, exist_ok=True)
    async with ClientSession() as session:
        async with session.get(url) as resp:
            resp.raise_for_status()
            with tempfile.NamedTemporaryFile(delete=False) as tmp:
                while True:
                    chunk = await resp.content.read(1 << 20)
                    if not chunk:
                        break
                    tmp.write(chunk)
    if fname.endswith((".tar.gz", ".tgz", ".tar")):
        with tarfile.open(tmp.name) as tar:
            tar.extractall(dest_dir, filter="data")
        os.unlink(tmp.name)
    else:
        shutil.move(tmp.name, os.path.join(dest_dir, fname))


def _fetch_hf(repo: str, dest_dir: str) -> None:
    from huggingface_hub import snapshot_download

    snapshot_download(
        repo_id=repo,
        local_dir=dest_dir,
        token=os.environ.get("HF_TOKEN") or None,
        allow_patterns=["*.safetensors", "*.json"],
    )


async def download(request: web.Request) -> web.Response:
    body = await request.json()
    name, source = body.get("name"), body.get("source", "")
    if not name or "/" in name or name.startswith("."):
        return web.json_response({"error": "invalid adapter name"}, status=400)
    dest = os.path.join(ADAPTER_DIR, name)
    try:
        if source.startswith("hf://"):
            await asyncio.get_running_loop().run_in_executor(
                None, _fetch_hf, source[len("hf://"):], dest
            )
        elif source.startswith(("http://", "https://")):
            await _fetch_http(source, dest)
        elif source.startswith("file://"):
            src = source[len("file://"):]
            if os.path.isdir(src):
                shutil.copytree(src, dest, dirs_exist_ok=True)
            else:
                os.makedirs(dest, exist_ok=True)
                shutil.copy(src, dest)
        else:
            return web.json_response(
                {"error": f"unsupported source scheme: {source}"}, status=400
            )
    except Exception as e:  # noqa: BLE001 — report, don't crash the sidecar
        return web.json_response({"error": str(e)}, status=502)
    return web.json_response({"name": name, "path": dest, "status": "ok"})


async def list_adapters(request: web.Request) -> web.Response:
    if not os.path.isdir(ADAPTER_DIR):
        return web.json_response({"adapters": []})
    return web.json_response(
        {"adapters": sorted(
            d for d in os.listdir(ADAPTER_DIR)
            if os.path.isdir(os.path.join(ADAPTER_DIR, d))
        )}
    )


async def healthz(request: web.Request) -> web.Response:
    return web.json_response({"status": "ok"})


def main() -> None:
    app = web.Application()
    app.router.add_post("/download", download)
    app.router.add_get("/adapters", list_adapters)
    app.router.add_get("/healthz", healthz)
    os.makedirs(ADAPTER_DIR, exist_ok=True)
    web.run_app(app, port=PORT)


if __name__ == "__main__":
    main()
