#!/usr/bin/env python3
"""Render a bench round's verdicts as a markdown report.

``python scripts/bench_report.py BENCH_r06.json [-o report.md]`` —
the human-facing face of ``benchmarks/verdicts.py``: per-claim status
table, the evidence bundles the round's forensics collector wrote, and
the round-over-round trajectory, so a reviewer reads one page instead
of diffing raw JSON against five prior rounds.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.verdicts import (  # noqa: E402
    evaluate_round, load_round, round_files, trajectory,
)

_STATUS_ICON = {"pass": "✅ pass", "fail": "❌ FAIL",
                "unevaluable": "⚪ unevaluable"}


def _fmt(val) -> str:
    if isinstance(val, float):
        return f"{val:g}"
    if isinstance(val, (dict, list)):
        return "`" + json.dumps(val, sort_keys=True) + "`"
    return str(val)


def render(verdicts: dict, evidence_dir: str = None) -> str:
    lines = ["# Bench round verdicts", ""]
    head = "**OK**" if verdicts.get("ok") else "**FAILING**"
    lines.append(
        f"{head} — {verdicts.get('n_pass', 0)} pass / "
        f"{verdicts.get('n_fail', 0)} fail / "
        f"{verdicts.get('n_unevaluable', 0)} unevaluable"
    )
    if verdicts.get("recovered_from"):
        lines.append("")
        lines.append(
            f"> Result recovered from the driver tail "
            f"(`{verdicts['recovered_from']}`; rc={verdicts.get('rc')}) — "
            "the round never emitted its final JSON."
        )
    if verdicts.get("error"):
        lines.append("")
        lines.append(f"> {verdicts['error']}")
    lines += ["", "| claim | target | status | observed |",
              "|---|---|---|---|"]
    for c in verdicts.get("claims", []):
        observed = _fmt(c.get("observed", "—"))
        note = c.get("note")
        status = _STATUS_ICON.get(c["status"], c["status"])
        if note:
            status += f" ({note})"
        lines.append(
            f"| {c['claim']} | {c['target']} | {status} | {observed} |"
        )

    bundles = []
    if evidence_dir and os.path.isdir(evidence_dir):
        bundles = sorted(
            f for f in os.listdir(evidence_dir) if f.endswith(".json")
        )
    if bundles:
        lines += ["", "## Evidence bundles", ""]
        for name in bundles:
            path = os.path.join(evidence_dir, name)
            trigger = phase = point = "?"
            try:
                with open(path) as f:
                    b = json.load(f)
                trigger, phase, point = (b.get("trigger"), b.get("phase"),
                                         b.get("point"))
            except (OSError, ValueError):
                pass
            lines.append(
                f"- `{name}` — trigger `{trigger}`, phase `{phase}`, "
                f"point `{point}`"
            )

    traj = verdicts.get("trajectory")
    if traj:
        lines += ["", "## Trajectory", "",
                  "| round | p50 TTFT (ms) | p99 TTFT (ms) | "
                  "restart→ready (s) | health |",
                  "|---|---|---|---|---|"]
        for row in traj:
            if row.get("parsed"):
                health = "parsed"
                if row.get("recovered_from"):
                    health = f"recovered ({row['recovered_from']})"
            else:
                health = f"UNPARSEABLE (rc={row.get('rc')})"
            lines.append(
                f"| {row['round']} | {_fmt(row.get('p50_ttft_ms', '—'))} "
                f"| {_fmt(row.get('p99_ttft_ms', '—'))} "
                f"| {_fmt(row.get('restart_to_ready_s', '—'))} "
                f"| {health} |"
            )
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("round", help="bench result JSON or BENCH_rNN capture")
    ap.add_argument("-o", "--out", default=None,
                    help="write the markdown here (default: stdout)")
    ap.add_argument("--rounds-dir", default=None,
                    help="BENCH_rNN.json directory for the trajectory "
                         "section (default: the round file's directory)")
    ap.add_argument("--evidence-dir", default=None,
                    help="forensics bundle directory (default: "
                         "<round>.evidence when it exists)")
    args = ap.parse_args(argv)

    parsed, meta = load_round(args.round)
    verdicts = parsed.get("verdicts") if isinstance(parsed, dict) else None
    if not isinstance(verdicts, dict) or "claims" not in verdicts:
        verdicts = evaluate_round(parsed, meta)
    root = args.rounds_dir or os.path.dirname(
        os.path.abspath(args.round)) or "."
    try:
        verdicts.setdefault("trajectory", trajectory(round_files(root)))
    except OSError:
        pass
    evidence = args.evidence_dir or (
        args.round + ".evidence" if os.path.isdir(args.round + ".evidence")
        else None
    )
    text = render(verdicts, evidence)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0 if verdicts.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
