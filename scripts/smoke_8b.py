"""Smoke: llama-3-8b quantized on one real chip — startup, prefill, decode.

Usage: smoke_8b.py [n_users] [history_tokens] [quant]   (quant: int8|int4)
"""
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams

    quant = sys.argv[3] if len(sys.argv) > 3 else "int8"
    print("backend:", jax.default_backend(), "quant:", quant, flush=True)
    t0 = time.time()
    cfg = EngineConfig(
        model="llama-3-8b",
        quantization=quant,
        max_model_len=32768,
        block_size=128,
        max_num_seqs=16,
        max_prefill_tokens=1024,
        attn_impl="pallas",
        kv_cache_dtype="float8_e4m3fn",
        num_decode_steps=4,
        min_decode_bucket=4,
    )
    engine = LLMEngine(cfg)
    print(f"engine up in {time.time()-t0:.1f}s, "
          f"{engine.runner.param_count/1e9:.2f}B params, "
          f"{engine.runner.num_blocks} kv pages", flush=True)

    rng = np.random.default_rng(0)
    V = engine.model_cfg.vocab_size
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    hist = int(sys.argv[2]) if len(sys.argv) > 2 else 21000

    # Short-gen sanity first (compile + correctness of shapes).
    t0 = time.time()
    out = engine.generate(
        [rng.integers(1, V - 1, size=32).tolist()],
        SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True),
    )
    print(f"short gen: 8 tokens in {time.time()-t0:.1f}s (incl. compile): "
          f"{out[0]['token_ids']}", flush=True)

    # Long prefill probe.
    prompt = rng.integers(1, V - 1, size=hist).tolist()
    t0 = time.time()
    engine.generate([prompt], SamplingParams(max_tokens=1, temperature=0.0,
                                             ignore_eos=True))
    dt = time.time() - t0
    print(f"cold prefill: {hist} tokens in {dt:.1f}s ({hist/dt:.0f} tok/s "
          f"incl. compiles)", flush=True)

    # Warm prefill probe (buckets compiled).
    prompt2 = rng.integers(1, V - 1, size=hist).tolist()
    t0 = time.time()
    engine.generate([prompt2], SamplingParams(max_tokens=1, temperature=0.0,
                                              ignore_eos=True))
    dt = time.time() - t0
    print(f"warm prefill: {hist} tokens in {dt:.1f}s ({hist/dt:.0f} tok/s)",
          flush=True)

    # Decode probe: n_users concurrent at full context. Timed window opens
    # only once EVERY user is past prefill (otherwise the other users'
    # prefill chunks pollute the decode rate).
    prompts = [rng.integers(1, V - 1, size=hist).tolist() for _ in range(n_users)]
    for i, p in enumerate(prompts):
        engine.add_request(f"dec-{i}", prompt_token_ids=p,
                           sampling=SamplingParams(max_tokens=96, temperature=0.0,
                                                   ignore_eos=True))
    emitted = {f"dec-{i}": 0 for i in range(n_users)}
    while engine.has_work():
        for o in engine.step():
            emitted[o.request_id] += len(o.new_token_ids)
        if all(v >= 1 for v in emitted.values()):
            break  # every user decoding now
    t0 = time.time()
    base = sum(emitted.values())
    while engine.has_work():
        for o in engine.step():
            emitted[o.request_id] += len(o.new_token_ids)
    dt = time.time() - t0
    toks = sum(emitted.values()) - base
    print(f"decode probe ({n_users} users @ {hist} ctx, saturated window): "
          f"{toks} tokens, {toks/max(dt, 1e-9):.0f} tok/s", flush=True)
    print("kv usage:", engine.allocator.usage,
          "swaps:", engine.swapper.swap_out_total if engine.swapper else 0,
          flush=True)


if __name__ == "__main__":
    main()
