"""Locate the decode-step bottleneck at the bench shape (8 users x 21k ctx).

Times, each as a jit that loops the op N times over a fori_loop (so the
~5ms tunnel dispatch floor amortizes away):
  1. attention kernel alone, one layer
  2. attention across all 16 layers (scan, no MLP)
  3. KV scatter alone across 16 layers
  4. the full model decode step (runner._step shape)

``--host-gap`` instead measures the serial host time between decode
bursts (pst_engine_host_gap_seconds) through the real engine loop,
pre/post pipeline: one leg with pipelining forced OFF (the synchronous
loop — every burst pays the full host bookkeeping gap) and one leg
pipelined (burst N+1 dispatched before burst N's bookkeeping runs), so
the overlapped-decode win is reproducible outside the bench harness.
"""

import time
import functools

import numpy as np
import jax
import jax.numpy as jnp

from production_stack_tpu.ops.paged_attention_pallas import pallas_paged_attention

L, nb, bs, KH, hd, H = 16, 1408, 128, 8, 128, 16
B, W, live = 8, 256, 21000
lanes = KH * hd
scale = 1.0 / np.sqrt(hd)


def timed(fn, *args, iters=10, inner=8):
    """fn must take (*args) and return something; we scan it inner times."""
    jfn = jax.jit(fn)
    out = jfn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jfn(*args)
    jax.block_until_ready(out)
    per_call = (time.perf_counter() - t0) / iters
    return per_call / inner


def main():
    import sys
    model_only = "--model-only" in sys.argv
    rng = np.random.default_rng(0)
    if "--host-gap" in sys.argv:
        host_gap_leg()
        return
    if model_only:
        model_leg(rng)
        return
    kv = jnp.zeros((L, nb, 2, bs, lanes), jnp.bfloat16)
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), jnp.bfloat16)
    # 8 x 256 > nb: page ids may repeat across rows (timing only).
    tables = jnp.asarray(
        rng.integers(0, nb, size=(B, W)).astype(np.int32)
    )
    lens = jnp.full((B,), live, jnp.int32)
    pos = jnp.full((B, 1), live - 1, jnp.int32)
    INNER = 8

    def attn_one_layer(q, kv):
        def body(i, acc):
            o = pallas_paged_attention(q, kv, tables, lens, pos, 0, scale=scale)
            return acc + o.astype(jnp.float32)
        return jax.lax.fori_loop(0, INNER, body, jnp.zeros(q.shape, jnp.float32))

    t = timed(attn_one_layer, q, kv, inner=INNER)
    gbs = B * live * 2 * KH * hd * 2 / t / 1e9
    print(f"attn 1 layer : {t*1e3:7.3f} ms  ({gbs:5.0f} GB/s live-KV)")

    def attn_16(q, kv):
        def body(i, acc):
            o = pallas_paged_attention(q, kv, tables, lens, pos, i % L, scale=scale)
            return acc + o.astype(jnp.float32)
        return jax.lax.fori_loop(0, INNER * L, body, jnp.zeros(q.shape, jnp.float32))

    t16 = timed(attn_16, q, kv, inner=INNER)  # per 16-layer sweep
    print(f"attn 16 layer: {t16*1e3:7.3f} ms  ({B*live*2*KH*hd*2*L/t16/1e9:5.0f} GB/s)")

    flat_write = jnp.asarray(
        (np.arange(B) * bs + live % bs).astype(np.int32)
    )
    kvd = jnp.asarray(rng.standard_normal((2 * B, lanes)), jnp.bfloat16)

    def scatter_16(kv):
        def body(i, kv):
            idx = jnp.concatenate([
                (i % L) * nb * 2 * bs + flat_write,
                (i % L) * nb * 2 * bs + flat_write + bs,
            ])
            flat = kv.reshape(L * nb * 2 * bs, lanes)
            flat = flat.at[idx].set(kvd, mode="drop")
            return flat.reshape(L, nb, 2, bs, lanes)
        return jax.lax.fori_loop(0, INNER * L, body, kv)

    jscatter = jax.jit(scatter_16, donate_argnums=(0,))
    kv2 = jscatter(kv)
    jax.block_until_ready(kv2)
    t0 = time.perf_counter()
    for _ in range(6):
        kv2 = jscatter(kv2)
    jax.block_until_ready(kv2)
    ts = (time.perf_counter() - t0) / 6 / INNER
    print(f"scatter x16  : {ts*1e3:7.3f} ms per 16-layer sweep")


def host_gap_leg():
    """--host-gap: serial host time between decode bursts, pre/post
    pipeline (reports pst_engine_host_gap_seconds p50/mean per bucket and
    the ratio against the mean decode-step wall)."""
    import sys

    import jax

    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine
    from production_stack_tpu.engine.sequence import SamplingParams
    from production_stack_tpu.obs import ENGINE_TELEMETRY

    on_tpu = jax.default_backend() == "tpu" and "--tiny" not in sys.argv
    if on_tpu:
        kw = dict(
            model="llama-1b", max_model_len=8192, block_size=bs,
            num_kv_blocks=nb, max_num_seqs=16, max_prefill_tokens=1024,
            attn_impl="pallas", num_decode_steps=2, min_decode_bucket=8,
        )
        n_seqs, prompt_len, max_tokens = 8, 512, 96
    else:
        kw = dict(
            model="tiny-llama-debug", max_model_len=512, block_size=8,
            num_kv_blocks=512, max_num_seqs=8, max_prefill_tokens=128,
            attn_impl="gather", num_decode_steps=2,
        )
        n_seqs, prompt_len, max_tokens = 4, 48, 48

    def run(pipelined: bool) -> tuple:
        ENGINE_TELEMETRY.reset_for_tests()
        eng = LLMEngine(EngineConfig(
            **kw,
            overlap_decode=False,  # isolate: pipeline ONLY when forced
            async_decode=pipelined,
            adaptive_decode_steps=0,
        ))
        r = np.random.default_rng(0)
        for i in range(n_seqs):
            eng.add_request(
                f"g{i}",
                prompt_token_ids=r.integers(
                    1, eng.model_cfg.vocab_size - 1, prompt_len
                ).tolist(),
                sampling=SamplingParams(
                    max_tokens=max_tokens, temperature=0.0, ignore_eos=True
                ),
            )
        steps, wall = 0, 0.0
        while eng.has_work():
            t0 = time.perf_counter()
            eng.step()
            wall += time.perf_counter() - t0
            steps += 1
        summary = ENGINE_TELEMETRY.host_gap_summary()
        return summary, wall / max(steps, 1)

    for pipelined in (False, True):
        summary, step_mean = run(pipelined)
        tag = "pipelined " if pipelined else "synchronous"
        if not summary:
            print(f"{tag}: no decode host-gap samples recorded")
            continue
        for bucket, s in summary.items():
            ratio = s["p50"] / step_mean if step_mean else float("inf")
            print(
                f"{tag} {bucket:>8}: host gap p50 {s['p50']*1e3:7.3f} ms  "
                f"mean {s['mean']*1e3:7.3f} ms  n={int(s['count'])}  "
                f"(engine step mean {step_mean*1e3:.3f} ms, "
                f"p50/step {ratio:.2%})"
            )


def model_leg(rng):
    # Full engine decode step (one token for 8 seqs).
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.runner import ModelRunner
    from production_stack_tpu.engine.sequence import Sequence, SamplingParams

    cfg = EngineConfig(
        model="llama-1b", max_model_len=32768, block_size=bs,
        num_kv_blocks=nb, max_num_seqs=16, max_prefill_tokens=1024,
        attn_impl="pallas", num_decode_steps=2, min_decode_bucket=8,
    )
    runner = ModelRunner(cfg)
    seqs = []
    blocks_per = -(-live // bs)  # 165 pages of 128 tokens for 21k ctx
    assert B * blocks_per <= nb, "synthetic tables must stay in range"
    for i in range(B):
        s = Sequence(f"s{i}", list(range(100)), SamplingParams(max_tokens=8))
        s.block_ids = list(range(i * blocks_per, (i + 1) * blocks_per))
        s.output_token_ids = [1] * (live - 100)
        s.num_computed_tokens = live
        seqs.append(s)
    runner.execute_decode(seqs)  # compile
    t0 = time.perf_counter()
    for _ in range(10):
        out = runner.execute_decode(seqs)
    dt = (time.perf_counter() - t0) / 10
    print(f"model decode : {dt*1e3:7.3f} ms per step (incl dispatch)")
    t0 = time.perf_counter()
    for _ in range(5):
        out = runner.execute_decode_multi(seqs, 2)
    dt = (time.perf_counter() - t0) / 5
    print(f"decode burst2: {dt*1e3:7.3f} ms per 2-token burst")


if __name__ == "__main__":
    main()
