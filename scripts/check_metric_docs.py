#!/usr/bin/env python3
"""Metric-name lint: every ``pst`` metric registered in code must be
documented in docs/observability.md.

The observability docs are a contract (dashboards, alert rules, and
operators' PromQL all read from them); a metric that exists in code but
not in the doc is invisible to everyone who needs it. Run by the
pre-commit CI workflow; exits non-zero listing the undocumented names.

A family wildcard in the doc (e.g. ``pst_resilience_*``) covers every
metric sharing that prefix; counters match with or without Prometheus's
implicit ``_total`` suffix.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
DOC = ROOT / "docs" / "observability.md"
CODE_DIRS = [ROOT / "production_stack_tpu"]

# Counter("pst_x", ...) / Gauge(...) / Histogram(...) — the constructor
# kind decides whether exposition appends _total.
_METRIC_RE = re.compile(
    r"\b(Counter|Gauge|Histogram)\(\s*[\'\"](pst[^\'\"]+)[\'\"]", re.S
)
_WILDCARD_RE = re.compile(r"(pst[\w:]*)\*")


def registered_metrics() -> list:
    """(name, kind) for every pst-prefixed metric constructor in code."""
    out = []
    for base in CODE_DIRS:
        for py in sorted(base.rglob("*.py")):
            text = py.read_text()
            for kind, name in _METRIC_RE.findall(text):
                out.append((name, kind, py.relative_to(ROOT)))
    return out


def undocumented(doc_text: str) -> list:
    # Bare "pst_*" (the name-family overview bullet) must not whitelist
    # every metric — only family wildcards with a real stem count.
    prefixes = [p for p in _WILDCARD_RE.findall(doc_text) if len(p) > 4]
    missing = []
    for name, kind, path in registered_metrics():
        exposition = name
        if kind == "Counter" and not name.endswith("_total"):
            exposition = name + "_total"
        if name in doc_text or exposition in doc_text:
            continue
        if any(name.startswith(p) for p in prefixes):
            continue
        missing.append((exposition, str(path)))
    return missing


def main() -> int:
    doc_text = DOC.read_text()
    missing = undocumented(doc_text)
    if missing:
        for name, path in missing:
            print(f"UNDOCUMENTED metric {name!r} (registered in {path}) "
                  f"— add it to docs/observability.md")
        return 1
    print(f"ok: all {len(registered_metrics())} pst metrics documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
