#!/usr/bin/env python3
"""Metric-name lint: registry-driven CI shim.

Historically this script carried its own regex scan and its own copy of
the documentation-matching rules; both now live in ONE place — the
``metric-registry`` check of :mod:`production_stack_tpu.analysis`
(pstlint), driven by the declarations in
``production_stack_tpu/obs/metric_registry.py``. This shim keeps the CI
entry point (pre-commit workflow) and the exit-code contract stable:
non-zero listing every violation — an undeclared constructor, a stale
declaration, or a declared metric missing from docs/observability.md.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(ROOT))

from production_stack_tpu.analysis.pstlint import run_checks  # noqa: E402


def main() -> int:
    findings = run_checks(
        [str(ROOT / "production_stack_tpu"), str(ROOT / "scripts")],
        checks=["metric-registry"],
        root=ROOT,
    )
    # Framework findings (bad-suppression etc.) elsewhere in the tree
    # belong to the dedicated pstlint CI job; this step owns ONLY the
    # metric contract.
    active = [
        f for f in findings
        if not f.suppressed and f.check == "metric-registry"
    ]
    for f in active:
        print(f.format())
    if active:
        return 1
    print("ok: metric registry, code, and docs agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
