"""Serving-config experiment: one protocol run, knobs via argv.

Usage: python scripts/serve_exp.py <model> <n_users> <num_decode_steps> \
          <async 0|1> <qps> [n_rounds] [quant]
Prints one JSON line: p50/p99 TTFT + decode tok/s for the config.
Used to tune num_decode_steps / pipelined-decode / quantization against
the reference protocol (VERDICT r3 items 2-3: decode throughput + p99 tail).
"""
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main() -> None:
    model = sys.argv[1]
    n_users = int(sys.argv[2])
    n_steps = int(sys.argv[3])
    use_async = bool(int(sys.argv[4]))
    qps = float(sys.argv[5])
    n_rounds = int(sys.argv[6]) if len(sys.argv) > 6 else 4
    quant = sys.argv[7] if len(sys.argv) > 7 else None

    from benchmarks.protocol import ProtocolRunner
    from production_stack_tpu.engine.config import EngineConfig
    from production_stack_tpu.engine.engine import LLMEngine

    import os

    blocks = {"llama-1b": 1408, "llama-3-8b": 840}[model]
    cfg = EngineConfig(
        model=model,
        quantization=quant,
        max_model_len=32768,
        block_size=128,
        num_kv_blocks=blocks,
        max_num_seqs=16,
        max_prefill_tokens=1024,
        attn_impl="pallas",
        kv_cache_dtype="float8_e4m3fn",
        num_decode_steps=n_steps,
        adaptive_decode_steps=int(os.environ.get("PST_ADAPTIVE", "0")),
        adaptive_decode_quiet_s=float(os.environ.get("PST_QUIET", "0.5")),
        adaptive_decode_min_running=int(os.environ.get("PST_MINRUN", "0")),
        min_decode_bucket=min(8, n_users),
        async_decode=use_async,
    )
    t0 = time.time()
    engine = LLMEngine(cfg)
    print(f"[exp] up in {time.time()-t0:.0f}s", file=sys.stderr, flush=True)
    pr = ProtocolRunner(engine, n_users)
    t0 = time.time()
    pr.cold_prefill()
    print(f"[exp] cold {time.time()-t0:.0f}s", file=sys.stderr, flush=True)
    pr.warm_compile()
    print("[exp] warm done", file=sys.stderr, flush=True)
    t0 = time.time()
    ttfts = pr.measured_rounds(qps, n_rounds)
    wall = time.time() - t0
    rate = pr.decode_probe()
    print(json.dumps({
        "model": model, "n_users": n_users, "num_decode_steps": n_steps,
        "async": use_async, "qps": qps, "quant": quant,
        "p50_ttft_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1),
        "p99_ttft_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 1),
        "n_requests": len(ttfts),
        "decode_tok_per_s": round(rate, 1) if rate else None,
        "measure_wall_s": round(wall, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
