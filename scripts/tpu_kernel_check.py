"""Standalone real-TPU check for the pallas decode kernel vs gather.

Run directly on the tunneled chip (ambient JAX_PLATFORMS=axon):
    python scripts/tpu_kernel_check.py
"""

import time

import numpy as np
import jax
import jax.numpy as jnp

from production_stack_tpu.ops.attention import gather_paged_attention
from production_stack_tpu.ops.paged_attention_pallas import pallas_paged_attention


def main():
    print("backend:", jax.default_backend(), jax.devices())
    B, H, KH, hd = 8, 16, 8, 128
    nb, bs, W = 512, 32, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((KH, nb, bs, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((KH, nb, bs, hd)), jnp.bfloat16)
    tables = jnp.asarray(
        rng.permutation(nb)[: B * W].reshape(B, W).astype(np.int32)
    )
    kv_lens = jnp.asarray(
        rng.integers(1, bs * W, size=B).astype(np.int32)
    )
    q_pos = (kv_lens - 1)[:, None]
    scale = 1.0 / np.sqrt(hd)

    ref_fn = jax.jit(lambda *a: gather_paged_attention(*a, scale=scale))
    pal_fn = jax.jit(lambda *a: pallas_paged_attention(*a, scale=scale))

    ref = np.asarray(ref_fn(q, k, v, tables, kv_lens, q_pos), np.float32)
    print("gather ok")
    got = np.asarray(pal_fn(q, k, v, tables, kv_lens, q_pos), np.float32)
    print("pallas ok; max abs diff:", np.abs(ref - got).max())

    for name, fn in [("gather", ref_fn), ("pallas", pal_fn)]:
        fn(q, k, v, tables, kv_lens, q_pos)[0].block_until_ready()
        t0 = time.perf_counter()
        for _ in range(20):
            out = fn(q, k, v, tables, kv_lens, q_pos)
        out.block_until_ready()
        dt = (time.perf_counter() - t0) / 20
        print(f"{name}: {dt*1e3:.3f} ms/call")


if __name__ == "__main__":
    main()
