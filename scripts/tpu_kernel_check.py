"""Standalone real-TPU check for the pallas paged-attention kernels vs gather.

Covers BOTH phases (the round-2 verdict flagged that only decode was ever
checked on-chip while the prefill kernel regressed TTFT):
  - decode  (T=1):  table widths W up to the 32k-context shape
  - prefill (T>1):  chunk lengths T in {128, 1024} x short/long histories

For each shape: correctness vs the gather oracle (skipped for the biggest
shapes, where gather would materialize the whole window), then wall time per
call. Run directly on the tunneled chip (ambient JAX_PLATFORMS=axon):
    python scripts/tpu_kernel_check.py
"""

import time

import numpy as np
import jax
import jax.numpy as jnp

from production_stack_tpu.ops.attention import gather_paged_attention
from production_stack_tpu.ops.paged_attention_pallas import pallas_paged_attention


def bench_fn(fn, args, iters=20):
    fn(*args).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    return (time.perf_counter() - t0) / iters


def run_case(B, T, H, KH, hd, nb, bs, W, kv_fill, rng, check=True,
             run_gather=True):
    """kv_fill: fraction of the table width actually holding live KV."""
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), jnp.bfloat16)
    kv = jnp.asarray(
        rng.standard_normal((1, nb, 2, bs, KH * hd)), jnp.bfloat16
    )
    tables = jnp.asarray(
        (rng.permutation(nb - 1)[: B * W] + 1).reshape(B, W).astype(np.int32)
    )
    live = max(int(bs * W * kv_fill), T + 1)
    kv_lens = jnp.asarray(np.full(B, live, np.int32))
    # queries are the chunk that ends at kv_len (runner contract)
    starts = live - T
    q_pos = starts + np.tile(np.arange(T, dtype=np.int32), (B, 1))
    q_pos = jnp.asarray(q_pos)
    scale = 1.0 / np.sqrt(hd)

    ref_fn = jax.jit(lambda *a: gather_paged_attention(*a, scale=scale))
    pal_fn = jax.jit(lambda *a: pallas_paged_attention(*a, scale=scale))
    args = (q, kv, tables, kv_lens, q_pos)

    # Ideal-bandwidth reference: bytes of live KV the kernel must stream.
    live_bytes = B * live * 2 * KH * hd * kv.dtype.itemsize
    if T > 1:  # causal triangle (tiles skip pages above their horizon)
        past = starts
        tri = B * T * KH * hd * kv.dtype.itemsize * 2 * (T + 1) // 2
        live_bytes = B * past * 2 * KH * hd * kv.dtype.itemsize + tri

    tag = f"B={B} T={T:4d} W={W:4d} live={live:6d}"
    if check and run_gather:
        ref = np.asarray(ref_fn(*args), np.float32)
        got = np.asarray(pal_fn(*args), np.float32)
        err = np.abs(ref - got).max()
        assert err < 2e-2, f"{tag}: max abs diff {err}"
    t_pal = bench_fn(pal_fn, args)
    gb_s = live_bytes / t_pal / 1e9
    if run_gather:
        t_ref = bench_fn(ref_fn, args)
        print(
            f"{tag}  gather {t_ref*1e3:7.3f} ms  pallas {t_pal*1e3:7.3f} ms  "
            f"speedup {t_ref/t_pal:5.2f}x  ({gb_s:5.0f} GB/s live-KV)"
        )
    else:
        print(f"{tag}  pallas {t_pal*1e3:7.3f} ms  ({gb_s:5.0f} GB/s live-KV)")


def main():
    print("backend:", jax.default_backend(), jax.devices())
    H, KH, hd, bs = 16, 8, 128, 32  # llama-1b shapes
    rng = np.random.default_rng(0)

    print("\n-- decode (T=1) --")
    for W, fill in [(32, 1.0), (64, 0.45), (128, 1.0), (640, 1.0), (1024, 0.65)]:
        nb = max(8 * W + 2, 512)
        run_case(8, 1, H, KH, hd, nb, bs, W, fill, rng)

    print("\n-- prefill (T>1) --")
    for B, T, W, fill in [
        (1, 128, 32, 1.0),     # short warm chunk, short history
        (1, 128, 640, 1.0),    # short warm chunk, 20k history (the protocol)
        (2, 128, 640, 1.0),    # batched warm chunks
        (1, 1024, 64, 1.0),    # cold prefill, mid context
        (1, 1024, 640, 1.0),   # cold prefill chunk late in a 20k prompt
        (1, 1024, 1024, 0.65), # 32k table bucket, 20k live
    ]:
        nb = max(B * W + 2, 512)
        run_case(B, T, H, KH, hd, nb, bs, W, fill, rng)

    print("\n-- block_size=128 (bench config) --")
    for B, T, W, fill in [(8, 1, 160, 1.0), (8, 1, 256, 0.65), (1, 128, 160, 1.0),
                          (1, 1024, 160, 1.0)]:
        nb = max(B * W + 2, 256)
        run_case(B, T, H, KH, hd, nb, 128, W, fill, rng, run_gather=(W <= 160))


if __name__ == "__main__":
    main()
